"""Capability-completion tier tests: paddle.flops (hapi/dynamic_flops),
incubate LookAhead/ModelAverage (incubate/optimizer/), ASP n:m sparsity
(incubate/asp/), auto-checkpoint resume
(fluid/incubate/checkpoint/auto_checkpoint.py), and the onnx gate.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import incubate


def _mlp(d=8, h=16, out=2):
    return nn.Sequential(nn.Linear(d, h), nn.ReLU(), nn.Linear(h, out))


# -- flops ---------------------------------------------------------------
def test_flops_linear_matches_analytic():
    paddle.seed(0)
    net = nn.Linear(8, 16)
    total = paddle.flops(net, [4, 8])
    # 2 * batch * in * out FLOPs (+bias adds); XLA counts at least the mults
    assert total >= 4 * 8 * 16
    assert total <= 3 * 4 * 8 * 16


def test_flops_prints_detail(capsys):
    paddle.seed(0)
    net = _mlp()
    total = paddle.flops(net, [2, 8], print_detail=True)
    out = capsys.readouterr().out
    assert "Linear" in out and "Total FLOPs" in out
    assert total > 0


# -- LookAhead -----------------------------------------------------------
def test_lookahead_syncs_every_k_steps():
    paddle.seed(0)
    net = _mlp()
    inner = paddle.optimizer.SGD(learning_rate=0.1,
                                 parameters=net.parameters())
    opt = incubate.LookAhead(inner, alpha=0.5, k=2)
    x = paddle.to_tensor(np.random.RandomState(0).randn(8, 8)
                         .astype(np.float32))
    y = paddle.to_tensor(np.random.RandomState(1).randint(0, 2, (8,)))

    # reference: plain SGD for one step gives identical fast weights
    # (sync happens at step k)
    losses = []
    for _ in range(6):
        loss = F.cross_entropy(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    # slow weights exist and differ from a pure-SGD trajectory
    assert "slow_param" in opt._accumulators


def test_lookahead_k1_tracks_inner_exactly():
    """k=1, alpha=1: slow==fast every step => identical to the inner."""
    def run(wrap):
        paddle.seed(3)
        net = _mlp()
        inner = paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=net.parameters())
        opt = incubate.LookAhead(inner, alpha=1.0, k=1) if wrap else inner
        rng = np.random.RandomState(5)
        losses = []
        for _ in range(4):
            x = paddle.to_tensor(rng.randn(8, 8).astype(np.float32))
            y = paddle.to_tensor(rng.randint(0, 2, (8,)))
            loss = F.cross_entropy(net(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        return losses

    np.testing.assert_allclose(run(True), run(False), rtol=1e-5)


def test_lookahead_composes_with_trainstep():
    from paddle_tpu.jit import TrainStep

    paddle.seed(0)
    net = _mlp()
    inner = paddle.optimizer.Adam(learning_rate=0.05,
                                  parameters=net.parameters())
    opt = incubate.LookAhead(inner, alpha=0.5, k=3)
    step = TrainStep(net, opt, F.cross_entropy)
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(6):
        x = paddle.to_tensor(rng.randn(16, 8).astype(np.float32))
        y = paddle.to_tensor((rng.randn(16) > 0).astype(np.int64))
        losses.append(float(step(x, label=y)))
    assert losses[-1] < losses[0]


# -- ModelAverage --------------------------------------------------------
def test_model_average_apply_restore():
    paddle.seed(0)
    net = _mlp()
    opt = paddle.optimizer.SGD(learning_rate=0.2,
                               parameters=net.parameters())
    ma = incubate.ModelAverage(0.15, parameters=net.parameters(),
                               min_average_window=2,
                               max_average_window=10)
    snapshots = []
    rng = np.random.RandomState(0)
    for _ in range(4):
        x = paddle.to_tensor(rng.randn(8, 8).astype(np.float32))
        y = paddle.to_tensor(rng.randint(0, 2, (8,)))
        F.cross_entropy(net(x), y).backward()
        opt.step()
        opt.clear_grad()
        ma.step()
        snapshots.append(np.asarray(net[0].weight._array).copy())

    live = np.asarray(net[0].weight._array).copy()
    with ma.apply():
        avg = np.asarray(net[0].weight._array)
        np.testing.assert_allclose(avg, np.mean(snapshots, axis=0),
                                   atol=1e-5)
    np.testing.assert_allclose(np.asarray(net[0].weight._array), live)


# -- ASP -----------------------------------------------------------------
def test_asp_prune_and_guarantee():
    from paddle_tpu.incubate import asp

    asp.reset_excluded_layers()
    paddle.seed(0)
    net = _mlp(d=8, h=16)
    masks = asp.prune_model(net, n=2, m=4)
    assert masks  # Linear layers pruned
    w0 = np.asarray(net[0].weight._array)
    assert asp.check_mask_1d(w0, 2, 4)
    assert abs(asp.calculate_density(w0) - 0.5) < 1e-6

    opt = asp.decorate(paddle.optimizer.SGD(
        learning_rate=0.1, parameters=net.parameters()))
    rng = np.random.RandomState(0)
    for _ in range(3):
        x = paddle.to_tensor(rng.randn(8, 8).astype(np.float32))
        y = paddle.to_tensor(rng.randint(0, 2, (8,)))
        F.cross_entropy(net(x), y).backward()
        opt.step()
        opt.clear_grad()
    # sparsity preserved through training
    assert asp.check_mask_1d(np.asarray(net[0].weight._array), 2, 4)


def test_asp_excluded_layers():
    from paddle_tpu.incubate import asp

    paddle.seed(0)
    net = _mlp()
    asp.set_excluded_layers(net, ["0"])
    masks = asp.prune_model(net, n=2, m=4)
    assert "0.weight" not in masks  # excluded layer untouched
    assert "2.weight" in masks      # the other Linear IS pruned
    # exclusions are scoped to the model they were set on
    paddle.seed(0)
    other = _mlp()
    masks2 = asp.prune_model(other, n=2, m=4)
    assert "0.weight" in masks2
    asp.reset_excluded_layers(net)
    assert not net._asp_excluded


# -- auto-checkpoint -----------------------------------------------------
def test_auto_checkpoint_resumes(tmp_path):
    from paddle_tpu.incubate import checkpoint as acp

    save_dir = str(tmp_path / "acp")

    def train(epochs_to_crash=None):
        paddle.seed(0)
        net = _mlp()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        done = []
        rng = np.random.RandomState(0)
        for epoch in acp.train_epoch_range(
                4, save_dir=save_dir, state={"model": net, "opt": opt}):
            x = paddle.to_tensor(rng.randn(8, 8).astype(np.float32))
            y = paddle.to_tensor(rng.randint(0, 2, (8,)))
            F.cross_entropy(net(x), y).backward()
            opt.step()
            opt.clear_grad()
            done.append(epoch)
            if epochs_to_crash is not None and \
                    len(done) >= epochs_to_crash:
                break  # simulated crash
        return done, net

    first, _ = train(epochs_to_crash=2)
    assert first == [0, 1]
    resumed, net = train()
    # epoch 0 completed+recorded; the "crash" hit before epoch 1's
    # completion was recorded, so it re-runs — resume is conservative
    assert resumed == [1, 2, 3]
    assert os.path.exists(os.path.join(save_dir, "acp_model.pd"))


# -- onnx gate -----------------------------------------------------------
def test_onnx_export_gated():
    pytest.importorskip  # noqa — only run the gate branch when absent
    try:
        import onnx  # noqa: F401
        pytest.skip("onnx installed; gate branch not reachable")
    except ImportError:
        pass
    with pytest.raises(ImportError, match="StableHLO"):
        paddle.onnx.export(_mlp(), "/tmp/x")


# -- timeline merge tool -------------------------------------------------
def test_merge_timelines_tool(tmp_path):
    import json
    import subprocess
    import sys

    import paddle_tpu

    for r in range(2):
        prof = paddle_tpu.profiler.Profiler()
        prof.start()
        with paddle_tpu.profiler.RecordEvent(f"work_r{r}"):
            paddle_tpu.to_tensor(np.ones(4, np.float32)).sum()
        prof.stop()
        prof.export(str(tmp_path / f"rank{r}.json"))

    out = str(tmp_path / "merged.json")
    tool = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "merge_timelines.py")
    res = subprocess.run(
        [sys.executable, tool, "-o", out,
         str(tmp_path / "rank0.json"), str(tmp_path / "rank1.json")],
        capture_output=True, text=True)
    assert res.returncode == 0, res.stderr
    doc = json.load(open(out))
    evs = doc["traceEvents"]
    pids = {e["pid"] for e in evs if "pid" in e}
    # the two ranks keep disjoint pid namespaces
    assert any(p >= 200000 for p in pids) and any(
        100000 <= p < 200000 for p in pids)
    names = {e.get("args", {}).get("name") for e in evs
             if e.get("ph") == "M"}
    assert any(n and n.startswith("rank0") for n in names)
    assert any("work_r1" == e.get("name") for e in evs)
