"""Op-schema tests (SURVEY §2 item 6): ops.yaml is authoritative and
may not drift from the code — every declared op exists with the declared
signature and Tensor-method status, the AMP lists come from the schema,
and every public op is declared.
"""
import inspect

import pytest

import paddle_tpu  # noqa: F401
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops import (activation, creation, linalg, manipulation,
                            math, nn_ops, random_ops, reduction, registry)

MODULES = {
    "math": math, "creation": creation, "manipulation": manipulation,
    "reduction": reduction, "linalg": linalg, "activation": activation,
    "random_ops": random_ops, "nn_ops": nn_ops,
}


def test_every_declared_op_exists_and_matches():
    assert len(registry.all_ops()) > 250
    for e in registry.all_ops():
        mod = MODULES[e["module"]]
        fn = getattr(mod, e["op"], None)
        assert callable(fn), f"{e['module']}.{e['op']} missing"
        if e["signature"] != "(...)":
            assert str(inspect.signature(fn)) == e["signature"], \
                f"signature drift for {e['op']}"
        assert callable(getattr(Tensor, e["op"], None)) == \
            e["tensor_method"], f"tensor_method drift for {e['op']}"


def test_every_public_op_is_declared():
    declared = {e["op"] for e in registry.all_ops()}
    for mod_name, mod in MODULES.items():
        for name in getattr(mod, "__all__", []):
            assert name in declared, \
                (f"{mod_name}.{name} is public but absent from ops.yaml —"
                 " run tools/gen_ops_yaml.py")


def test_amp_lists_come_from_schema():
    from paddle_tpu.amp.auto_cast import BLACK_LIST, WHITE_LIST

    assert WHITE_LIST == set(registry.amp_white())
    assert BLACK_LIST == set(registry.amp_black())
    # spot checks: the policy itself
    assert {"matmul", "conv2d", "resnet_stem_s2d"} <= WHITE_LIST
    assert {"softmax", "batch_norm", "cross_entropy"} <= BLACK_LIST
    assert not (WHITE_LIST & BLACK_LIST)


def test_registry_lookup_and_search():
    e = registry.get("conv2d")
    assert e["module"] == "nn_ops" and e["amp"] == "white"
    hits = {x["op"] for x in registry.search("conv")}
    assert {"conv2d", "conv1d", "conv3d", "conv2d_transpose"} <= hits
    assert registry.get("no_such_op") is None
