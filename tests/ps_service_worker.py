"""Worker for the PS service-tier tests: launched via
`python -m paddle_tpu.distributed.launch --nprocs T --servers S
ps_service_worker.py <mode> <out_file>`.

Server processes serve tables (run_server); trainer processes train
wide&deep against TableClient handles with the given Communicator mode
and write their final losses to <out_file>.<trainer_id>.
"""
import json
import sys

import numpy as np


def _graph_mode(service, tid, out_file):
    """GraphTableClient e2e: each trainer loads a disjoint slice of a
    shared graph into the 2 servers, waits until the WHOLE graph is
    visible, then both sample neighbors + read features written by the
    OTHER trainer."""
    import time

    from paddle_tpu.distributed.ps.service import GraphTableClient

    g = GraphTableClient("social")
    # trainer t owns sources {10+t, 20+t}: edges to a shared hub 99
    base = 10 + tid
    g.add_edges([base, base, 20 + tid], [99, base + 100, 99],
                weights=[5.0, 1.0, 1.0])
    g.set_node_feat([base], "h", np.array([[float(tid), 1.0]]))
    # whole graph = {10,11,20,21,99,110,111}: wait until the other
    # trainer's slice AND its feature write landed on the servers (the
    # node count alone races the in-flight set_node_feat rpc)
    deadline = time.time() + 60
    while time.time() < deadline:
        if (g.stats()["nodes"] >= 7
                and g.get_node_feat([10 + (1 - tid)], "h")[0, 1] == 1.0):
            break
        time.sleep(0.1)
    st = g.stats()
    nbrs = g.random_sample_neighbors([10 + (1 - tid)], 64, seed=tid)
    other_feat = g.get_node_feat([10 + (1 - tid)], "h")
    result = {
        "stats": st,
        "other_neighbors": sorted(set(map(int, nbrs.ravel()))),
        "graph_window": [int(i) for i in g.pull_graph_list(1, 3)],
        "other_feat": other_feat.tolist(),
    }
    if out_file:
        with open(f"{out_file}.{tid}", "w") as f:
            json.dump(result, f)
    print(f"TRAINER_DONE graph nodes={st['nodes']}", flush=True)
    service.stop_servers()


def main():
    mode = sys.argv[1]
    out_file = sys.argv[2] if len(sys.argv) > 2 else None

    from paddle_tpu.distributed.ps import service

    if service.is_server():
        service.run_server()
        print("SERVER_DONE", flush=True)
        return

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed.ps import (Communicator, SparseAdagradRule,
                                           TableClient)
    from paddle_tpu.models import DeepFM, WideDeep

    service.init_ps_rpc()
    tid = service.trainer_index()

    if mode == "graph":
        _graph_mode(service, tid, out_file)
        return

    # mode "ssd" = sync communicator + disk-spill tier on the servers;
    # mode "deepfm" = sync communicator, DeepFM model (BASELINE row 5)
    comm_mode = "sync" if mode in ("ssd", "deepfm") else mode
    ssd_rows = 64 if mode == "ssd" else None
    comm = Communicator(mode=comm_mode, k_steps=3)
    deep_client = TableClient("deep_table", 8,
                              rule=SparseAdagradRule(0.05), seed=0,
                              communicator=comm,
                              ssd_max_mem_rows=ssd_rows)
    wide_comm = Communicator(mode=comm_mode, k_steps=3)
    wide_client = TableClient("wide_table", 1,
                              rule=SparseAdagradRule(0.05), seed=1,
                              communicator=wide_comm)

    paddle.seed(0)
    model_cls = DeepFM if mode == "deepfm" else WideDeep
    model = model_cls(4, embedding_dim=8, hidden=(32,),
                      deep_table=deep_client, wide_table=wide_client)
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=model.parameters())

    # disjoint id slices per trainer so async staleness can't flip
    # convergence; click iff field-0 id is even (same task as
    # tests/test_ps.py::test_wide_deep_trains)
    rs = np.random.RandomState(100 + tid)
    ids_np = (rs.randint(0, 500, size=(128, 4)) * 2 +
              tid).astype(np.int64)
    y_np = (ids_np[:, :1] % 2 == 0).astype(np.float32)

    losses = []
    for epoch in range(30):
        p = model(paddle.to_tensor(ids_np))
        loss = F.binary_cross_entropy(p, paddle.to_tensor(y_np))
        loss.backward()
        opt.step()
        opt.clear_grad()
        model.push_sparse()
        losses.append(float(loss))
    comm.flush()
    wide_comm.flush()
    comm.stop()
    wide_comm.stop()

    touched = deep_client.touched()
    stats = deep_client.stats()
    sd = deep_client.state_dict()
    if out_file:
        with open(f"{out_file}.{tid}", "w") as f:
            json.dump({"losses": losses, "touched": touched,
                       "stats": stats, "state_rows": len(sd)}, f)
    print(f"TRAINER_DONE loss0={losses[0]:.4f} "
          f"lossN={losses[-1]:.4f} touched={touched}", flush=True)
    service.stop_servers()


if __name__ == "__main__":
    main()
