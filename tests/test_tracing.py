"""Request-scoped tracing + host-gap timeline (ISSUE 17).

The contracts, proven the way PRs 12/13/15 proved theirs:

- OFF IS FREE: a tracing-disabled engine carries no tracer, registers
  no trace series, and emits token streams identical to a
  tracing-ENABLED engine (tracing is host-side only — by construction
  it can never become a compiled-program argument), and
  `decode_traces == 1` holds per (backend, K) with tracing ON.
- PHASES PARTITION THE STEP: `PhaseTimer` is exclusive — nesting
  pauses the enclosing phase, so per-phase totals sum to (at most)
  wall time and `engine_step_device_fraction` is a real fraction. The
  `engine_step_host_gap_seconds{phase}` histogram is ALWAYS on (the
  ROADMAP item 3 measured baseline), tracing knob or not.
- RINGS ARE BOUNDED: TraceRecorder and FlightRecorder hold the newest
  `capacity` events, count their drops, and never grow; `drain()`'s
  leak audit arrives WITH the flight-recorder history.
- ONE TIMELINE: engine spans merge with the profiler's
  `_HostEventRecorder` stream (same monotonic clock); a disaggregated
  2-replica request exports a single Perfetto file whose routing,
  prefill, handoff, and decode spans share ONE trace id across
  per-process track groups.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import GenerationEngine, ServingFleet
from paddle_tpu.observability.metrics import (label_snapshot,
                                              merge_snapshots,
                                              series_total)
from paddle_tpu.observability.tracing import (STEP_PHASES,
                                              FlightRecorder,
                                              PhaseTimer,
                                              TraceRecorder,
                                              merge_trace_events,
                                              new_trace_id)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
VOCAB = 64


def _model(seed=0):
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(seed)
    cfg = GPTConfig.tiny(vocab=VOCAB, hidden=32, layers=2, heads=2,
                         seq=64)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return _model()


def _trace(rng_seed=0, n=4):
    rng = np.random.RandomState(rng_seed)
    return [(rng.randint(0, VOCAB, rng.randint(4, 14))
             .astype(np.int32), int(rng.randint(3, 7)))
            for _ in range(n)]


def _serve(eng, reqs):
    ids = [eng.add_request(p, mn, req_id=i)
           for i, (p, mn) in enumerate(reqs)]
    out = eng.run()
    return [list(map(int, out[i])) for i in ids]


# ---------------------------------------------------------------------------
# tracing.py primitives
# ---------------------------------------------------------------------------

def test_phase_timer_exclusive_accounting():
    """Nested phases PAUSE the enclosing one: totals are disjoint and
    sum to (at most) the wall time of the outermost section."""
    pt = PhaseTimer()
    t0 = time.perf_counter()
    with pt.phase("outer"):
        time.sleep(0.01)
        with pt.phase("inner"):
            time.sleep(0.02)
        time.sleep(0.01)
    wall = time.perf_counter() - t0
    tot = pt.totals()
    assert set(tot) == {"outer", "inner"}
    assert tot["inner"] >= 0.02
    # exclusive: outer excludes inner's slice entirely
    assert tot["outer"] < wall - tot["inner"] + 0.005
    assert tot["outer"] + tot["inner"] <= wall + 0.005
    # reset returns and clears
    assert pt.reset() == tot
    assert pt.totals() == {}


def test_phase_timer_reentrant_same_name():
    pt = PhaseTimer()
    for _ in range(3):
        with pt.phase("a"):
            time.sleep(0.002)
    assert pt.totals()["a"] >= 0.006


def test_phase_timer_thread_confined_clocks():
    """ISSUE 18 regression: the async core's drafter helper runs its
    `draft_propose` phases on ANOTHER thread while the step thread
    sits in its own phase. Each thread owns its whole clock — stack
    AND accumulator — so an off-thread phase must neither pause the
    step thread's active phase nor leak seconds into its totals (the
    step thread's phase totals must keep partitioning ITS wall
    time)."""
    import threading

    pt = PhaseTimer()
    helper_done = threading.Event()
    helper_tot = {}

    def helper():
        with pt.phase("draft_propose"):
            time.sleep(0.03)
        helper_tot.update(pt.totals())
        helper_done.set()

    t0 = time.perf_counter()
    with pt.phase("dispatch"):
        th = threading.Thread(target=helper)
        th.start()
        helper_done.wait()
        th.join()
    wall = time.perf_counter() - t0
    # step thread: ONLY its own phase, covering its full wall — the
    # helper's concurrent phase neither paused nor shortened it
    tot = pt.totals()
    assert set(tot) == {"dispatch"}
    assert tot["dispatch"] >= 0.03
    assert tot["dispatch"] <= wall + 0.005
    # helper thread: its seconds landed on ITS clock only
    assert set(helper_tot) == {"draft_propose"}
    assert helper_tot["draft_propose"] >= 0.03
    # reset is per-thread too: clearing the step thread's clock is
    # what `_flush_step_phases` does between steps — the helper's
    # clock was never part of the step partition
    assert pt.reset() == tot
    assert pt.totals() == {}


def test_trace_recorder_ring_bound_and_drops():
    tr = TraceRecorder(capacity=4)
    for i in range(10):
        tr.add_span(f"s{i}", i, i + 1)
    snap = tr.snapshot()
    assert len(snap) == 4
    assert [e["name"] for e in snap] == ["s6", "s7", "s8", "s9"]
    assert tr.total_recorded == 10 and tr.dropped == 6
    # snapshot is non-destructive
    assert len(tr.snapshot()) == 4
    tr.clear()
    assert tr.snapshot() == [] and tr.dropped == 0
    with pytest.raises(ValueError):
        TraceRecorder(capacity=0)


def test_trace_recorder_span_ids_and_context():
    tr = TraceRecorder()
    tid = new_trace_id()
    parent = tr.add_span("root", 0, 5, trace_id=tid)
    child = tr.add_span("leaf", 1, 2, trace_id=tid, parent_id=parent)
    assert child != parent
    ev = tr.snapshot()[1]
    assert ev["args"]["trace_id"] == tid
    assert ev["args"]["parent_id"] == parent
    assert ev["ph"] == "X" and ev["dur"] == 1
    with tr.span("ctx", trace_id=tid):
        pass
    assert tr.snapshot()[-1]["name"] == "ctx"


def test_new_trace_ids_are_unique_and_pid_prefixed():
    ids = {new_trace_id() for _ in range(100)}
    assert len(ids) == 100
    assert all(i.startswith(f"{os.getpid():x}-") for i in ids)


def test_flight_recorder_bound_and_format():
    fl = FlightRecorder(capacity=3)
    for i in range(5):
        fl.record("ev", req_id=i, k=i * 10)
    rows = fl.dump()
    assert len(rows) == 3 and [r["req_id"] for r in rows] == [2, 3, 4]
    assert fl.total_recorded == 5
    txt = fl.format()
    assert "flight recorder (3 of 5 events" in txt
    assert "k=40" in txt and "req=4" in txt
    assert len(fl.format(limit=1).splitlines()) == 2


def test_merge_trace_events_repids_and_names():
    merged = merge_trace_events([
        ("alpha", [{"name": "a", "ph": "X", "ts": 0, "dur": 1,
                    "pid": 999, "tid": 0}]),
        ("beta", [{"name": "b", "ph": "X", "ts": 0, "dur": 1,
                   "pid": 999, "tid": 0}]),
    ])
    metas = [e for e in merged if e["ph"] == "M"]
    assert [(m["pid"], m["args"]["name"]) for m in metas] == \
        [(1, "alpha"), (2, "beta")]
    spans = [e for e in merged if e["ph"] == "X"]
    assert [(s["name"], s["pid"]) for s in spans] == \
        [("a", 1), ("b", 2)]


# ---------------------------------------------------------------------------
# tentpole: engine lifecycle + phases
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("K", [0, 4])
def test_tracing_off_is_token_identical_and_traces_hold(model,
                                                        monkeypatch,
                                                        K):
    """THE acceptance gate: tracing never changes tokens (host-side
    only, the sampling=False precedent) and `decode_traces == 1`
    holds with tracing ON — the spans ride outside the compiled
    programs."""
    monkeypatch.delenv("PADDLE_SERVE_TRACING", raising=False)
    reqs = _trace(3)

    def mk(on):
        return GenerationEngine(model, num_slots=2, block_size=8,
                                spec_decode_k=K, tracing=on)

    eng_off = mk(False)
    out_off = _serve(eng_off, reqs)
    eng_on = mk(True)
    out_on = _serve(eng_on, reqs)
    assert out_on == out_off
    assert eng_off.tracer is None and eng_on.tracer is not None
    assert eng_on.decode_traces == 1
    # conditional registration: the trace series exist only when on
    snap_on = eng_on.metrics_snapshot()
    snap_off = eng_off.metrics_snapshot()
    assert "engine_trace_spans_total" in snap_on
    assert "engine_trace_spans_total" not in snap_off
    assert "engine_trace_dropped_total" not in snap_off
    assert series_total(snap_on, "engine_trace_spans_total") \
        == eng_on.tracer.total_recorded


def test_tracing_env_knob_wins(model, monkeypatch):
    monkeypatch.setenv("PADDLE_SERVE_TRACING", "1")
    assert GenerationEngine(model, num_slots=2,
                            block_size=8).tracer is not None
    monkeypatch.setenv("PADDLE_SERVE_TRACING", "0")
    assert GenerationEngine(model, num_slots=2, block_size=8,
                            tracing=True).tracer is None


@pytest.mark.parametrize("K", [0, 4])
def test_host_gap_histogram_and_device_fraction(model, monkeypatch,
                                                K):
    """The measured baseline for ROADMAP item 3: every step folds its
    phase clock into `engine_step_host_gap_seconds{phase}` — tracing
    knob OFF (the histogram is always on) — and the device fraction
    is a real fraction."""
    monkeypatch.delenv("PADDLE_SERVE_TRACING", raising=False)
    eng = GenerationEngine(model, num_slots=2, block_size=8,
                           spec_decode_k=K)
    assert eng.tracer is None
    _serve(eng, _trace(1))
    snap = eng.metrics_snapshot()
    hg = snap["engine_step_host_gap_seconds"]
    phases = {s["labels"]["phase"] for s in hg["series"]}
    assert phases <= set(STEP_PHASES)
    expect = {"schedule", "dispatch", "device_wait", "finish"}
    if K:
        expect |= {"draft_propose", "accept_walk"}
    assert expect <= phases
    for s in hg["series"]:
        assert s["count"] > 0 and s["sum"] >= 0
    frac = snap["engine_step_device_fraction"]["series"][0]["value"]
    assert 0.0 <= frac <= 1.0


def test_request_lifecycle_spans_share_one_trace_id(model,
                                                    monkeypatch):
    monkeypatch.delenv("PADDLE_SERVE_TRACING", raising=False)
    eng = GenerationEngine(model, num_slots=2, block_size=8,
                           tracing=True)
    reqs = _trace(5, n=3)
    _serve(eng, reqs)
    events = eng.tracer.snapshot()
    by_req = {}
    for e in events:
        a = e.get("args") or {}
        if "req_id" in a and "trace_id" in a:
            by_req.setdefault(a["req_id"], set()).add(a["trace_id"])
    assert set(by_req) == {"0", "1", "2"}
    # one trace id per request, all distinct
    assert all(len(tids) == 1 for tids in by_req.values())
    assert len({t for tids in by_req.values() for t in tids}) == 3
    names = {e["name"] for e in events}
    assert {"request.queued", "request.admitted",
            "request.first_token", "request.finish",
            "prefill.chunk", "decode.step"} <= names
    # phase spans ride a separate category
    assert any(e.get("cat") == "phase" for e in events)


def test_flight_recorder_lifecycle_and_shed(model, monkeypatch):
    monkeypatch.delenv("PADDLE_SERVE_TRACING", raising=False)
    eng = GenerationEngine(model, num_slots=1, block_size=8,
                           max_queue=1)
    reqs = _trace(7, n=3)
    for i, (p, mn) in enumerate(reqs):
        eng.add_request(p, mn, req_id=i)
    eng.run()
    events = [e["event"] for e in eng.dump_flight_recorder()]
    assert "queued" in events and "admitted" in events
    assert "first_token" in events and "finish" in events
    assert "shed" in events      # max_queue=1 shed the overflow


def test_drain_leak_audit_attaches_flight_recorder(model,
                                                   monkeypatch):
    """The postmortem contract: a failed leak audit arrives WITH the
    recent request history, not as a bare assertion."""
    monkeypatch.delenv("PADDLE_SERVE_TRACING", raising=False)
    eng = GenerationEngine(model, num_slots=2, block_size=8)
    _serve(eng, _trace(2, n=2))
    eng.cache.allocate(1)              # drop a block on the floor
    with pytest.raises(RuntimeError) as ei:
        eng.drain()
    msg = str(ei.value)
    assert "leak check failed" in msg
    assert "flight recorder" in msg
    assert "finish" in msg             # the history rode along


def test_export_trace_merges_profiler_stream(model, monkeypatch,
                                             tmp_path):
    """One timeline: the engine's span ring and the profiler's
    RecordEvent stream land in one Chrome-trace file as separate
    re-pidded track groups (same monotonic clock, no offsets)."""
    from paddle_tpu.profiler.profiler import _recorder

    monkeypatch.delenv("PADDLE_SERVE_TRACING", raising=False)
    eng = GenerationEngine(model, num_slots=2, block_size=8,
                           tracing=True)
    monkeypatch.setattr(_recorder, "enabled", True)
    try:
        _serve(eng, _trace(4, n=2))
    finally:
        _recorder.enabled = False
    path = tmp_path / "timeline.json"
    n = eng.export_trace(str(path))
    _recorder.drain()                  # leave no residue for others
    doc = json.loads(path.read_text())
    evs = doc["traceEvents"]
    assert len(evs) == n
    tracks = {e["args"]["name"] for e in evs if e["ph"] == "M"}
    assert tracks == {"engine", "profiler"}
    prof_pid = next(e["pid"] for e in evs if e["ph"] == "M"
                    and e["args"]["name"] == "profiler")
    prof_names = {e["name"] for e in evs
                  if e.get("pid") == prof_pid and e["ph"] == "X"}
    assert "engine.step" in prof_names
    # off engines refuse loudly instead of writing an empty file
    with pytest.raises(RuntimeError, match="tracing is off"):
        GenerationEngine(model, num_slots=2, block_size=8) \
            .export_trace(str(tmp_path / "nope.json"))


def test_trace_ring_bound_holds_under_load(model, monkeypatch):
    monkeypatch.delenv("PADDLE_SERVE_TRACING", raising=False)
    eng = GenerationEngine(model, num_slots=2, block_size=8,
                           tracing=True, trace_capacity=16)
    _serve(eng, _trace(6, n=4))
    assert len(eng.tracer.snapshot()) == 16
    assert eng.tracer.dropped > 0
    snap = eng.metrics_snapshot()
    assert series_total(snap, "engine_trace_dropped_total") \
        == eng.tracer.dropped


# ---------------------------------------------------------------------------
# fleet: trace context across replicas
# ---------------------------------------------------------------------------

def test_disaggregated_handoff_exports_single_timeline(model,
                                                       monkeypatch,
                                                       tmp_path):
    """THE cross-replica gate: a disaggregated request's routing,
    prefill, handoff export/ingest, and decode spans share ONE trace
    id across the router's and both replicas' track groups — one
    Perfetto file shows the request crossing engines."""
    monkeypatch.delenv("PADDLE_SERVE_TRACING", raising=False)
    fleet = ServingFleet(model, num_replicas=1,
                         num_prefill_replicas=1, num_slots=2,
                         block_size=8, tracing=True)
    rng = np.random.RandomState(0)
    rid = fleet.add_request(rng.randint(0, VOCAB, 10)
                            .astype(np.int32), 8)
    out = fleet.run()
    assert len(out[rid]) == 18
    path = tmp_path / "fleet.json"
    fleet.export_trace(str(path))
    evs = json.loads(path.read_text())["traceEvents"]
    tracks = {e["args"]["name"] for e in evs if e["ph"] == "M"}
    assert {"fleet.router", "replica 0 (decode)",
            "replica 1 (prefill)"} <= tracks
    tids = {e["args"]["trace_id"] for e in evs
            if e.get("args") and e["args"].get("trace_id")}
    assert len(tids) == 1              # one request -> one trace id
    tid = next(iter(tids))
    handoff = {e["name"] for e in evs if e.get("cat") == "handoff"}
    assert handoff == {"handoff.export", "handoff.ingest"}
    assert all(e["args"]["trace_id"] == tid for e in evs
               if e.get("cat") == "handoff")
    route = next(e for e in evs if e["name"] == "fleet.route")
    assert route["args"]["reason"] in ("affinity", "least_loaded")
    assert "replica" in route["args"]
    # the id crosses >= 3 track groups: router, prefill, decode
    pids = {e["pid"] for e in evs
            if e.get("args") and e["args"].get("trace_id") == tid}
    assert len(pids) >= 3


def test_fleet_route_spans_annotate_affinity(model, monkeypatch):
    monkeypatch.delenv("PADDLE_SERVE_TRACING", raising=False)
    fleet = ServingFleet(model, num_replicas=2, num_slots=2,
                         block_size=8, tracing=True)
    rng = np.random.RandomState(1)
    hot = rng.randint(0, VOCAB, 16).astype(np.int32)
    fleet.add_request(hot, 4)
    fleet.run()
    fleet.add_request(hot.copy(), 4)   # warm chain -> affinity win
    fleet.run()
    routes = [e for e in fleet.tracer.snapshot()
              if e["name"] == "fleet.route"]
    assert len(routes) == 2
    assert routes[1]["args"]["reason"] == "affinity"
    assert routes[1]["args"]["affinity_tokens"] > 0


def test_fleet_folds_host_gap_and_trace_series(model, monkeypatch):
    """PR 12's fold contract re-proven with the NEW series present:
    replica-labeled `engine_step_host_gap_seconds{phase}` buckets sum
    exactly across a 2-replica fleet, trace counters fold, and an
    unlabeled collision still raises."""
    monkeypatch.delenv("PADDLE_SERVE_TRACING", raising=False)
    fleet = ServingFleet(model, num_replicas=2, num_slots=2,
                         block_size=8, tracing=True)
    reqs = _trace(9, n=4)
    for i, (p, mn) in enumerate(reqs):
        fleet.add_request(p, mn, req_id=i)
    fleet.run()
    snaps = [rep.engine.metrics.snapshot()
             for rep in fleet._replicas.values()]
    merged = fleet.metrics_snapshot()
    hg = merged["engine_step_host_gap_seconds"]
    assert "replica" in hg["labelnames"]
    # exact fold: each replica's per-phase buckets appear verbatim
    for rid, snap in zip(fleet._replicas, snaps):
        for s in snap["engine_step_host_gap_seconds"]["series"]:
            match = [m for m in hg["series"]
                     if m["labels"] == {**s["labels"],
                                        "replica": str(rid)}]
            assert len(match) == 1
            assert match[0]["counts"] == s["counts"]
            assert match[0]["sum"] == s["sum"]
            assert match[0]["count"] == s["count"]
    # trace counters fold too, and total equals the per-replica sum
    assert series_total(merged, "engine_trace_spans_total") == sum(
        series_total(s, "engine_trace_spans_total") for s in snaps)
    # the collision contract survives the new series: re-stamping an
    # already replica-labeled snapshot raises instead of shadowing
    with pytest.raises(ValueError):
        label_snapshot(label_snapshot(snaps[0], replica="0"),
                       replica="1")
    # and merging UNLABELED replica snapshots silently sums identical
    # series — the exact-merge semantics the replica stamp exists for
    folded = merge_snapshots(snaps)
    assert series_total(folded, "engine_trace_spans_total") == \
        series_total(merged, "engine_trace_spans_total")


# ---------------------------------------------------------------------------
# satellites: profiler export collision, import smoke, bench row
# ---------------------------------------------------------------------------

def test_export_chrome_tracing_same_second_no_collision(monkeypatch,
                                                        tmp_path):
    """Regression (ISSUE 17 satellite): two exports within one
    wall-clock second used to silently overwrite — the monotonic
    sequence suffix keeps them distinct files."""
    from paddle_tpu.profiler import profiler as prof_mod

    monkeypatch.setattr(prof_mod.time, "time", lambda: 1234567890.5)
    handler = prof_mod.export_chrome_tracing(str(tmp_path),
                                             worker_name="w")
    p1 = prof_mod.Profiler(timer_only=True)
    p2 = prof_mod.Profiler(timer_only=True)
    handler(p1)
    handler(p2)
    assert p1._export_path != p2._export_path
    assert os.path.exists(p1._export_path)
    assert os.path.exists(p2._export_path)
    for p in (p1, p2):
        assert "traceEvents" in json.loads(
            open(p._export_path).read())


def test_tracing_import_has_no_backend_init():
    """Importing observability.tracing must never initialize a JAX
    backend (the paged-attention/conv smoke precedent): the fleet
    router and serving hosts import it at module import."""
    code = (
        "import paddle_tpu.observability.tracing as t\n"
        "from jax._src import xla_bridge\n"
        "assert not xla_bridge._backends, 'backend initialized'\n"
        "assert len(t.STEP_PHASES) == 10\n"
        "r = t.TraceRecorder(capacity=2)\n"
        "r.add_span('x', 0, 1)\n"
        "assert r.snapshot()[0]['name'] == 'x'\n"
        "print('SMOKE_OK')\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    res = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "SMOKE_OK" in res.stdout


def test_suite_rows_carry_host_gap_row():
    import bench_ops

    assert "gpt_engine_host_gap" in bench_ops.SUITE_ROWS


@pytest.mark.slow
def test_host_gap_bench_runner_tiny():
    """The `gpt_engine_host_gap` runner end-to-end on a tiny config:
    phases report for K in {0,4}, cold and warm, device fraction is a
    fraction, and the record carries the adoption-gate "ms" key."""
    from paddle_tpu.models import GPTConfig

    import bench_ops

    cfg = GPTConfig.tiny(vocab=VOCAB, hidden=32, layers=2, heads=2,
                         seq=64)
    rec = bench_ops._engine_host_gap_case(
        model_cfg=cfg, num_requests=3, num_slots=2, block_size=8,
        max_new=6)()
    assert "ms" in rec and rec["ms"] > 0
    for k in ("k0", "k4"):
        for window in ("cold", "warm"):
            phases = rec[k][f"phase_ms_per_step_{window}"]
            assert "dispatch" in phases and "device_wait" in phases
            frac = rec[k][f"device_fraction_{window}"]
            assert 0.0 <= frac <= 1.0
        assert rec[k]["spans"] > 0
    assert "draft_propose" in rec["k4"]["phase_ms_per_step_warm"]
