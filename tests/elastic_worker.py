"""Elastic scale-in worker: trains a counter with per-step collectives,
checkpoints every step, and SIGKILLs the last n_kill ranks at step 5 on
the first attempt. On the scaled-in relaunch (with the survivor count)
every survivor resumes from the checkpoint and finishes.

Usage (via launch --nprocs N --elastic-min M --max-restarts 1):
    elastic_worker.py <ckpt.json> <kill_sentinel> [n_kill=1]
"""
import json
import os
import signal
import sys

import numpy as np


def main():
    ckpt_path, sentinel = sys.argv[1], sys.argv[2]
    n_kill = int(sys.argv[3]) if len(sys.argv) > 3 else 1

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    dist.init_parallel_env()
    rank = dist.get_rank()
    world = dist.get_world_size()

    start = 0
    if os.path.exists(ckpt_path):
        with open(ckpt_path) as f:
            start = json.load(f)["step"]

    for step in range(start, 10):
        t = paddle.to_tensor(np.ones((1,), np.float32))
        dist.all_reduce(t)  # proves the collective at the CURRENT size
        assert float(np.asarray(t._array)[0]) == float(world)
        if rank == 0:
            tmp = ckpt_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"step": step + 1, "world": world}, f)
            os.replace(tmp, ckpt_path)
        # snapshot BEFORE the barrier: the sentinel is written after it,
        # so every doomed rank reads the same first-attempt verdict
        first_attempt = not os.path.exists(sentinel)
        dist.barrier()  # the checkpoint is visible before anyone dies
        if step == 5 and rank >= world - n_kill and first_attempt:
            if rank == world - 1:  # one sentinel write is enough
                open(sentinel, "w").close()
            print(f"KILLING self rank={rank} (simulated host loss)",
                  flush=True)
            os.kill(os.getpid(), signal.SIGKILL)

    print(f"ELASTIC_DONE rank={rank} world={world} resumed_from={start}",
          flush=True)


if __name__ == "__main__":
    main()
