"""PP-YOLOE-family functional config (BASELINE.md row 5: conv + NMS
custom-op path): the anchor-free detector trains end-to-end through
jit.TrainStep and detects synthetic boxes through multiclass_nms.
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.vision.models import (
    ppyoloe_lite,
    yolo_loss,
    yolo_postprocess,
)


def _synthetic_scene(rng, size=64, n=1):
    """Bright square on dark background; the box is its bound."""
    img = np.zeros((3, size, size), np.float32)
    boxes = np.full((2, 4), -1.0, np.float32)
    labels = np.zeros((2,), np.int64)
    for i in range(n):
        w = rng.randint(16, 28)
        x0 = rng.randint(0, size - w)
        y0 = rng.randint(0, size - w)
        img[:, y0:y0 + w, x0:x0 + w] = 1.0
        boxes[i] = [x0, y0, x0 + w, y0 + w]
    return img, boxes, labels


def test_yolo_forward_shapes():
    paddle.seed(0)
    m = ppyoloe_lite(num_classes=3, width=8)
    out = m(paddle.to_tensor(np.zeros((2, 3, 64, 64), np.float32)))
    cls, boxes, pts, strides = out
    A = 8 * 8 + 4 * 4 + 2 * 2  # strides 8/16/32 on 64px
    assert cls.shape == [2, A, 3] and boxes.shape == [2, A, 4]
    assert pts.shape == [A, 2] and strides.shape == [A]
    # decoded boxes are valid (x2>x1, y2>y1 — softplus distances)
    b = boxes.numpy()
    assert (b[..., 2] >= b[..., 0]).all() and (b[..., 3] >= b[..., 1]).all()


def test_yolo_trains_and_detects():
    """Loss decreases under the compiled TrainStep, and after training
    the NMS postprocess localizes the synthetic square (IoU > 0.5)."""
    from paddle_tpu.jit import TrainStep

    paddle.seed(0)
    rng = np.random.RandomState(0)
    model = ppyoloe_lite(num_classes=2, width=8)
    opt = paddle.optimizer.Adam(learning_rate=5e-3,
                                parameters=model.parameters())
    # single-tensor target packing for the compiled step: [B,G,5] =
    # (xyxy, label)
    step = TrainStep(
        model, opt,
        lambda out, lab: yolo_loss(
            out, (lab[:, :, :4], lab[:, :, 4].cast("int64"))))

    imgs, gtb, gtl = zip(*[_synthetic_scene(rng) for _ in range(8)])
    x = paddle.to_tensor(np.stack(imgs))
    packed = np.concatenate(
        [np.stack(gtb), np.stack(gtl)[..., None].astype(np.float32)],
        axis=-1)
    target = paddle.to_tensor(packed)

    losses = [float(step(x, label=target)) for _ in range(150)]
    assert losses[-1] < losses[0] * 0.1, losses[:3] + losses[-3:]

    model.eval()
    out = model(x)
    dets = yolo_postprocess(out, score_threshold=0.2)

    def iou(a, b):
        ix = max(0, min(a[2], b[2]) - max(a[0], b[0]))
        iy = max(0, min(a[3], b[3]) - max(a[1], b[1]))
        inter = ix * iy
        ua = (a[2] - a[0]) * (a[3] - a[1]) + \
            (b[2] - b[0]) * (b[3] - b[1]) - inter
        return inter / max(ua, 1e-6)

    hits = 0
    for i in range(len(dets)):
        if len(dets[i]) == 0:
            continue
        best = max(iou(d[2:6], np.stack(gtb)[i, 0]) for d in dets[i][:5])
        hits += best > 0.5
    assert hits >= 6, f"only {hits}/{len(dets)} localized at IoU>0.5"


def test_yolo_loss_assignment():
    """Anchors inside a gt box are positives; an empty scene yields a
    pure-negative loss that pushes scores down."""
    paddle.seed(0)
    m = ppyoloe_lite(num_classes=2, width=8)
    x = paddle.to_tensor(np.zeros((1, 3, 64, 64), np.float32))
    out = m(x)
    empty = (paddle.to_tensor(np.full((1, 2, 4), -1.0, np.float32)),
             paddle.to_tensor(np.zeros((1, 2), np.int64)))
    l_empty = float(yolo_loss(out, empty))
    assert np.isfinite(l_empty) and l_empty > 0
    one = np.full((1, 2, 4), -1.0, np.float32)
    one[0, 0] = [8, 8, 40, 40]
    l_one = float(yolo_loss(out, (paddle.to_tensor(one),
                                  paddle.to_tensor(
                                      np.zeros((1, 2), np.int64)))))
    assert np.isfinite(l_one) and l_one != l_empty
