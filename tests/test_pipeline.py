"""Pipeline-parallel tests (VERDICT r1 missing #1). Runs on the 8-device
virtual CPU mesh from conftest. The SPMD shift-register schedule must be
numerically identical to running the same stacked layers sequentially."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import (
    DistributedTrainStep,
    LayerDesc,
    PipelineLayer,
    PipelineStack,
    SegmentLayers,
    SharedLayerDesc,
)
from paddle_tpu.distributed.topology import (
    HybridCommunicateGroup,
    set_hybrid_communicate_group,
)


class Block(nn.Layer):
    def __init__(self, hidden):
        super().__init__()
        self.ln = nn.LayerNorm(hidden)
        self.fc1 = nn.Linear(hidden, hidden * 2)
        self.fc2 = nn.Linear(hidden * 2, hidden)

    def forward(self, x):
        return x + self.fc2(F.gelu(self.fc1(self.ln(x))))


class Embed(nn.Layer):
    def __init__(self, vocab, hidden):
        super().__init__()
        self.emb = nn.Embedding(vocab, hidden)

    def forward(self, ids):
        return self.emb(ids)


class Head(nn.Layer):
    def __init__(self, hidden, vocab):
        super().__init__()
        self.proj = nn.Linear(hidden, vocab)

    def forward(self, x):
        return self.proj(x)


def _mk_model(pp, seed=0):
    paddle.seed(seed)
    set_hybrid_communicate_group(HybridCommunicateGroup(pp=pp))
    descs = [
        LayerDesc(Embed, 64, 16),
        *[LayerDesc(Block, 16) for _ in range(4)],
        LayerDesc(Head, 16, 64),
    ]
    return PipelineLayer(descs, num_stages=pp, num_microbatches=4)


def test_segment_layers_uniform():
    assert SegmentLayers.uniform(8, 4) == [0, 2, 4, 6, 8]
    assert SegmentLayers.uniform(10, 4) == [0, 3, 6, 8, 10]


def test_pipeline_forward_parity_pp4_vs_sequential():
    """Same weights: pipelined execution == sequential execution."""
    model = _mk_model(pp=4)
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 64, (8, 12), np.int32))
    out_pipe = model(ids).numpy()

    # rerun the stack sequentially with the same weights
    h = model.pre_layers[0](ids)
    h_seq = model.stack(h, pipelined=False)
    for layer, ffn in model._post:
        h_seq = ffn(layer, h_seq) if ffn is not None else layer(h_seq)
    np.testing.assert_allclose(out_pipe, h_seq.numpy(), atol=1e-4)


def test_pipeline_train_parity_vs_single_device():
    """pp=2 training loss curve matches the identical model trained with
    pp=1 (sequential) — same seed => same stacked init."""
    def run(pp, steps=4):
        model = _mk_model(pp=pp, seed=3)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        step = DistributedTrainStep(
            model, opt,
            lambda out, lab: F.cross_entropy(
                out.reshape([-1, 64]), lab.reshape([-1])))
        rng = np.random.RandomState(7)
        losses = []
        for _ in range(steps):
            ids = paddle.to_tensor(rng.randint(0, 64, (8, 12), np.int32))
            losses.append(float(step(ids, ids)))
        return losses

    l1 = run(1)
    l2 = run(2)
    np.testing.assert_allclose(l1, l2, rtol=2e-3)


def test_pipeline_microbatch_counts():
    """M != S still correct (more microbatches than stages)."""
    model = _mk_model(pp=2)
    ids = paddle.to_tensor(
        np.random.RandomState(1).randint(0, 64, (8, 12), np.int32))
    out_m4 = model(ids, num_microbatches=4).numpy()
    out_m2 = model(ids, num_microbatches=2).numpy()
    h = model.pre_layers[0](ids)
    ref = model.stack(h, pipelined=False)
    for layer, ffn in model._post:
        ref = ffn(layer, ref) if ffn is not None else layer(ref)
    np.testing.assert_allclose(out_m4, ref.numpy(), atol=1e-4)
    np.testing.assert_allclose(out_m2, ref.numpy(), atol=1e-4)


def test_shared_layer_desc_ties_weights():
    """SharedLayerDesc with the same key shares ONE layer instance."""
    paddle.seed(0)
    set_hybrid_communicate_group(HybridCommunicateGroup(pp=2))

    def head_fwd(layer, x):
        return paddle.matmul(x, layer.emb.weight, transpose_y=True)

    descs = [
        SharedLayerDesc("embed", Embed, None, "weight", 64, 16),
        *[LayerDesc(Block, 16) for _ in range(4)],
        SharedLayerDesc("embed", Embed, head_fwd, "weight", 64, 16),
    ]
    model = PipelineLayer(descs, num_stages=2, num_microbatches=2)
    # only one embedding parameter set exists
    emb_params = [p for p in model.parameters()
                  if p._array.shape == (64, 16)]
    assert len(emb_params) == 1
    ids = paddle.to_tensor(np.arange(24, dtype=np.int32).reshape(2, 12))
    out = model(ids)
    assert list(out.shape) == [2, 12, 64]


def test_pipeline_recompute_interval():
    paddle.seed(5)
    set_hybrid_communicate_group(HybridCommunicateGroup(pp=2))
    descs = [LayerDesc(Block, 16) for _ in range(4)]
    m_plain = PipelineLayer(descs, num_stages=2, num_microbatches=2)
    paddle.seed(5)
    m_ck = PipelineLayer(descs, num_stages=2, num_microbatches=2,
                         recompute_interval=1)
    x = paddle.randn([4, 8, 16])
    np.testing.assert_allclose(m_plain(x).numpy(), m_ck(x).numpy(),
                               atol=1e-5)


def test_pipeline_gpt_trains_mp2_pp2_sharding2():
    """The flagship hybrid config (BASELINE GPT mp2/pp2/sharding2) builds,
    compiles and decreases loss on the virtual 8-device mesh."""
    from paddle_tpu.models import GPTConfig
    from paddle_tpu.models.gpt import build_pipeline_gpt

    paddle.seed(0)
    set_hybrid_communicate_group(
        HybridCommunicateGroup(dp=1, mp=2, pp=2, sharding=2))
    cfg = GPTConfig.tiny(vocab=128, hidden=32, layers=4, heads=4, seq=16)
    model = build_pipeline_gpt(cfg, num_stages=2, num_microbatches=2)
    model.eval()
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=model.parameters())
    step = DistributedTrainStep(
        model, opt,
        lambda out, lab: F.cross_entropy(
            out.reshape([-1, cfg.vocab_size]), lab.reshape([-1])),
        sharding_stage=2, batch_axes=("dp", "sharding"))
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 128, (4, 16), np.int32))
    losses = [float(step(ids, ids)) for _ in range(5)]
    assert losses[-1] < losses[0], losses
    # tied embedding: exactly one (vocab, hidden) param
    tied = [p for p in model.parameters()
            if tuple(p._array.shape) == (128, 32)]
    assert len(tied) == 1


def test_pipeline_stack_params_sharded_over_pp():
    model = _mk_model(pp=2)
    for p in model.stack._stacked:
        assert p.dist_spec is not None and tuple(p.dist_spec)[0] == "pp"


def _mk_nonuniform(pp, n_blocks=5, seed=0, **kw):
    paddle.seed(seed)
    set_hybrid_communicate_group(HybridCommunicateGroup(pp=pp))
    descs = [
        LayerDesc(Embed, 64, 16),
        *[LayerDesc(Block, 16) for _ in range(n_blocks)],
        LayerDesc(Head, 16, 64),
    ]
    return PipelineLayer(descs, num_stages=pp, num_microbatches=4, **kw)


def test_segment_layers_weighted():
    # heavy first layer pulls the boundary early
    assert SegmentLayers.weighted([8, 1, 1, 1, 1], 2) == [0, 1, 5]
    assert SegmentLayers.weighted([1, 1, 1, 1], 2) == [0, 2, 4]
    b = SegmentLayers.weighted([1] * 7, 3)
    assert b[0] == 0 and b[-1] == 7 and len(b) == 4
    assert all(b[i] < b[i + 1] for i in range(3))


def test_pipeline_nonuniform_forward_parity():
    """5 body blocks over pp=2 (stages of 3 and 2, padded+masked):
    pipelined == sequential == a plain eager stack of the same layers."""
    model = _mk_nonuniform(pp=2)
    assert model.stack.stage_counts == [3, 2]
    assert not model.stack.uniform
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 64, (8, 12), np.int32))
    out_pipe = model(ids).numpy()

    h = model.pre_layers[0](ids)
    h_seq = model.stack(h, pipelined=False)
    for layer, ffn in model._post:
        h_seq = ffn(layer, h_seq) if ffn is not None else layer(h_seq)
    np.testing.assert_allclose(out_pipe, h_seq.numpy(), atol=1e-4)


def test_pipeline_nonuniform_train_parity():
    """Non-uniform pp=2 training == the same model at pp=1."""
    def run2(pp, steps=3):
        model = _mk_nonuniform(pp=pp, seed=3)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        step = DistributedTrainStep(
            model, opt,
            lambda out, lab: F.cross_entropy(
                out.reshape([-1, 64]), lab.reshape([-1])))
        rng = np.random.RandomState(7)
        losses = []
        for _ in range(steps):
            ids = paddle.to_tensor(rng.randint(0, 64, (8, 12), np.int32))
            losses.append(float(step(ids, ids)))
        return losses

    l_pp = run2(2)
    l_seq = run2(1)
    np.testing.assert_allclose(l_pp, l_seq, rtol=2e-3, atol=2e-4)


def test_pipeline_seg_method_parameters():
    model = _mk_nonuniform(pp=2, seg_method="parameters")
    counts = model.stack.stage_counts
    assert sum(counts) == 5 and len(counts) == 2
    ids = paddle.to_tensor(
        np.random.RandomState(1).randint(0, 64, (8, 12), np.int32))
    assert np.isfinite(model(ids).numpy()).all()


def test_pipeline_padded_slots_get_zero_grad():
    """The padded slot's parameters must not move during training."""
    model = _mk_nonuniform(pp=2, seed=5)
    # stacked params: [S=2, k_max=3, ...]; stage 1 slot 2 is the pad
    before = [np.asarray(p._array)[1, 2].copy()
              for p in model.stack._stacked]
    opt = paddle.optimizer.SGD(learning_rate=0.5,
                               parameters=model.parameters())
    step = DistributedTrainStep(
        model, opt,
        lambda out, lab: F.cross_entropy(
            out.reshape([-1, 64]), lab.reshape([-1])))
    rng = np.random.RandomState(2)
    for _ in range(2):
        ids = paddle.to_tensor(rng.randint(0, 64, (8, 12), np.int32))
        step(ids, ids)
    for b, p in zip(before, model.stack._stacked):
        np.testing.assert_allclose(b, np.asarray(p._array)[1, 2], atol=1e-7)
