"""Tier-1 tpu-race gate: the analyzer runs self-clean over the whole
codebase against the committed baseline, the TPU203 zombie-write rule
demonstrably fires on the broken depth-2 pipe shape (and passes the
fixed form), the TPU2xx namespace stays disjoint from tpu-lint's
TPU0xx and tpu-verify's TPU1xx, the introspect effect tables name
real framework methods, and importing the race package touches no JAX
backend."""
import os
import subprocess
import sys
from pathlib import Path

import pytest

import paddle_tpu.analysis.race as R
from paddle_tpu.analysis.race.cli import DEFAULT_BASELINE

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = Path(__file__).parent / "fixtures" / "tpu_race"

GATE_PATHS = [os.path.join(REPO, "paddle_tpu")] + sorted(
    str(p) for p in Path(REPO).glob("bench*.py")) + [
    os.path.join(REPO, "tools")]


@pytest.fixture(scope="module")
def repo_analysis():
    """One analysis of the whole repo shared by the gate assertions."""
    baseline = R.load_baseline(DEFAULT_BASELINE)
    return baseline, R.analyze_paths(GATE_PATHS, baseline=baseline)


def test_repo_is_race_clean_against_baseline(repo_analysis):
    """THE gate: any non-baselined TPU2xx finding in paddle_tpu/,
    bench*.py or tools/ fails tier-1. Hold the lock, annotate the
    caller contract with `# guarded-by:`, or fix the ordering — a
    baseline entry is the exceptional last resort."""
    _baseline, res = repo_analysis
    new = res.new_findings()
    assert new == [], "non-baselined tpu-race findings:\n" + "\n".join(
        f.render() for f in new)
    assert res.parse_errors == []
    # the gate must actually cover the codebase, not an empty glob
    assert len(res.files) > 185


def test_baseline_is_small_and_justified(repo_analysis):
    baseline, res = repo_analysis  # load_baseline raises if unjustified
    assert len(baseline) <= 5, (
        "tpu-race baseline grew past 5 entries — fix the concurrency "
        "instead of grandfathering it")
    for e in baseline.values():
        assert len(str(e["justification"]).strip()) >= 20, \
            f"baseline justification for {e['id']} is too thin"
    # no stale entries: every baselined id still matches a finding
    assert res.stale_baseline == []


def test_tpu203_fires_on_broken_depth2_pipe_and_passes_fixed():
    """The zombie-proofing gate for async pipe depth > 1 (ROADMAP
    item 3): freeing the previous iteration's blocks BEFORE waiting on
    its dispatch must fire; the complete-then-free ordering must not.
    The fixtures model the engine's loop-carried depth-2 shape."""
    broken, _ = R.analyze_file(str(FIXTURES / "tpu203_pos.py"))
    assert [(f.rule, f.line) for f in broken] == [("TPU203", 17)], \
        [f.render() for f in broken]
    assert "zombie" in broken[0].message
    fixed, _ = R.analyze_file(str(FIXTURES / "tpu203_neg.py"))
    assert fixed == [], [f.render() for f in fixed]


def test_rule_id_namespaces_are_disjoint():
    """One registry test over all four analysis tiers: tpu-lint
    TPU0xx, tpu-verify TPU1xx, tpu-race TPU2xx, tpu-shard TPU3xx — no
    id collisions, each tier inside its own hundred-block."""
    from paddle_tpu.analysis import all_rule_ids
    from paddle_tpu.analysis.race.rules import all_race_rule_ids
    from paddle_tpu.analysis.shard.rules import all_shard_rule_ids
    from paddle_tpu.analysis.trace.rules import all_trace_rule_ids

    tiers = {
        "lint": (set(all_rule_ids()), 0),
        "trace": (set(all_trace_rule_ids()), 100),
        "race": (set(all_race_rule_ids()), 200),
        "shard": (set(all_shard_rule_ids()), 300),
    }
    for name, (ids, base) in tiers.items():
        assert ids, name
        assert all(base <= int(r[3:]) <= base + 99 for r in ids), name
    names = sorted(tiers)
    for i, a in enumerate(names):
        for b in names[i + 1:]:
            assert not (tiers[a][0] & tiers[b][0]), (a, b)


def test_introspect_effect_tables_name_real_methods():
    """The dispatch/release tables TPU203 consumes must track the real
    framework surface (the ENGINE_STEP_DONATION pattern): every name
    is a callable on the class that declares it, and the classes
    reference the table rather than restating the strings."""
    from paddle_tpu.adapters.pool import PagedAdapterPool
    from paddle_tpu.inference.engine import (GenerationEngine,
                                             PagedKVCache)
    from paddle_tpu.jit import introspect as I

    by_name = {"PagedKVCache": PagedKVCache,
               "PagedAdapterPool": PagedAdapterPool}
    assert sorted(by_name) == sorted(I.ALLOCATOR_RELEASE_EFFECTS)
    for cls_name, methods in I.ALLOCATOR_RELEASE_EFFECTS.items():
        cls = by_name[cls_name]
        assert cls.RACE_RELEASE_METHODS == methods
        for m in methods:
            assert callable(getattr(cls, m)), (cls_name, m)
    assert GenerationEngine.RACE_DISPATCH_METHODS \
        == I.ENGINE_DISPATCH_EFFECTS
    for m in I.ENGINE_DISPATCH_EFFECTS:
        assert callable(getattr(GenerationEngine, m)), m
    assert GenerationEngine.RACE_COMPLETE_CALLS == I.STEP_COMPLETE_CALLS
    assert "jax.block_until_ready" in I.STEP_COMPLETE_CALLS
    # the serial completes sync via host conversion, not an explicit
    # block_until_ready — the table must cover that path too
    assert "numpy.asarray" in I.STEP_COMPLETE_CALLS


def test_race_import_has_no_backend_init_and_no_jax_use():
    """Importing + running the race analyzer must not initialize a JAX
    backend: pure AST work over introspect metadata, safe in
    pre-device CI stages."""
    code = (
        "import paddle_tpu.analysis.race as R\n"
        "from jax._src import xla_bridge\n"
        "assert not xla_bridge._backends, 'import initialized a backend'\n"
        "src = ('import threading\\n'\n"
        "       'class W:\\n'\n"
        "       '    def __init__(self):\\n'\n"
        "       '        self.n = 0\\n'\n"
        "       '        threading.Thread(target=self._w).start()\\n'\n"
        "       '    def _w(self):\\n'\n"
        "       '        self.n += 1\\n'\n"
        "       '    def step(self):\\n'\n"
        "       '        return self.n\\n')\n"
        "findings, _ = R.analyze_file('snippet.py', src)\n"
        "assert [f.rule for f in findings] == ['TPU201'], findings\n"
        "assert not xla_bridge._backends, 'analysis touched a backend'\n"
        "print('RACE_SMOKE_OK')\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    res = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "RACE_SMOKE_OK" in res.stdout


def test_cli_acceptance_command_exits_zero():
    """The ISSUE acceptance command, verbatim."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tpu_race.py"),
         os.path.join(REPO, "paddle_tpu"),
         os.path.join(REPO, "bench_ops.py"),
         os.path.join(REPO, "tools")],
        env=env, capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "tpu-race clean" in res.stdout
