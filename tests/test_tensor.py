"""Tensor basics: creation, dtype, arithmetic, indexing — the analog of
the reference's eager tensor unit tests (test_egr_python_api etc.)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_and_numpy():
    t = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert t.shape == [2, 2]
    assert t.dtype == "float32"
    np.testing.assert_allclose(t.numpy(), [[1, 2], [3, 4]])


def test_dtype_inference():
    # TPU-native: ints are 32-bit natively; "int64" is an accepted alias
    assert paddle.to_tensor(1).dtype == "int32"
    assert paddle.to_tensor(1, dtype="int64").dtype == "int32"
    assert paddle.to_tensor(1.5).dtype == "float32"
    assert paddle.to_tensor(True).dtype == "bool"
    assert paddle.to_tensor(np.float64(2.0)).dtype == "float32"
    assert paddle.to_tensor([1, 2], dtype="bfloat16").dtype == "bfloat16"


def test_creation_ops():
    assert paddle.zeros([2, 3]).numpy().sum() == 0
    assert paddle.ones([4]).numpy().sum() == 4
    np.testing.assert_array_equal(paddle.arange(5).numpy(), np.arange(5))
    assert paddle.full([2, 2], 7.0).numpy().sum() == 28
    assert paddle.eye(3).numpy().trace() == 3
    assert paddle.linspace(0, 1, 5).shape == [5]


def test_arithmetic():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    b = paddle.to_tensor([4.0, 5.0, 6.0])
    np.testing.assert_allclose((a + b).numpy(), [5, 7, 9])
    np.testing.assert_allclose((a - b).numpy(), [-3, -3, -3])
    np.testing.assert_allclose((a * b).numpy(), [4, 10, 18])
    np.testing.assert_allclose((b / a).numpy(), [4, 2.5, 2])
    np.testing.assert_allclose((a ** 2).numpy(), [1, 4, 9], rtol=1e-5)
    np.testing.assert_allclose((-a).numpy(), [-1, -2, -3])
    np.testing.assert_allclose((2.0 * a).numpy(), [2, 4, 6])
    np.testing.assert_allclose((1.0 + a).numpy(), [2, 3, 4])


def test_scalar_keeps_dtype():
    a = paddle.to_tensor([1.0], dtype="bfloat16")
    assert (a * 2.0).dtype == "bfloat16"
    assert (a + 1).dtype == "bfloat16"


def test_comparisons():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    np.testing.assert_array_equal((a > 1.5).numpy(), [False, True, True])
    np.testing.assert_array_equal((a == 2.0).numpy(), [False, True, False])


def test_matmul():
    a = paddle.randn([3, 4])
    b = paddle.randn([4, 5])
    c = a @ b
    assert c.shape == [3, 5]
    np.testing.assert_allclose(c.numpy(), a.numpy() @ b.numpy(), rtol=1e-5)


def test_indexing():
    a = paddle.to_tensor(np.arange(12).reshape(3, 4).astype(np.float32))
    np.testing.assert_allclose(a[0].numpy(), [0, 1, 2, 3])
    np.testing.assert_allclose(a[1, 2].numpy(), 6)
    np.testing.assert_allclose(a[:, 1].numpy(), [1, 5, 9])
    np.testing.assert_allclose(a[0:2, 1:3].numpy(), [[1, 2], [5, 6]])


def test_setitem():
    a = paddle.zeros([3, 3])
    a[1, 1] = 5.0
    assert a.numpy()[1, 1] == 5.0


def test_reshape_transpose():
    a = paddle.arange(6, dtype="float32")
    b = a.reshape([2, 3])
    assert b.shape == [2, 3]
    c = b.transpose([1, 0])
    assert c.shape == [3, 2]
    np.testing.assert_allclose(c.numpy(), b.numpy().T)
    assert b.T.shape == [3, 2]


def test_reductions():
    a = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert float(a.sum()) == 10
    assert float(a.mean()) == 2.5
    assert float(a.max()) == 4
    np.testing.assert_allclose(a.sum(axis=0).numpy(), [4, 6])
    np.testing.assert_allclose(a.sum(axis=1, keepdim=True).numpy(), [[3], [7]])
    assert a.argmax().numpy() == 3


def test_concat_split_stack():
    a = paddle.ones([2, 3])
    b = paddle.zeros([2, 3])
    c = paddle.concat([a, b], axis=0)
    assert c.shape == [4, 3]
    s = paddle.stack([a, b], axis=0)
    assert s.shape == [2, 2, 3]
    parts = paddle.split(c, 2, axis=0)
    assert len(parts) == 2 and parts[0].shape == [2, 3]
    np.testing.assert_allclose(parts[0].numpy(), a.numpy())


def test_cast():
    a = paddle.to_tensor([1.5, 2.5])
    assert a.astype("int32").dtype == "int32"
    assert a.astype("bfloat16").dtype == "bfloat16"


def test_where_clip():
    a = paddle.to_tensor([-1.0, 0.5, 2.0])
    np.testing.assert_allclose(a.clip(0.0, 1.0).numpy(), [0, 0.5, 1.0])
    w = paddle.where(a > 0, a, paddle.zeros_like(a))
    np.testing.assert_allclose(w.numpy(), [0, 0.5, 2.0])


def test_item_and_bool():
    a = paddle.to_tensor([3.0])
    assert a.item() == 3.0
    assert bool(a > 2.0)
    with pytest.raises(ValueError):
        bool(paddle.ones([2]) > 0)


def test_gather_scatter():
    x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
    idx = paddle.to_tensor([0, 2])
    g = paddle.gather(x, idx)
    np.testing.assert_allclose(g.numpy(), [[1, 2], [5, 6]])
    upd = paddle.to_tensor([[9.0, 9.0], [8.0, 8.0]])
    s = paddle.scatter(x, idx, upd)
    np.testing.assert_allclose(s.numpy(), [[9, 9], [3, 4], [8, 8]])


def test_topk_sort():
    a = paddle.to_tensor([3.0, 1.0, 4.0, 1.0, 5.0])
    v, i = paddle.topk(a, 2)
    np.testing.assert_allclose(v.numpy(), [5, 4])
    s = paddle.sort(a, descending=True)
    np.testing.assert_allclose(s.numpy(), [5, 4, 3, 1, 1])


def test_random_deterministic():
    import paddle_tpu

    paddle_tpu.seed(7)
    a = paddle.randn([4])
    paddle_tpu.seed(7)
    b = paddle.randn([4])
    np.testing.assert_allclose(a.numpy(), b.numpy())
