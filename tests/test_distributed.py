"""Distributed tests on the 8-device virtual CPU mesh — the reference's
multi-process localhost pattern (SURVEY §4) translated to SPMD: loss/grad
parity between single-device and sharded execution.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.topology import (
    HybridCommunicateGroup,
    set_hybrid_communicate_group,
)


@pytest.fixture(autouse=True)
def _reset_hcg():
    yield
    import paddle_tpu.distributed.topology as topo

    topo._default_hcg = None


def _devices():
    import jax

    return jax.devices()


def test_topology_math():
    topo = dist.CommunicateTopology(["data", "pipe", "sharding", "model"],
                                    [2, 2, 1, 2])
    assert topo.world_size() == 8
    assert topo.get_rank(data=1, pipe=0, sharding=0, model=1) == 5
    assert topo.get_coord(5) == (1, 0, 1, 0) or topo.get_coord(5)
    groups = topo.get_comm_list("model")
    assert len(groups) == 4 and all(len(g) == 2 for g in groups)


def test_hcg_mesh_axes():
    hcg = HybridCommunicateGroup(dp=2, mp=2, sharding=2)
    assert hcg.get_data_parallel_world_size() == 2
    assert hcg.get_model_parallel_world_size() == 2
    assert hcg.get_sharding_parallel_world_size() == 2
    assert hcg.mesh.shape["dp"] == 2 and hcg.mesh.shape["mp"] == 2
    assert hcg.nranks == 8


def test_shard_map_collectives():
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    shard_map = __import__("jax").shard_map

    hcg = HybridCommunicateGroup(dp=8)
    set_hybrid_communicate_group(hcg)
    mesh = hcg.mesh
    x = jnp.arange(8.0)

    def body(v):
        s = dist.functional.all_reduce(v, "dp")
        g = dist.functional.all_gather(v, "dp")
        return s, g

    f = shard_map(body, mesh=mesh, in_specs=P("dp"),
                  out_specs=(P("dp"), P("dp")))
    s, g = f(x)
    np.testing.assert_allclose(np.asarray(s), [28.0] * 8)  # psum
    assert g.shape == (64,)  # gathered per shard then stacked over shards


def test_distributed_train_step_dp_parity():
    """dp=8 SPMD step must match single-device training numerically."""

    def build():
        paddle.seed(5)
        net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        return net, opt

    paddle.seed(11)
    x = paddle.randn([16, 8])
    y = paddle.randn([16, 1])

    # single device reference
    net1, opt1 = build()
    losses1 = []
    for _ in range(3):
        loss = F.mse_loss(net1(x), y)
        loss.backward()
        opt1.step()
        opt1.clear_grad()
        losses1.append(float(loss))

    # dp=8 SPMD
    hcg = HybridCommunicateGroup(dp=8)
    set_hybrid_communicate_group(hcg)
    net2, opt2 = build()
    step = dist.DistributedTrainStep(net2, opt2, lambda o, t: F.mse_loss(o, t),
                                     hcg=hcg)
    losses2 = [float(step(x, y)) for _ in range(3)]

    np.testing.assert_allclose(losses1, losses2, rtol=1e-4)
    np.testing.assert_allclose(net1.parameters()[0].numpy(),
                               net2.parameters()[0].numpy(), rtol=1e-4)


def test_distributed_train_step_mp_parity():
    """mp=2 tensor-parallel GPT-tiny must track the replicated run."""
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    def build():
        paddle.seed(7)
        cfg = GPTConfig.tiny(vocab=64, hidden=32, layers=2, heads=4, seq=16)
        m = GPTForCausalLM(cfg)
        o = paddle.optimizer.Adam(learning_rate=1e-3,
                                  parameters=m.parameters())
        return m, o

    paddle.seed(13)
    ids = paddle.randint(0, 64, [4, 16])

    m1, o1 = build()
    s1 = paddle.jit.TrainStep(m1, o1, m1.loss_fn)
    ref = [float(s1(ids, ids)) for _ in range(3)]

    hcg = HybridCommunicateGroup(dp=2, mp=2)
    set_hybrid_communicate_group(hcg)
    m2, o2 = build()
    # annotate qkv/mlp weights over mp (what mp_layers do automatically)
    from jax.sharding import PartitionSpec as P

    for name, p in m2.named_parameters():
        if "qkv_proj.weight" in name or "fc1.weight" in name:
            p.dist_spec = P(None, "mp")
        elif "out_proj.weight" in name or "fc2.weight" in name:
            p.dist_spec = P("mp", None)
    s2 = dist.DistributedTrainStep(m2, o2, m2.loss_fn, hcg=hcg,
                                   batch_axes=("dp",))
    got = [float(s2(ids, ids)) for _ in range(3)]
    np.testing.assert_allclose(ref, got, rtol=2e-3)


def test_column_row_parallel_layers_single_device():
    """mp degree 1: parallel layers behave exactly like Linear."""
    paddle.seed(0)
    col = dist.ColumnParallelLinear(8, 16)
    row = dist.RowParallelLinear(16, 8)
    x = paddle.randn([2, 8])
    h = col(x)
    assert h.shape == [2, 16]
    out = row(h)
    assert out.shape == [2, 8]
    expect = (x.numpy() @ col.weight.numpy() + col.bias.numpy()) \
        @ row.weight.numpy() + row.bias.numpy()
    np.testing.assert_allclose(out.numpy(), expect, rtol=1e-4)


def test_mp_sharded_layer_forward_under_mesh():
    """Column/Row parallel with mp=4: sharded jit forward == dense."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    hcg = HybridCommunicateGroup(mp=4)
    set_hybrid_communicate_group(hcg)
    paddle.seed(2)
    col = dist.ColumnParallelLinear(8, 16, gather_output=False)
    row = dist.RowParallelLinear(16, 8, input_is_parallel=True)
    x = paddle.randn([4, 8])
    dense = (x.numpy() @ col.weight.numpy() + col.bias.numpy()) \
        @ row.weight.numpy() + row.bias.numpy()

    # place weights sharded per their dist_spec and run a jitted forward
    for p in list(col.parameters()) + list(row.parameters()):
        spec = p.dist_spec or P()
        p._array = jax.device_put(p._array,
                                  NamedSharding(hcg.mesh, spec))

    from paddle_tpu.jit import to_static

    @to_static
    def fwd(x):
        return row(col(x))

    out = fwd(x)
    np.testing.assert_allclose(out.numpy(), dense, rtol=1e-4)


def test_ring_attention_matches_dense():
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    shard_map = __import__("jax").shard_map

    hcg = HybridCommunicateGroup(cp=8)
    set_hybrid_communicate_group(hcg)
    B, S, H, D = 2, 64, 4, 8
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))

    ring = shard_map(
        lambda a, b, c: dist.ring_attention(a, b, c, axis_name="cp",
                                            causal=True),
        mesh=hcg.mesh,
        in_specs=(P(None, "cp"), P(None, "cp"), P(None, "cp")),
        out_specs=P(None, "cp"))
    out_ring = np.asarray(ring(q, k, v))

    # dense reference
    from paddle_tpu.ops.nn_ops import scaled_dot_product_attention
    from paddle_tpu.core.tensor import Tensor

    ref = scaled_dot_product_attention(
        Tensor._wrap(q), Tensor._wrap(k), Tensor._wrap(v),
        is_causal=True, training=False).numpy()
    np.testing.assert_allclose(out_ring, ref, atol=2e-4)


def test_ulysses_attention_matches_dense():
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    shard_map = __import__("jax").shard_map

    hcg = HybridCommunicateGroup(cp=4)
    set_hybrid_communicate_group(hcg)
    B, S, H, D = 2, 32, 4, 8
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))

    uly = shard_map(
        lambda a, b, c: dist.ulysses_attention(a, b, c, axis_name="cp",
                                               causal=True),
        mesh=hcg.submesh("cp"),
        in_specs=(P(None, "cp"), P(None, "cp"), P(None, "cp")),
        out_specs=P(None, "cp"))
    out = np.asarray(uly(q, k, v))

    from paddle_tpu.ops.nn_ops import scaled_dot_product_attention
    from paddle_tpu.core.tensor import Tensor

    ref = scaled_dot_product_attention(
        Tensor._wrap(q), Tensor._wrap(k), Tensor._wrap(v),
        is_causal=True, training=False).numpy()
    np.testing.assert_allclose(out, ref, atol=2e-4)


def test_moe_layer_forward():
    paddle.seed(3)
    moe = dist.MoELayer(d_model=16, d_hidden=32, num_experts=4,
                        capacity_factor=2.0)
    x = paddle.randn([2, 8, 16])
    out = moe(x)
    assert out.shape == [2, 8, 16]
    assert np.isfinite(out.numpy()).all()
    assert moe.aux_loss is not None
    # top-2 combine weights roughly preserve scale; backward works
    out.sum().backward()
    assert moe.w1.grad is not None


def test_moe_switch_gate():
    paddle.seed(4)
    moe = dist.MoELayer(d_model=8, d_hidden=16, num_experts=2, gate="switch",
                        capacity_factor=4.0)
    out = moe(paddle.randn([1, 8, 8]))
    assert out.shape == [1, 8, 8]


def test_recompute_grads_match():
    paddle.seed(6)
    block = nn.Sequential(nn.Linear(8, 32), nn.Tanh(), nn.Linear(32, 8))
    x = paddle.randn([4, 8])
    x.stop_gradient = False

    out1 = block(x)
    out1.sum().backward()
    g_plain = [p.grad.numpy().copy() for p in block.parameters()]
    gx_plain = x.grad.numpy().copy()
    block.clear_gradients()
    x.clear_grad()

    out2 = dist.recompute(block, x)
    out2.sum().backward()
    g_rc = [p.grad.numpy() for p in block.parameters()]
    np.testing.assert_allclose(gx_plain, x.grad.numpy(), rtol=1e-5)
    for a, b in zip(g_plain, g_rc):
        np.testing.assert_allclose(a, b, rtol=1e-5)


def test_group_sharded_stage2_opt_state_sharded():
    import jax

    hcg = HybridCommunicateGroup(sharding=8)
    set_hybrid_communicate_group(hcg)
    paddle.seed(8)
    net = nn.Linear(16, 64)
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=net.parameters())
    net, opt, _ = dist.group_sharded_parallel(net, opt, level="os_g")
    step = dist.DistributedTrainStep(net, opt, lambda o, t: F.mse_loss(o, t),
                                     hcg=hcg, sharding_stage=2)
    x = paddle.randn([8, 16])
    y = paddle.randn([8, 64])
    loss0 = float(step(x, y))
    loss1 = float(step(x, y))
    assert loss1 < loss0
    # optimizer moments sharded over 'sharding' axis (ZeRO-1/2)
    m = opt._accumulators["moment1"][0]
    assert "sharding" in str(m.sharding.spec)


def test_distributed_strategy_roundtrip(tmp_path):
    s = dist.fleet.DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 2, "mp_degree": 4}
    assert s.hybrid_configs["dp_degree"] == 2
    assert s.hybrid_configs["pp_degree"] == 1  # merged, not replaced
    p = str(tmp_path / "strategy.json")
    s.save_to_prototxt(p)
    s2 = dist.fleet.DistributedStrategy()
    s2.load_from_prototxt(p)
    assert s2.hybrid_configs["mp_degree"] == 4


def test_fleet_init_builds_mesh():
    s = dist.fleet.DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "sharding_degree": 2}
    hcg = dist.fleet.init(is_collective=True, strategy=s)
    assert hcg.nranks == 8
    assert dist.fleet.is_initialized()
    from paddle_tpu.distributed.topology import get_hybrid_communicate_group

    assert get_hybrid_communicate_group() is hcg


def test_distributed_gradient_merge_parity():
    """K micro-batches with accumulate_steps=K == one K-times-larger
    batch (mean-reduced loss), on the dp mesh — including ZeRO-2
    sharded merge buffers."""
    import paddle_tpu.nn as nn

    def mk(stage):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        return net, opt

    rng = np.random.RandomState(0)
    xs = rng.randn(4, 8, 8).astype(np.float32)   # 4 micro-batches of 8
    ys = rng.randint(0, 2, (4, 8))

    hcg = HybridCommunicateGroup(dp=2, sharding=2)
    set_hybrid_communicate_group(hcg)

    # merged: 4 micro-batches, update on the 4th
    net_m, opt_m = mk(2)
    step_m = dist.DistributedTrainStep(net_m, opt_m, lambda o, l:
                                  F.cross_entropy(o, l),
                                  sharding_stage=2, accumulate_steps=4)
    for i in range(4):
        step_m(paddle.to_tensor(xs[i]), label=paddle.to_tensor(ys[i]))
    assert opt_m._step_count == 1

    # reference: ONE batch of 32 (same samples), one update
    net_r, opt_r = mk(2)
    step_r = dist.DistributedTrainStep(net_r, opt_r, lambda o, l:
                                  F.cross_entropy(o, l),
                                  sharding_stage=2)
    step_r(paddle.to_tensor(xs.reshape(32, 8)),
           label=paddle.to_tensor(ys.reshape(32)))

    for pm, pr in zip(net_m.parameters(), net_r.parameters()):
        np.testing.assert_allclose(np.asarray(pm._array),
                                   np.asarray(pr._array),
                                   rtol=2e-4, atol=2e-5)


def test_gradient_merge_sum_mode():
    """accumulate_avg=False applies the SUM of the K micro-grads
    (GradientMergeOptimizer avg=False parity)."""
    import paddle_tpu.nn as nn

    def run(avg, lr):
        paddle.seed(0)
        net = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(learning_rate=lr,
                                   parameters=net.parameters())
        step = dist.DistributedTrainStep(
            net, opt, F.cross_entropy, accumulate_steps=2,
            accumulate_avg=avg)
        rng = np.random.RandomState(0)
        for i in range(2):
            step(paddle.to_tensor(rng.randn(8, 4).astype(np.float32)),
                 label=paddle.to_tensor(rng.randint(0, 2, (8,))))
        return [np.asarray(p._array) for p in net.parameters()]

    set_hybrid_communicate_group(HybridCommunicateGroup(dp=2))
    # sum at lr == mean at 2*lr
    p_sum = run(False, 0.05)
    p_avg = run(True, 0.10)
    for a, b in zip(p_sum, p_avg):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_fleet_init_validates_hybrid_configs():
    """fleet.init fails fast on a wrong hybrid_configs (VERDICT r3 weak
    #3) instead of surfacing an opaque mesh error at first compile."""
    import pytest

    import paddle_tpu.distributed.fleet as fleet
    from paddle_tpu.distributed.fleet import DistributedStrategy

    s = DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 64, "mp_degree": 2}
    with pytest.raises(ValueError, match="128 devices"):
        fleet.init(is_collective=True, strategy=s)

    # unknown keys warn (reference-style extras like "order"/"mp_configs"
    # pass silently; a typo'd degree is ignored with a warning)
    import warnings as _warnings

    s2 = DistributedStrategy()
    s2.hybrid_configs = {"dp_degree": 2, "np_degree": 3}
    with _warnings.catch_warnings(record=True) as w:
        _warnings.simplefilter("always")
        fleet.init(is_collective=True, strategy=s2)
    assert any("np_degree" in str(x.message) for x in w)

    s2b = DistributedStrategy()
    s2b.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                          "order": ["dp", "pp", "sharding", "mp"],
                          "mp_configs": {"sync_param": False}}
    with _warnings.catch_warnings(record=True) as w:
        _warnings.simplefilter("always")
        fleet.init(is_collective=True, strategy=s2b)
    assert not w  # reference-style keys are accepted silently

    s3 = DistributedStrategy()
    s3.hybrid_configs = {"dp_degree": 0}
    with pytest.raises(ValueError, match=">= 1"):
        fleet.init(is_collective=True, strategy=s3)

    # a valid config still initializes
    s4 = DistributedStrategy()
    s4.hybrid_configs = {"dp_degree": 2, "mp_degree": 2}
    hcg = fleet.init(is_collective=True, strategy=s4)
    assert hcg.get_data_parallel_world_size() == 2
