"""PS-lite tests (VERDICT r2 #7): host-RAM sparse tables with pull/push,
DistributedEmbedding gradient flow, and wide&deep training.

Reference analogs: distributed/ps/table/memory_sparse_table.h,
sparse_sgd_rule.h, the_one_ps.py:1031, ps/README.md taxonomy.
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed.ps import (DistributedEmbedding,
                                       MemorySparseTable,
                                       SparseAdagradRule, SparseSGDRule)
from paddle_tpu.models import WideDeep


def test_table_pull_push_sgd():
    t = MemorySparseTable(dim=4, rule=SparseSGDRule(0.1), nshards=3)
    ids = np.array([7, 2, 7, 100000001])
    rows = t.pull(ids)
    assert rows.shape == (4, 4)
    # duplicate id pulls the same row; only 3 rows materialized
    np.testing.assert_array_equal(rows[0], rows[2])
    assert t.touched == 3

    g = np.ones((3, 4), np.float32)
    before = t.pull(np.array([7, 2, 100000001])).copy()
    t.push(np.array([7, 2, 100000001]), g)
    after = t.pull(np.array([7, 2, 100000001]))
    np.testing.assert_allclose(after, before - 0.1, rtol=1e-6)
    # untouched id unaffected and lazily created elsewhere
    assert t.touched == 3


def test_table_adagrad_state():
    t = MemorySparseTable(dim=2, rule=SparseAdagradRule(1.0, eps=0.0))
    r0 = t.pull(np.array([5])).copy()
    t.push(np.array([5]), np.array([[2.0, 2.0]], np.float32))
    r1 = t.pull(np.array([5]))
    # adagrad first step: lr * g / sqrt(g^2) = lr
    np.testing.assert_allclose(r1, r0 - 1.0, rtol=1e-6)
    t.push(np.array([5]), np.array([[2.0, 2.0]], np.float32))
    r2 = t.pull(np.array([5]))
    # second step: 2/sqrt(8) ≈ 0.7071 — accumulator grows
    np.testing.assert_allclose(r2, r1 - 2.0 / np.sqrt(8.0), rtol=1e-5)


def test_table_checkpoint_roundtrip():
    t = MemorySparseTable(dim=3, nshards=2)
    t.pull(np.array([1, 2, 9]))
    t.push(np.array([1]), np.ones((1, 3), np.float32))
    sd = t.state_dict()
    # point-in-time: later pushes must not mutate the saved copy
    frozen = sd["1"][0].copy()
    t.push(np.array([1]), np.ones((1, 3), np.float32))
    np.testing.assert_array_equal(sd["1"][0], frozen)
    # reload under a DIFFERENT shard count: rows route by id
    t2 = MemorySparseTable(dim=3, nshards=3, seed=123)
    t2.set_state_dict(sd)
    got = t2.pull(np.array([1, 2, 9]))
    np.testing.assert_array_equal(got[1:], t.pull(np.array([2, 9])))
    np.testing.assert_array_equal(got[0], frozen)
    # loaded table is independent of the source
    t2.push(np.array([2]), np.ones((1, 3), np.float32))
    assert not np.array_equal(t2.pull(np.array([2])),
                              t.pull(np.array([2])))


def test_embedding_grads_reach_table():
    emb = DistributedEmbedding(0, 4, rule=SparseSGDRule(0.5))
    ids = paddle.to_tensor(np.array([[1, 2], [1, 3]], np.int64))
    before = emb.table.pull(np.array([1, 2, 3])).copy()
    out = emb(ids)          # [2, 2, 4]
    out.sum().backward()
    emb.push_gradients()
    after = emb.table.pull(np.array([1, 2, 3]))
    # d(sum)/d(row) = multiplicity of the id in the batch
    np.testing.assert_allclose(after[0], before[0] - 0.5 * 2, rtol=1e-6)
    np.testing.assert_allclose(after[1], before[1] - 0.5 * 1, rtol=1e-6)
    np.testing.assert_allclose(after[2], before[2] - 0.5 * 1, rtol=1e-6)
    assert len(emb._pending) == 0
    # eval mode: no pending push state accumulates
    emb.eval()
    emb(ids)
    assert len(emb._pending) == 0


def test_wide_deep_trains():
    paddle.seed(0)
    rs = np.random.RandomState(0)
    num_fields, vocab = 4, 1000
    model = WideDeep(num_fields, embedding_dim=8, hidden=(32,))
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=model.parameters())
    # synthetic CTR: click iff field-0 id is even
    ids_np = rs.randint(0, vocab, size=(256, num_fields)).astype(np.int64)
    y_np = (ids_np[:, :1] % 2 == 0).astype(np.float32)

    losses = []
    for epoch in range(30):
        p = model(paddle.to_tensor(ids_np))
        loss = F.binary_cross_entropy(p, paddle.to_tensor(y_np))
        loss.backward()
        opt.step()
        opt.clear_grad()
        model.push_sparse()
        losses.append(float(loss))
    assert losses[-1] < 0.35, losses[-5:]
    # sparse rows really host-resident: table rows are numpy
    assert model.embedding.table.touched > 0
    shard = model.embedding.table._shards[0]
    if shard.rows:
        assert isinstance(next(iter(shard.rows.values())), np.ndarray)


def test_per_id_init_topology_invariant():
    """per_id_init: the same id initializes identically under ANY shard
    count (the portability the service tier relies on; review fix r4)."""
    t2 = MemorySparseTable(dim=4, nshards=2, seed=7, per_id_init=True)
    t4 = MemorySparseTable(dim=4, nshards=4, seed=7, per_id_init=True)
    ids = np.array([0, 1, 5, 6, 123456789])
    np.testing.assert_array_equal(t2.pull(ids), t4.pull(ids))
    # ...and independently of materialization ORDER
    t2b = MemorySparseTable(dim=4, nshards=2, seed=7, per_id_init=True)
    t2b.pull(ids[::-1])
    np.testing.assert_array_equal(t2.pull(ids), t2b.pull(ids))


def test_ssd_table_spills_and_reloads(tmp_path):
    """SSD tier (ssd_sparse_table.h analog): rows beyond max_mem_rows
    LRU-evict to disk with their accessor state; pulling a cold row
    loads it back with identical values and optimizer behavior."""
    from paddle_tpu.distributed.ps import SSDSparseTable

    t = SSDSparseTable(dim=4, rule=SparseSGDRule(0.1), max_mem_rows=8,
                       path=str(tmp_path / "t.sqlite"), seed=3)
    ids = np.arange(20)
    rows = t.pull(ids).copy()          # 20 rows through an 8-row cache
    assert t.touched == 20
    assert t.mem_rows <= 8
    assert t.disk_rows >= 12
    # cold rows reload with the SAME values
    np.testing.assert_array_equal(t.pull(ids[:4]), rows[:4])
    # pushes against a cold row apply to the reloaded copy
    before = t.pull(np.array([0])).copy()
    t.push(np.array([0]), np.ones((1, 4), np.float32))
    np.testing.assert_allclose(t.pull(np.array([0])), before - 0.1,
                               rtol=1e-6)
    # accessor state spills too: Adagrad semantics survive eviction
    ta = SSDSparseTable(dim=2, rule=SparseAdagradRule(1.0, eps=0.0),
                        max_mem_rows=2, path=str(tmp_path / "a.sqlite"))
    g = np.array([[2.0, 2.0]], np.float32)
    ta.push(np.array([5]), g)
    r1 = ta.pull(np.array([5])).copy()
    ta.pull(np.arange(100, 110))       # force id 5 to disk
    assert ta.mem_rows <= 2
    ta.push(np.array([5]), g)          # second step on the reloaded row
    r2 = ta.pull(np.array([5]))
    np.testing.assert_allclose(r2, r1 - 2.0 / np.sqrt(8.0), rtol=1e-5)
    # checkpoint covers disk-resident rows
    sd = t.state_dict()
    assert len(sd) == 20


def test_ssd_table_behaves_like_memory_table(tmp_path):
    """Any cache size produces the same numbers as the pure-RAM table."""
    from paddle_tpu.distributed.ps import SSDSparseTable

    rs = np.random.RandomState(0)
    mem = MemorySparseTable(dim=3, rule=SparseSGDRule(0.05), seed=9,
                            per_id_init=True)
    ssd = SSDSparseTable(dim=3, rule=SparseSGDRule(0.05), seed=9,
                         per_id_init=True, max_mem_rows=4,
                         path=str(tmp_path / "p.sqlite"))
    for _ in range(5):
        ids = rs.randint(0, 30, size=8)
        g = rs.randn(8, 3).astype(np.float32)
        mem.push(ids, g)
        ssd.push(ids, g)
    probe = np.arange(30)
    np.testing.assert_allclose(ssd.pull(probe), mem.pull(probe),
                               rtol=1e-6)


def test_ssd_table_restore_and_budget_edge(tmp_path):
    """Review r4: restored checkpoint rows join the LRU (evictable, no
    KeyError on push), stale disk copies never shadow restored rows,
    and a tiny budget still works."""
    from paddle_tpu.distributed.ps import SSDSparseTable

    src = SSDSparseTable(dim=2, rule=SparseSGDRule(0.1), max_mem_rows=50,
                         path=str(tmp_path / "src.sqlite"))
    src.pull(np.arange(20))
    sd = src.state_dict()

    # restore into a table whose budget is smaller than the checkpoint
    dst = SSDSparseTable(dim=2, rule=SparseSGDRule(0.1), max_mem_rows=8,
                         path=str(tmp_path / "dst.sqlite"))
    dst.set_state_dict(sd)
    assert dst.mem_rows <= 8        # restored rows spill to budget
    assert dst.touched == 20
    # push on any id works (the once-crashing path)
    before = dst.pull(np.array([3])).copy()
    dst.push(np.array([3]), np.ones((1, 2), np.float32))
    np.testing.assert_allclose(dst.pull(np.array([3])), before - 0.1,
                               rtol=1e-6)

    # stale-disk shadowing: spill id 7, restore a NEWER value for it
    dst2 = SSDSparseTable(dim=2, rule=SparseSGDRule(0.1), max_mem_rows=4,
                          path=str(tmp_path / "d2.sqlite"))
    dst2.pull(np.arange(10))        # id 7 likely on disk now
    newer = {"7": (np.array([9.0, 9.0], np.float32),
                   np.zeros((0,), np.float32))}
    dst2.set_state_dict(newer)
    assert dst2.state_dict()["7"][0].tolist() == [9.0, 9.0]
    # no double count
    assert dst2.touched == 10
