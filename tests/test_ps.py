"""PS-lite tests (VERDICT r2 #7): host-RAM sparse tables with pull/push,
DistributedEmbedding gradient flow, and wide&deep training.

Reference analogs: distributed/ps/table/memory_sparse_table.h,
sparse_sgd_rule.h, the_one_ps.py:1031, ps/README.md taxonomy.
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed.ps import (DistributedEmbedding,
                                       MemorySparseTable,
                                       SparseAdagradRule, SparseSGDRule)
from paddle_tpu.models import WideDeep


def test_table_pull_push_sgd():
    t = MemorySparseTable(dim=4, rule=SparseSGDRule(0.1), nshards=3)
    ids = np.array([7, 2, 7, 100000001])
    rows = t.pull(ids)
    assert rows.shape == (4, 4)
    # duplicate id pulls the same row; only 3 rows materialized
    np.testing.assert_array_equal(rows[0], rows[2])
    assert t.touched == 3

    g = np.ones((3, 4), np.float32)
    before = t.pull(np.array([7, 2, 100000001])).copy()
    t.push(np.array([7, 2, 100000001]), g)
    after = t.pull(np.array([7, 2, 100000001]))
    np.testing.assert_allclose(after, before - 0.1, rtol=1e-6)
    # untouched id unaffected and lazily created elsewhere
    assert t.touched == 3


def test_table_adagrad_state():
    t = MemorySparseTable(dim=2, rule=SparseAdagradRule(1.0, eps=0.0))
    r0 = t.pull(np.array([5])).copy()
    t.push(np.array([5]), np.array([[2.0, 2.0]], np.float32))
    r1 = t.pull(np.array([5]))
    # adagrad first step: lr * g / sqrt(g^2) = lr
    np.testing.assert_allclose(r1, r0 - 1.0, rtol=1e-6)
    t.push(np.array([5]), np.array([[2.0, 2.0]], np.float32))
    r2 = t.pull(np.array([5]))
    # second step: 2/sqrt(8) ≈ 0.7071 — accumulator grows
    np.testing.assert_allclose(r2, r1 - 2.0 / np.sqrt(8.0), rtol=1e-5)


def test_table_checkpoint_roundtrip():
    t = MemorySparseTable(dim=3, nshards=2)
    t.pull(np.array([1, 2, 9]))
    t.push(np.array([1]), np.ones((1, 3), np.float32))
    sd = t.state_dict()
    # point-in-time: later pushes must not mutate the saved copy
    frozen = sd["1"][0].copy()
    t.push(np.array([1]), np.ones((1, 3), np.float32))
    np.testing.assert_array_equal(sd["1"][0], frozen)
    # reload under a DIFFERENT shard count: rows route by id
    t2 = MemorySparseTable(dim=3, nshards=3, seed=123)
    t2.set_state_dict(sd)
    got = t2.pull(np.array([1, 2, 9]))
    np.testing.assert_array_equal(got[1:], t.pull(np.array([2, 9])))
    np.testing.assert_array_equal(got[0], frozen)
    # loaded table is independent of the source
    t2.push(np.array([2]), np.ones((1, 3), np.float32))
    assert not np.array_equal(t2.pull(np.array([2])),
                              t.pull(np.array([2])))


def test_embedding_grads_reach_table():
    emb = DistributedEmbedding(0, 4, rule=SparseSGDRule(0.5))
    ids = paddle.to_tensor(np.array([[1, 2], [1, 3]], np.int64))
    before = emb.table.pull(np.array([1, 2, 3])).copy()
    out = emb(ids)          # [2, 2, 4]
    out.sum().backward()
    emb.push_gradients()
    after = emb.table.pull(np.array([1, 2, 3]))
    # d(sum)/d(row) = multiplicity of the id in the batch
    np.testing.assert_allclose(after[0], before[0] - 0.5 * 2, rtol=1e-6)
    np.testing.assert_allclose(after[1], before[1] - 0.5 * 1, rtol=1e-6)
    np.testing.assert_allclose(after[2], before[2] - 0.5 * 1, rtol=1e-6)
    assert len(emb._pending) == 0
    # eval mode: no pending push state accumulates
    emb.eval()
    emb(ids)
    assert len(emb._pending) == 0


def test_wide_deep_trains():
    paddle.seed(0)
    rs = np.random.RandomState(0)
    num_fields, vocab = 4, 1000
    model = WideDeep(num_fields, embedding_dim=8, hidden=(32,))
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=model.parameters())
    # synthetic CTR: click iff field-0 id is even
    ids_np = rs.randint(0, vocab, size=(256, num_fields)).astype(np.int64)
    y_np = (ids_np[:, :1] % 2 == 0).astype(np.float32)

    losses = []
    for epoch in range(30):
        p = model(paddle.to_tensor(ids_np))
        loss = F.binary_cross_entropy(p, paddle.to_tensor(y_np))
        loss.backward()
        opt.step()
        opt.clear_grad()
        model.push_sparse()
        losses.append(float(loss))
    assert losses[-1] < 0.35, losses[-5:]
    # sparse rows really host-resident: table rows are numpy
    assert model.embedding.table.touched > 0
    shard = model.embedding.table._shards[0]
    if shard.rows:
        assert isinstance(next(iter(shard.rows.values())), np.ndarray)


def test_per_id_init_topology_invariant():
    """per_id_init: the same id initializes identically under ANY shard
    count (the portability the service tier relies on; review fix r4)."""
    t2 = MemorySparseTable(dim=4, nshards=2, seed=7, per_id_init=True)
    t4 = MemorySparseTable(dim=4, nshards=4, seed=7, per_id_init=True)
    ids = np.array([0, 1, 5, 6, 123456789])
    np.testing.assert_array_equal(t2.pull(ids), t4.pull(ids))
    # ...and independently of materialization ORDER
    t2b = MemorySparseTable(dim=4, nshards=2, seed=7, per_id_init=True)
    t2b.pull(ids[::-1])
    np.testing.assert_array_equal(t2.pull(ids), t2b.pull(ids))
