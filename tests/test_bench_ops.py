"""op μbench harness tests (VERDICT r2 #10): slope-based timing returns
sane values and the regression gate trips correctly.

Reference analog: paddle/fluid/operators/benchmark/op_tester.cc +
tools/ci benchmark gating.
"""
import json
import subprocess
import sys

import jax.numpy as jnp
import numpy as np

import bench_ops


def test_timeit_measures_real_work():
    import jax

    f = jax.jit(lambda a: jnp.tanh(a @ a.T).sum()[None])
    x = jnp.ones((256, 256), jnp.float32)
    ms = bench_ops._timeit(f, x, n_small=2, target_s=0.05,
                           n_cap=64)
    assert 0 < ms < 1000


def test_regression_gate(tmp_path, monkeypatch):
    fake = {"op_a": {"op": "op_a", "ms": 1.0}, "op_b": {"op": "op_b",
                                                        "ms": 2.0}}
    base = tmp_path / "base.json"
    base.write_text(json.dumps(fake))

    # simulate a 2x regression on op_a via a fake run()
    slow = {"op_a": {"op": "op_a", "ms": 2.0}, "op_b": {"op": "op_b",
                                                        "ms": 2.0}}
    monkeypatch.setattr(bench_ops, "run", lambda: slow)
    monkeypatch.setattr(sys, "argv", ["bench_ops.py", "--check", str(base)])
    try:
        bench_ops.main()
        raised = False
    except SystemExit as e:
        raised = e.code == 1
    assert raised, "gate must fail on a 100% regression"

    # within threshold passes
    ok = {"op_a": {"op": "op_a", "ms": 1.1}, "op_b": {"op": "op_b",
                                                      "ms": 2.0}}
    monkeypatch.setattr(bench_ops, "run", lambda: ok)
    bench_ops.main()  # no SystemExit


def test_decode_case_shape_and_tokens_field():
    """VERDICT r4 next #8: the decode μbench entry decodes through the
    compiled KV-cache path and reports tokens/s (gate coverage: the
    case lives in suite(), so --check trips on its regressions too)."""
    case = bench_ops._decode_case()
    assert len(case) == 4
    fn, args, flops, extra = case
    assert extra["tokens"] == 4 * 32 and flops > 0
    out = np.asarray(fn(*args))
    assert out.shape == (4, 48)              # [B, max_length] tokens
    assert out.dtype == np.float32           # scalarizable carry
    assert (out >= 0).all() and (out < 4096).all()
    # salting the fuzz input changes the prompt (nothing loop-invariant)
    out2 = np.asarray(fn(args[0] + 1.0))
    assert not np.array_equal(out, out2)
    assert "gpt_decode_kv_32tok" in bench_ops.suite()
