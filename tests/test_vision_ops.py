"""Detection-op tests (BASELINE PP-YOLOE functional row): NMS against a
numpy reference, class-aware NMS, the fixed-shape jittable core, and
multiclass_nms assembly.
"""
import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.vision.ops import box_iou, multiclass_nms, nms, nms_fixed


def _np_nms(boxes, scores, thr):
    """Reference O(N^2) NMS."""
    order = np.argsort(-scores)
    keep = []
    while len(order):
        i = order[0]
        keep.append(i)
        if len(order) == 1:
            break
        xx1 = np.maximum(boxes[i, 0], boxes[order[1:], 0])
        yy1 = np.maximum(boxes[i, 1], boxes[order[1:], 1])
        xx2 = np.minimum(boxes[i, 2], boxes[order[1:], 2])
        yy2 = np.minimum(boxes[i, 3], boxes[order[1:], 3])
        w = np.maximum(0.0, xx2 - xx1)
        h = np.maximum(0.0, yy2 - yy1)
        inter = w * h
        a1 = (boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1])
        a2 = (boxes[order[1:], 2] - boxes[order[1:], 0]) * \
             (boxes[order[1:], 3] - boxes[order[1:], 1])
        iou = inter / (a1 + a2 - inter + 1e-9)
        order = order[1:][iou < thr]
    return np.array(keep)


def _random_boxes(n, seed):
    rs = np.random.RandomState(seed)
    xy = rs.uniform(0, 90, (n, 2)).astype(np.float32)
    wh = rs.uniform(5, 30, (n, 2)).astype(np.float32)
    return np.concatenate([xy, xy + wh], axis=1)


def test_box_iou_known_values():
    a = np.array([[0, 0, 10, 10]], np.float32)
    b = np.array([[0, 0, 10, 10], [5, 5, 15, 15], [20, 20, 30, 30]],
                 np.float32)
    iou = np.asarray(box_iou(a, b)._array)
    np.testing.assert_allclose(iou[0], [1.0, 25 / 175, 0.0], rtol=1e-5)


def test_nms_matches_numpy_reference():
    for seed in range(5):
        boxes = _random_boxes(60, seed)
        scores = np.random.RandomState(100 + seed) \
            .uniform(size=60).astype(np.float32)
        for thr in (0.3, 0.5, 0.7):
            got = np.asarray(nms(boxes, thr, scores=scores)._array)
            want = _np_nms(boxes, scores, thr)
            np.testing.assert_array_equal(got, want)


def test_nms_class_aware():
    # identical overlapping boxes in different classes both survive
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11]], np.float32)
    scores = np.array([0.9, 0.8], np.float32)
    cats = np.array([0, 1])
    kept = np.asarray(nms(boxes, 0.3, scores=scores, category_idxs=cats,
                          categories=[0, 1])._array)
    assert len(kept) == 2
    # same class: the lower-scored one is suppressed
    kept2 = np.asarray(nms(boxes, 0.3, scores=scores)._array)
    np.testing.assert_array_equal(kept2, [0])


def test_nms_fixed_is_jittable_inside_program():
    boxes = jnp.asarray(_random_boxes(32, 3))
    scores = jnp.asarray(np.random.RandomState(9)
                         .uniform(size=32).astype(np.float32))

    @jax.jit
    def head(b, s):
        idxs, valid = nms_fixed(b, s, jnp.float32(0.5), 10)
        return idxs, valid

    idxs, valid = head(boxes, scores)
    assert idxs.shape == (10,)
    want = _np_nms(np.asarray(boxes), np.asarray(scores), 0.5)[:10]
    np.testing.assert_array_equal(np.asarray(idxs)[np.asarray(valid)],
                                  want)


def test_nms_categories_filter_and_keep_all():
    boxes = np.array([[0, 0, 10, 10], [20, 20, 30, 30], [40, 40, 50, 50]],
                     np.float32)
    scores = np.array([0.9, 0.8, 0.7], np.float32)
    cats = np.array([0, 1, 2])
    # only classes 0 and 2 participate; class-1 box excluded entirely
    kept = np.asarray(nms(boxes, 0.5, scores=scores, category_idxs=cats,
                          categories=[0, 2])._array)
    np.testing.assert_array_equal(sorted(kept), [0, 2])
    # top_k=-1 is paddle's keep-all convention
    kept2 = np.asarray(nms(boxes, 0.5, scores=scores, top_k=-1)._array)
    assert len(kept2) == 3


def test_multiclass_nms():
    boxes = _random_boxes(40, 5)
    rs = np.random.RandomState(6)
    scores = rs.uniform(size=(3, 40)).astype(np.float32)
    out, k = multiclass_nms(boxes, scores, score_threshold=0.5,
                            nms_threshold=0.5, keep_top_k=20)
    out = np.asarray(out._array)
    assert out.shape[0] == k <= 20 and out.shape[1] == 6
    # sorted by score desc, labels in range, scores above threshold
    assert (np.diff(out[:, 1]) <= 1e-6).all()
    assert ((out[:, 0] >= 0) & (out[:, 0] <= 2)).all()
    assert (out[:, 1] >= 0.5).all()
