"""Auto-parallel Engine tests (SURVEY §2.5 auto-parallel row; reference
python/paddle/distributed/auto_parallel/engine.py): fit/evaluate/predict
over the virtual 8-device mesh, strategy-driven sharding plans, the XLA
cost model, and the stage tuner.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import auto_parallel as auto
from paddle_tpu.distributed import topology
from paddle_tpu.io import Dataset
from paddle_tpu.metric import Accuracy


class _RandDS(Dataset):
    """Linearly separable 2-class problem."""

    def __init__(self, n=128, d=16, seed=0):
        rs = np.random.RandomState(seed)
        self.x = rs.randn(n, d).astype(np.float32)
        w = rs.randn(d)
        self.y = (self.x @ w > 0).astype(np.int64)

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def _mlp(d=16, h=32, classes=2):
    return nn.Sequential(nn.Linear(d, h), nn.ReLU(), nn.Linear(h, classes))


@pytest.fixture(autouse=True)
def _fresh_topology():
    saved = topology._default_hcg
    topology._default_hcg = None
    yield
    topology._default_hcg = saved


def _engine(strategy=None, metrics=None):
    paddle.seed(0)
    model = _mlp()
    opt = paddle.optimizer.Adam(learning_rate=0.05,
                                parameters=model.parameters())
    return auto.Engine(model, F.cross_entropy, opt, metrics=metrics,
                       strategy=strategy), model


def test_engine_fit_converges_and_evaluates():
    eng, _ = _engine(metrics=[Accuracy()])
    ds = _RandDS()
    hist = eng.fit(ds, epochs=3, batch_size=32)
    assert hist["loss"][-1] < hist["loss"][0]
    logs = eng.evaluate(ds, batch_size=32)
    assert logs["acc"] > 0.8 and np.isfinite(logs["loss"])
    preds = eng.predict(ds, batch_size=32)
    assert preds.shape == (128, 2)


def test_engine_strategy_sharding_plan():
    strategy = auto.Strategy()
    strategy.sharding.enable = True
    strategy.sharding.stage = 2
    strategy.sharding.degree = 4
    eng, _ = _engine(strategy=strategy)
    hist = eng.fit(_RandDS(), epochs=2, batch_size=32)
    assert hist["loss"][-1] < hist["loss"][0]
    hcg = eng._ensure_hcg()
    assert hcg.axis_size("sharding") == 4 and hcg.axis_size("dp") == 2


def test_engine_respects_user_topology():
    hcg = topology.HybridCommunicateGroup(dp=2, mp=1)
    topology.set_hybrid_communicate_group(hcg)
    eng, _ = _engine()
    assert eng._ensure_hcg() is hcg


def test_engine_cost_model():
    eng, _ = _engine()
    ds = _RandDS()
    x = ds.x[:32]
    y = ds.y[:32]
    cost = eng.cost(x, y)
    assert cost["flops"] is None or cost["flops"] > 0
    # the lowered step must still execute afterwards
    hist = eng.fit(ds, epochs=1, batch_size=32)
    assert np.isfinite(hist["loss"][0])


def test_engine_tuner_picks_a_stage():
    strategy = auto.Strategy()
    strategy.tuning.enable = True
    strategy.tuning.verbose = False
    eng, _ = _engine(strategy=strategy)
    ds = _RandDS()
    best, results = eng.tune(ds.x[:32], ds.y[:32], candidates=(0, 2))
    assert best in (0, 2) and len(results) == 2
    hist = eng.fit(ds, epochs=1, batch_size=32)
    assert np.isfinite(hist["loss"][0])


def test_engine_predict_keeps_tail_batch():
    eng, _ = _engine()
    ds = _RandDS(n=100)  # 100 % 32 != 0: tail of 4 runs replicated
    eng.fit(ds, epochs=1, batch_size=32)
    preds = eng.predict(ds, batch_size=32)
    assert preds.shape == (100, 2)


def test_engine_second_engine_replans_its_own_strategy():
    engA, _ = _engine()
    engA.fit(_RandDS(), epochs=1, batch_size=32)  # publishes a dp-only mesh
    strategy = auto.Strategy()
    strategy.sharding.enable = True
    strategy.sharding.degree = 4
    engB, _ = _engine(strategy=strategy)
    assert engB._ensure_hcg().axis_size("sharding") == 4


def test_engine_save_load_roundtrip(tmp_path):
    eng, model = _engine()
    ds = _RandDS()
    eng.fit(ds, epochs=1, batch_size=32)
    path = str(tmp_path / "auto" / "ckpt")
    eng.save(path)

    paddle.seed(1)
    model2 = _mlp()
    opt2 = paddle.optimizer.Adam(learning_rate=0.05,
                                 parameters=model2.parameters())
    eng2 = auto.Engine(model2, F.cross_entropy, opt2)
    eng2.load(path)
    for p1, p2 in zip(model.parameters(), model2.parameters()):
        np.testing.assert_allclose(np.asarray(p1._array),
                                   np.asarray(p2._array), rtol=1e-6)
    # loaded engine keeps training
    hist = eng2.fit(ds, epochs=1, batch_size=32)
    assert np.isfinite(hist["loss"][0])


def test_engine_amp_o2_casts_weights():
    strategy = auto.Strategy()
    strategy.amp.enable = True
    eng, model = _engine(strategy=strategy)
    eng.fit(_RandDS(), epochs=1, batch_size=32)
    assert str(model.parameters()[0].dtype).endswith("bfloat16")


def test_strategy_roundtrip_and_validation():
    s = auto.Strategy({"sharding": {"enable": True, "stage": 3}})
    assert s.sharding.enable and s.sharding.stage == 3
    d = s.to_dict()
    assert d["sharding"]["stage"] == 3
    with pytest.raises(ValueError):
        auto.Strategy({"sharding": {"bogus_field": 1}})


def test_cost_does_not_advance_global_rng():
    from paddle_tpu.core import random as random_mod

    eng, _ = _engine()
    ds = _RandDS()
    state_before = random_mod._gen().get_state()
    eng.cost(ds.x[:32], ds.y[:32])
    state_after = random_mod._gen().get_state()
    assert state_before == state_after


def test_step_structured_pytree_inputs_preserved():
    """A list of equal-shape arrays is a pytree input, not a stack."""
    from paddle_tpu.distributed.spmd import _unwrap

    a = np.ones((4, 3), np.float32)
    out = _unwrap([a, a])
    assert isinstance(out, list) and len(out) == 2  # untouched pytree
    assert isinstance(_unwrap([np.int64(1), np.int64(0)]), np.ndarray)


def test_engine_gradient_merge():
    strategy = auto.Strategy()
    strategy.gradient_merge.enable = True
    strategy.gradient_merge.k_steps = 2
    eng, _ = _engine(strategy=strategy)
    ds = _RandDS()
    hist = eng.fit(ds, epochs=2, batch_size=16)  # 8 micro-steps/epoch
    assert hist["loss"][-1] < hist["loss"][0]
    assert eng._step.accumulate_steps == 2
    assert eng.optimizer._step_count == 8  # 16 micro / 2
