"""tpu-lint unit tests: per-rule fixtures (exact file:line), inline
suppressions, baseline round-trip, stable finding IDs, CLI output."""
import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

import paddle_tpu.analysis as A
from paddle_tpu.analysis.findings import assign_ids

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = Path(__file__).parent / "fixtures" / "tpu_lint"
LINT = os.path.join(REPO, "tools", "tpu_lint.py")


def analyze(name):
    findings, _mod = A.analyze_file(str(FIXTURES / name))
    return assign_ids(findings)


def hits(findings, rule):
    """(line, suppressed) pairs for one rule, in line order."""
    return [(f.line, f.suppressed) for f in findings if f.rule == rule]


# -- per-rule fixtures: >=1 positive and >=1 negative, exact lines --------

@pytest.mark.parametrize("rule,pos,neg,lines", [
    ("TPU001", "tpu001_pos.py", "tpu001_neg.py", [8, 9, 10, 16]),
    ("TPU002", "tpu002_pos.py", "tpu002_neg.py", [6, 16]),
    ("TPU003", "tpu003_pos.py", "tpu003_neg.py", [6, 13]),
    # the PR-15 sampling-step key-fold pattern: a folded per-slot key
    # consumed twice fires; fold_in-per-draw (ops/sampling.py) passes
    ("TPU003", "tpu003_sampling_pos.py", "tpu003_sampling_neg.py",
     [10]),
    ("TPU004", "tpu004_pos.py", "tpu004_neg.py", [8, 14]),
    ("TPU005", "tpu005_pos.py", "tpu005_neg.py", [10, 11]),
    ("TPU006", "tpu006_pos.py", "tpu006_neg.py", [3, 9]),
    ("TPU007", "tpu007_pos.py", "tpu007_neg.py", [8]),
    ("TPU008", "tpu008_pos.py", "tpu008_neg.py", [9]),
])
def test_rule_fixture(rule, pos, neg, lines):
    findings = analyze(pos)
    assert hits(findings, rule) == [(ln, False) for ln in lines], \
        [f.render() for f in findings]
    # the positive fixture must not trip OTHER rules (fixture isolation)
    assert {f.rule for f in findings} == {rule}
    neg_findings = analyze(neg)
    assert hits(neg_findings, rule) == [], \
        [f.render() for f in neg_findings]


def test_shard_map_bodies_are_traced_contexts():
    """ISSUE 8 satellite: a callable staged through
    `jax.experimental.shard_map.shard_map` is a traced context for the
    jit-reachability walker — host syncs (TPU001) and eager
    collectives (TPU007) inside the body are findings, while the
    mesh-level `jax.lax.psum`/`all_gather` the sharded serving engine
    actually uses never misfire."""
    findings = analyze("shard_map_pos.py")
    assert hits(findings, "TPU001") == [(6, False)], \
        [f.render() for f in findings]
    assert {f.rule for f in findings} == {"TPU001"}
    findings = analyze("shard_map_tpu007_pos.py")
    assert hits(findings, "TPU007") == [(8, False)], \
        [f.render() for f in findings]
    assert {f.rule for f in findings} == {"TPU007"}
    neg = analyze("shard_map_neg.py")
    assert not neg, [f.render() for f in neg]


def test_unparseable_file_is_reported_not_skipped():
    findings = analyze("unparseable.py")
    assert [f.rule for f in findings] == ["TPU000"]
    assert "unparseable" in findings[0].message


# -- suppressions ---------------------------------------------------------

def test_inline_suppression_same_line_only():
    findings = analyze("suppressed.py")
    assert hits(findings, "TPU005") == [(8, True), (14, False)]


# -- stable finding ids ---------------------------------------------------

def test_finding_ids_survive_line_shifts():
    src = (FIXTURES / "tpu003_pos.py").read_text()
    base, _ = A.analyze_file("k.py", src)
    assign_ids(base)
    shifted, _ = A.analyze_file("k.py", "# a comment\n\n" + src)
    assign_ids(shifted)
    assert [f.id for f in base] == [f.id for f in shifted]
    assert [f.line + 2 for f in base] == [f.line for f in shifted]


def test_finding_ids_change_when_the_hazard_line_changes():
    src = (FIXTURES / "tpu003_pos.py").read_text()
    base, _ = A.analyze_file("k.py", src)
    assign_ids(base)
    edited, _ = A.analyze_file(
        "k.py", src.replace("jax.random.uniform(key, (2,))",
                            "jax.random.uniform(key, (3,))"))
    assign_ids(edited)
    assert base[0].id != edited[0].id  # grandfathering invalidated


def test_tpu004_resolves_introspect_donation_constants():
    """The framework's own donation idiom — `donate_argnums=
    introspect.TRAINSTEP_DONATE_ARGNUMS if flag else ()`, possibly via
    a local variable — must stay visible to TPU004 (the analyzer reads
    the metadata, not a literal)."""
    src = (
        "import jax\n"
        "from paddle_tpu.jit import introspect\n"
        "def run(params, accums, bufs, x, flag, step_fn):\n"
        "    donate = introspect.TRAINSTEP_DONATE_ARGNUMS if flag "
        "else ()\n"
        "    step = jax.jit(step_fn, donate_argnums=donate)\n"
        "    out = step(params, accums, bufs, x)\n"
        "    return params\n")
    findings, _ = A.analyze_file("donate.py", src)
    assert [(f.rule, f.line) for f in findings] == [("TPU004", 7)], \
        [f.render() for f in findings]
    # direct keyword form, no intermediate variable
    src2 = (
        "import jax\n"
        "from paddle_tpu.jit import introspect\n"
        "def run(grads, x, acc_fn):\n"
        "    acc = jax.jit(acc_fn, "
        "donate_argnums=introspect.ACCUM_DONATE_ARGNUMS)\n"
        "    out = acc(grads, x)\n"
        "    return grads\n")
    findings2, _ = A.analyze_file("donate2.py", src2)
    assert [(f.rule, f.line) for f in findings2] == [("TPU004", 6)], \
        [f.render() for f in findings2]


def test_relative_imports_resolve_in_package_init():
    """A relative import in a package __init__.py resolves against the
    PACKAGE, not its parent — a TPU007 hazard reached through
    `from .collective import all_reduce` must not slip the gate."""
    src = ("import jax\n"
           "from .collective import all_reduce\n"
           "@jax.jit\n"
           "def step(x):\n"
           "    return all_reduce(x)\n")
    findings, _ = A.analyze_file(
        "paddle_tpu/distributed/__init__.py", src)
    assert [(f.rule, f.line) for f in findings] == [("TPU007", 5)], \
        [f.render() for f in findings]


def test_finding_ids_in_lambdas_survive_line_shifts():
    src = ("import jax, time\n"
           "f = jax.jit(lambda x: x + time.time())\n")
    base, _ = A.analyze_file("lam.py", src)
    assign_ids(base)
    assert [f.rule for f in base] == ["TPU005"]
    shifted, _ = A.analyze_file("lam.py", "# c\n# c\n" + src)
    assign_ids(shifted)
    assert [f.id for f in base] == [f.id for f in shifted]


# -- baseline round-trip --------------------------------------------------

def test_baseline_round_trip(tmp_path):
    res = A.analyze_paths([str(FIXTURES / "tpu001_pos.py")])
    assert len(res.new_findings()) == 4
    bpath = tmp_path / "baseline.json"
    A.write_baseline(str(bpath), res.new_findings())
    # skeleton entries have empty justifications: loader must refuse
    with pytest.raises(A.BaselineError, match="justification"):
        A.load_baseline(str(bpath))
    doc = json.loads(bpath.read_text())
    for e in doc["entries"]:
        e["justification"] = "test grandfathering"
    doc["entries"].append({"id": "TPU009:deadbeef00", "rule": "TPU009",
                           "path": "gone.py",
                           "justification": "stale on purpose"})
    bpath.write_text(json.dumps(doc))
    baseline = A.load_baseline(str(bpath))
    res2 = A.analyze_paths([str(FIXTURES / "tpu001_pos.py")],
                           baseline=baseline)
    assert res2.new_findings() == []
    assert sum(1 for f in res2.findings if f.baselined) == 4
    assert res2.stale_baseline == ["TPU009:deadbeef00"]


def test_baseline_accepts_bare_list_form(tmp_path):
    bpath = tmp_path / "list.json"
    bpath.write_text(json.dumps([
        {"id": "TPU001:0000000000",
         "justification": "list-form baseline entry for the loader"}]))
    baseline = A.load_baseline(str(bpath))
    assert "TPU001:0000000000" in baseline


# -- CLI ------------------------------------------------------------------

def _run_lint(args, cwd=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run([sys.executable, LINT] + args, env=env,
                          capture_output=True, text=True, timeout=300,
                          cwd=cwd)


def test_finding_ids_do_not_depend_on_cwd(tmp_path):
    """The committed baseline must hold from ANY invocation directory:
    paths in finding IDs are repo-root-relative, not cwd-relative."""
    res = _run_lint([os.path.join(REPO, "paddle_tpu", "core",
                                  "pylayer.py")], cwd=str(tmp_path))
    assert res.returncode == 0, res.stdout + res.stderr
    assert "2 baselined" in res.stdout


def test_cli_json_format_and_exit_code():
    res = _run_lint([str(FIXTURES / "tpu002_pos.py"),
                     "--baseline", "none", "--format", "json"])
    assert res.returncode == 1
    doc = json.loads(res.stdout)
    assert [f["line"] for f in doc["findings"]] == [6, 16]
    assert all(f["rule"] == "TPU002" for f in doc["findings"])
    assert doc["files"] == 1
    res = _run_lint([str(FIXTURES / "tpu002_neg.py"),
                     "--baseline", "none"])
    assert res.returncode == 0, res.stdout + res.stderr


def test_cli_stats_reports_counts_and_unparseable():
    res = _run_lint([str(FIXTURES), "--baseline", "none", "--stats"])
    assert res.returncode == 1
    out = res.stdout
    assert "files analyzed: 23" in out
    assert "UNPARSEABLE files: 1" in out
    assert "unparseable.py" in out
    # per-rule counts visible (no silent skips); the shard_map
    # fixtures add one TPU001 and one TPU007 hit
    # the PR-15 sampling fixtures add one TPU003 hit
    for rule, n in [("TPU001", 5), ("TPU002", 2), ("TPU003", 3),
                    ("TPU004", 2), ("TPU005", 4), ("TPU006", 2),
                    ("TPU007", 2), ("TPU008", 1)]:
        assert any(line.startswith(rule) and line.rstrip().endswith(str(n))
                   for line in out.splitlines()), (rule, n, out)
    assert "suppressed inline: 1" in out


def test_cli_list_rules_covers_all_eight():
    res = _run_lint(["--list-rules"])
    assert res.returncode == 0
    for rule in ["TPU00%d" % i for i in range(1, 9)]:
        assert rule in res.stdout
