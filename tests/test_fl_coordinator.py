"""FL coordinator (VERDICT r4 missing #5, built in r5): client
registry + per-round JOIN/WAIT/FINISH strategies + sample-weighted
FedAvg folding, over the rpc pickle-framed TCP transport.

Reference: python/paddle/distributed/ps/coordinator.py:1.
"""
import threading

import pytest

import numpy as np

from paddle_tpu.distributed.ps import (
    ClientSelector, ClientSelectorBase, Coordinator, FLClient, FLStrategy,
)
from paddle_tpu.distributed.ps.coordinator import ClientInfoAttr


def test_fedavg_weighted_fold_exact():
    coord = Coordinator({"w": np.zeros(2)},
                        selector=ClientSelector(max_rounds=1))
    try:
        c0 = FLClient(coord.endpoint, 0,
                      info={ClientInfoAttr.DEVICE_TYPE: "tpu"})
        c1 = FLClient(coord.endpoint, 1)
        s0, r0, g0 = c0.pull()
        assert s0 == FLStrategy.JOIN and r0 == 0
        np.testing.assert_allclose(g0["w"], [0, 0])
        # client 0: w=[1,1] with 30 samples; client 1: w=[4,0] with 10
        c0.push(0, {"w": np.array([1.0, 1.0])}, 30)
        c1.push(0, {"w": np.array([4.0, 0.0])}, 10)
        assert coord.wait_rounds(1) == 1
        np.testing.assert_allclose(coord.global_state["w"],
                                   [1.75, 0.75])   # (30*1+10*4)/40 ...
        # after max_rounds every client sees FINISH
        assert c0.pull()[0] == FLStrategy.FINISH
    finally:
        coord.close()


def test_fl_clients_converge_linear_regression():
    """3 clients with disjoint data shards learn w*=[2,-3] by FedAvg."""
    rng = np.random.RandomState(0)
    w_true = np.array([2.0, -3.0])
    shards = []
    for i in range(3):
        X = rng.randn(64, 2)
        shards.append((X, X @ w_true + 0.01 * rng.randn(64)))

    # min_clients gates the first round: a fast first client must not
    # complete rounds solo while its peers are still registering
    coord = Coordinator({"w": np.zeros(2)},
                        selector=ClientSelector(max_rounds=8),
                        min_clients=3)

    def make_train(X, y):
        def train(global_state):
            w = np.asarray(global_state["w"], np.float64).copy()
            for _ in range(5):
                grad = 2 * X.T @ (X @ w - y) / len(y)
                w -= 0.1 * grad
            return {"w": w}, len(y)
        return train

    try:
        threads, rounds = [], []
        for i, (X, y) in enumerate(shards):
            c = FLClient(coord.endpoint, i)
            t = threading.Thread(
                target=lambda c=c, f=make_train(X, y):
                rounds.append(c.run(f)))
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=120)
        assert coord.round_idx == 8
        assert rounds == [8, 8, 8]
        np.testing.assert_allclose(coord.global_state["w"], w_true,
                                   atol=0.05)
    finally:
        coord.close()


def test_custom_selector_wait_and_capability_info():
    """A selector can hold specific clients in WAIT using the
    registered capability info (the reference's selection hook)."""

    class OnlyFast(ClientSelectorBase):
        def __init__(self):
            self.rounds_seen = 0

        def select(self, clients_info, round_idx):
            if round_idx >= 1:
                return {c: FLStrategy.FINISH for c in clients_info}
            return {c: (FLStrategy.JOIN
                        if info.get(ClientInfoAttr.BANDWIDTH, 0) >= 100
                        else FLStrategy.WAIT)
                    for c, info in clients_info.items()}

    coord = Coordinator({"w": np.zeros(1)}, selector=OnlyFast())
    try:
        fast = FLClient(coord.endpoint, "fast",
                        info={ClientInfoAttr.BANDWIDTH: 1000})
        slow = FLClient(coord.endpoint, "slow",
                        info={ClientInfoAttr.BANDWIDTH: 1})
        assert slow.pull()[0] == FLStrategy.WAIT
        assert fast.pull()[0] == FLStrategy.JOIN
        fast.push(0, {"w": np.array([5.0])}, 10)
        assert coord.wait_rounds(1) == 1
        np.testing.assert_allclose(coord.global_state["w"], [5.0])
        assert slow.pull()[0] == FLStrategy.FINISH
    finally:
        coord.close()


def test_tree_index_structure_and_lookups(tmp_path):
    """index_dataset TreeIndex (reference index_wrapper.h): complete
    binary tree over 8 items, code arithmetic + travel/ancestor."""
    from paddle_tpu.distributed.ps import TreeIndex

    items = np.arange(100, 108, dtype=np.uint64)
    t = TreeIndex.from_items("demo", items, branch=2)
    assert t.height() == 4 and t.branch() == 2
    assert t.total_node_nums() == 15          # 8 + 4 + 2 + 1
    np.testing.assert_array_equal(t.get_all_leafs(), items)
    assert t.emb_size() > 107
    # leaves live at codes 7..14; item 100 -> code 7
    np.testing.assert_array_equal(t.get_layer_codes(3),
                                  np.arange(7, 15))
    np.testing.assert_array_equal(t.get_travel_codes(100), [7, 3, 1, 0])
    np.testing.assert_array_equal(t.get_travel_codes(107, 1), [14, 6, 2])
    np.testing.assert_array_equal(
        t.get_ancestor_codes([100, 107], 1), [1, 2])
    np.testing.assert_array_equal(
        t.get_children_codes(1, 3), [7, 8, 9, 10])
    assert t.get_pi_relation([100, 103], 2) == {100: 3, 103: 4}
    # save/load roundtrip (the reference's path ctor)
    path = str(tmp_path / "tree.pkl")
    t.save(path)
    t2 = TreeIndex("demo", path)
    np.testing.assert_array_equal(t2.get_travel_codes(100), [7, 3, 1, 0])


def test_tree_index_layerwise_sampling():
    from paddle_tpu.distributed.ps import TreeIndex

    items = np.arange(100, 108, dtype=np.uint64)
    t = TreeIndex.from_items("demo", items, branch=2)
    t.init_layerwise_sampler([1, 2, 3], start_sample_layer=1, seed=0)
    users = np.array([[0.5], [0.7]])
    targets = np.array([100, 107], np.uint64)
    u, nodes, labels = t.layerwise_sample(users, targets)
    # per pair: layer1 1+1, layer2 1+2, layer3 1+3 = 9 rows; 2 pairs
    assert len(labels) == 18
    assert labels.sum() == 6                   # 3 positives per pair
    # positives for item 100 are the ids at its travel codes
    pos_nodes = nodes[(labels == 1) & (u[:, 0] == 0.5)]
    want = t.get_nodes(t.get_travel_codes(100)[:-1])  # codes 7,3,1
    assert set(map(int, pos_nodes)) == set(map(int, want))


def test_min_clients_gate_and_light_poll():
    coord = Coordinator({"w": np.zeros(1)},
                        selector=ClientSelector(max_rounds=1),
                        min_clients=2)
    try:
        c0 = FLClient(coord.endpoint, 0)
        # cohort still assembling: WAIT, and poll_round ships no state
        assert c0.pull()[0] == FLStrategy.WAIT
        assert c0.poll_round() == (FLStrategy.WAIT, 0)
        c1 = FLClient(coord.endpoint, 1)
        assert c0.poll_round()[0] == FLStrategy.JOIN
        c0.push(0, {"w": np.array([2.0])}, 1)
        c1.push(0, {"w": np.array([4.0])}, 3)
        assert coord.wait_rounds(1) == 1
        np.testing.assert_allclose(coord.global_state["w"], [3.5])
    finally:
        coord.close()


def test_tree_index_validation():
    from paddle_tpu.distributed.ps import TreeIndex

    items = np.arange(4, dtype=np.uint64)
    with pytest.raises(ValueError, match="probabilities length"):
        TreeIndex.from_items("t", items, probabilities=[0.5, 0.5])
    t = TreeIndex.from_items("t", items)
    t.init_layerwise_sampler([1, 1])
    with pytest.raises(NotImplementedError, match="hierarchy"):
        t.layerwise_sample(np.zeros((1, 1)), items[:1],
                           with_hierarchy=True)


def test_waited_client_push_cannot_contaminate_round():
    """A stray push from a WAITed client must neither trigger the fold
    early nor enter the round's average."""

    class FastOnly(ClientSelectorBase):
        def select(self, clients_info, round_idx):
            if round_idx >= 1:
                return {c: FLStrategy.FINISH for c in clients_info}
            return {c: (FLStrategy.JOIN if c == "fast"
                        else FLStrategy.WAIT)
                    for c in clients_info}

    coord = Coordinator({"w": np.zeros(1)}, selector=FastOnly())
    try:
        fast = FLClient(coord.endpoint, "fast")
        slow = FLClient(coord.endpoint, "slow")
        slow.push(0, {"w": np.array([100.0])}, 1000)  # stray push
        assert coord.round_idx == 0                   # no early fold
        fast.push(0, {"w": np.array([5.0])}, 10)
        assert coord.wait_rounds(1) == 1
        # ONLY the joined client's update entered the average
        np.testing.assert_allclose(coord.global_state["w"], [5.0])
    finally:
        coord.close()


def test_zero_sample_push_participates_without_weight():
    """An empty-shard client's n_samples=0 push counts as round
    participation (no deadlock) but contributes nothing to the
    average; an all-zero round advances with the model unchanged."""
    coord = Coordinator({"w": np.zeros(1)},
                        selector=ClientSelector(max_rounds=2))
    try:
        c0 = FLClient(coord.endpoint, 0)
        c1 = FLClient(coord.endpoint, 1)
        c0.push(0, {"w": np.array([7.0])}, 10)
        c1.push(0, {"w": np.array([999.0])}, 0)   # empty shard
        assert coord.wait_rounds(1) == 1
        np.testing.assert_allclose(coord.global_state["w"], [7.0])
        # all-zero round: model stands, round still advances
        c0.push(1, {"w": np.array([1.0])}, 0)
        c1.push(1, {"w": np.array([2.0])}, 0)
        assert coord.wait_rounds(2) == 2
        np.testing.assert_allclose(coord.global_state["w"], [7.0])
    finally:
        coord.close()


def test_malformed_push_errors_client_not_round():
    """ADVICE r5 #4: a push whose keys/shapes don't match global_state
    errors AT PUSH TIME on the offending client; the round stays
    foldable for everyone else (no wedged poll loops)."""
    coord = Coordinator({"w": np.zeros(2), "b": np.zeros(1)},
                        selector=ClientSelector(max_rounds=1))
    try:
        good = FLClient(coord.endpoint, "good")
        bad = FLClient(coord.endpoint, "bad")
        with pytest.raises(ValueError, match="missing keys"):
            bad.push(0, {"w": np.ones(2)}, 5)            # 'b' absent
        with pytest.raises(ValueError, match="unknown keys"):
            bad.push(0, {"w": np.ones(2), "b": np.zeros(1),
                         "extra": np.ones(3)}, 5)
        with pytest.raises(ValueError, match="shape"):
            bad.push(0, {"w": np.ones(3), "b": np.zeros(1)}, 5)
        assert coord.round_idx == 0                      # nothing stored
        # the round folds normally once both clients push well-formed
        good.push(0, {"w": np.array([2.0, 4.0]), "b": np.ones(1)}, 10)
        bad.push(0, {"w": np.array([4.0, 8.0]), "b": np.ones(1)}, 10)
        assert coord.wait_rounds(1) == 1
        np.testing.assert_allclose(coord.global_state["w"], [3.0, 6.0])
    finally:
        coord.close()


def test_selector_wait_midround_then_join_next_round():
    """VERDICT r5 next #6: a selector WAITs a low-bandwidth client for
    round 0 (cohort gate + stray-push guard hold under selector-driven
    partitioning), then the waited client JOINs round 1 and its update
    enters that round's average."""

    class BandwidthGate(ClientSelectorBase):
        def select(self, clients_info, round_idx):
            if round_idx >= 2:
                return {c: FLStrategy.FINISH for c in clients_info}
            if round_idx == 0:
                return {c: (FLStrategy.JOIN
                            if info.get(ClientInfoAttr.BANDWIDTH, 0)
                            >= 100 else FLStrategy.WAIT)
                        for c, info in clients_info.items()}
            return {c: FLStrategy.JOIN for c in clients_info}

    coord = Coordinator({"w": np.zeros(1)}, selector=BandwidthGate(),
                        min_clients=2)
    try:
        fast = FLClient(coord.endpoint, "fast",
                        info={ClientInfoAttr.BANDWIDTH: 1000})
        slow = FLClient(coord.endpoint, "slow",
                        info={ClientInfoAttr.BANDWIDTH: 3})
        assert fast.poll_round() == (FLStrategy.JOIN, 0)
        assert slow.poll_round() == (FLStrategy.WAIT, 0)
        # stray push from the WAITed client mid-round 0: neither folds
        # the round early nor enters the average
        slow.push(0, {"w": np.array([500.0])}, 50)
        assert coord.round_idx == 0
        fast.push(0, {"w": np.array([8.0])}, 10)
        assert coord.wait_rounds(1) == 1
        np.testing.assert_allclose(coord.global_state["w"], [8.0])
        # round 1: the waited client JOINs and participates
        assert slow.poll_round() == (FLStrategy.JOIN, 1)
        fast.push(1, {"w": np.array([6.0])}, 10)
        slow.push(1, {"w": np.array([12.0])}, 30)
        assert coord.wait_rounds(2) == 2
        np.testing.assert_allclose(coord.global_state["w"], [10.5])
        assert slow.poll_round()[0] == FLStrategy.FINISH
    finally:
        coord.close()
