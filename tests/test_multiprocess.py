"""Real multi-process localhost distributed tests — the TestDistBase
pattern (python/paddle/fluid/tests/unittests/test_dist_base.py:899,
_run_cluster :1190): spawn 2 worker processes through the launcher CLI,
run collectives + a dp=2 DistributedTrainStep, and assert loss parity
against a single-process baseline.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "launch_worker.py")


def _launch(phase, out_file=None, nprocs=2, timeout=600):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # workers pick their own backend config via the launcher
    env.pop("PADDLE_TRAINER_ID", None)
    env.pop("PADDLE_TRAINERS_NUM", None)
    args = [sys.executable, "-m", "paddle_tpu.distributed.launch",
            "--nprocs", str(nprocs), "--backend", "cpu", WORKER, phase]
    if out_file:
        args.append(out_file)
    return subprocess.run(args, env=env, capture_output=True, text=True,
                          timeout=timeout)


def test_two_process_collectives():
    res = _launch("collectives")
    assert res.returncode == 0, f"stdout:\n{res.stdout}\nstderr:\n{res.stderr}"
    assert res.stdout.count("WORKER_DONE") == 2
    for name in ("all_reduce", "all_gather[1]", "broadcast", "reduce",
                 "scatter", "alltoall[1]", "reduce_scatter", "barrier"):
        assert f"ok {name}" in res.stdout, \
            f"missing 'ok {name}' in:\n{res.stdout}"


def test_two_process_ps_pull_push():
    res = _launch("ps")
    assert res.returncode == 0, f"stdout:\n{res.stdout}\nstderr:\n{res.stderr}"
    assert res.stdout.count("ok ps\n") == 2


def test_two_process_zero_sharding_parity(tmp_path):
    """ZeRO-2 across process boundaries matches a single-process
    baseline on the same global batches (multi-host group_sharded)."""
    out_file = str(tmp_path / "zero_losses.json")
    res = _launch("zero", out_file)
    assert res.returncode == 0, \
        f"stdout:\n{res.stdout}\nstderr:\n{res.stderr}"
    with open(out_file) as f:
        dist_losses = json.load(f)

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    import paddle_tpu.jit as jit

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(16, 64), nn.Tanh(), nn.Linear(64, 16))
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=net.parameters())
    step = jit.TrainStep(net, opt, F.mse_loss)
    rng = np.random.RandomState(7)
    base = []
    for _ in range(4):
        x = rng.randn(8, 16).astype(np.float32)
        y = rng.randn(8, 16).astype(np.float32)
        base.append(float(step(paddle.to_tensor(x), paddle.to_tensor(y))))
    np.testing.assert_allclose(dist_losses, base, rtol=1e-4, atol=1e-6)


def test_two_process_tensor_parallel_parity(tmp_path):
    """mp=2 across processes (cross-process partial-sum all-reduce)
    matches a replicated single-process run."""
    out_file = str(tmp_path / "mp_losses.json")
    res = _launch("mp", out_file)
    assert res.returncode == 0, \
        f"stdout:\n{res.stdout}\nstderr:\n{res.stderr}"
    with open(out_file) as f:
        dist_losses = json.load(f)

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    import paddle_tpu.jit as jit
    from paddle_tpu.distributed import (HybridCommunicateGroup,
                                        set_hybrid_communicate_group)

    set_hybrid_communicate_group(HybridCommunicateGroup())  # degree 1
    paddle.seed(0)

    class MPNet(nn.Layer):
        def __init__(self):
            super().__init__()
            self.col = dist.ColumnParallelLinear(16, 32,
                                                 gather_output=False)
            self.row = dist.RowParallelLinear(32, 16,
                                              input_is_parallel=True)

        def forward(self, x):
            return self.row(self.col(x))

    net = MPNet()
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=net.parameters())
    step = jit.TrainStep(net, opt, F.mse_loss)
    rng = np.random.RandomState(11)
    base = []
    for _ in range(4):
        x = rng.randn(8, 16).astype(np.float32)
        y = rng.randn(8, 16).astype(np.float32)
        base.append(float(step(paddle.to_tensor(x), paddle.to_tensor(y))))
    np.testing.assert_allclose(dist_losses, base, rtol=1e-4, atol=1e-6)


def test_two_process_pipeline_parity(tmp_path):
    """pp=2 across processes (shift-register collective-permute over the
    process fabric) matches the same model at pp=1."""
    out_file = str(tmp_path / "pp_losses.json")
    res = _launch("pp", out_file)
    assert res.returncode == 0, \
        f"stdout:\n{res.stdout}\nstderr:\n{res.stderr}"
    with open(out_file) as f:
        dist_losses = json.load(f)

    import paddle_tpu as paddle
    from paddle_tpu.distributed import (HybridCommunicateGroup,
                                        set_hybrid_communicate_group)
    from tests.pp_model import build_pp_model, run_pp_losses

    set_hybrid_communicate_group(HybridCommunicateGroup(pp=1))
    _, step = build_pp_model(num_stages=1)
    base = run_pp_losses(step, paddle)
    set_hybrid_communicate_group(HybridCommunicateGroup())
    np.testing.assert_allclose(dist_losses, base, rtol=1e-3, atol=1e-5)


def test_two_process_ep_and_cp_parity(tmp_path):
    """MoE expert-parallel forward and ring-attention context parallel
    with their axes across processes match single-process references."""
    out_file = str(tmp_path / "epcp.json")
    res = _launch("epcp", out_file)
    assert res.returncode == 0, \
        f"stdout:\n{res.stdout}\nstderr:\n{res.stderr}"
    with open(out_file) as f:
        got = json.load(f)

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import (HybridCommunicateGroup,
                                        set_hybrid_communicate_group)

    # ep baseline: same seed/weights at ep degree 1
    set_hybrid_communicate_group(HybridCommunicateGroup())
    paddle.seed(0)
    moe = dist.MoELayer(d_model=8, d_hidden=16, num_experts=4,
                        capacity_factor=4.0)
    x_np = np.random.RandomState(0).randn(2, 8, 8).astype(np.float32)
    want_moe = np.asarray(moe(paddle.to_tensor(x_np))._array)
    np.testing.assert_allclose(np.asarray(got["moe_out"], np.float32),
                               want_moe, rtol=1e-4, atol=1e-5)

    # cp baseline: dense causal attention; compare rank 0's seq shard
    from paddle_tpu.ops import nn_ops

    B, S, H, D = 1, 8, 2, 4
    rs = np.random.RandomState(1)
    q = rs.randn(B, S, H, D).astype(np.float32)
    k = rs.randn(B, S, H, D).astype(np.float32)
    v = rs.randn(B, S, H, D).astype(np.float32)
    dense = np.asarray(nn_ops.scaled_dot_product_attention(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        is_causal=True, dropout_p=0.0)._array)
    local = np.asarray(got["cp_local"], np.float32)
    s0 = got["cp_start"]
    np.testing.assert_allclose(
        local, dense[:, s0:s0 + local.shape[1]], rtol=1e-4, atol=1e-5)


def test_two_process_train_parity(tmp_path):
    out_file = str(tmp_path / "losses.json")
    res = _launch("train", out_file)
    assert res.returncode == 0, f"stdout:\n{res.stdout}\nstderr:\n{res.stderr}"
    with open(out_file) as f:
        dist_losses = json.load(f)

    # single-process baseline on the SAME global batches (dp=1)
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    import paddle_tpu.jit as jit

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 32), nn.Tanh(), nn.Linear(32, 4))
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=net.parameters())
    step = jit.TrainStep(net, opt, F.cross_entropy)
    rng = np.random.RandomState(42)
    base = []
    for _ in range(5):
        x = rng.uniform(-1, 1, (8, 8)).astype(np.float32)
        y = rng.randint(0, 4, (8,)).astype(np.int64)
        base.append(float(step(paddle.to_tensor(x), paddle.to_tensor(y))))

    np.testing.assert_allclose(dist_losses, base, rtol=1e-4, atol=1e-5)


def test_launcher_propagates_failure(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import sys; sys.exit(3)\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    res = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nprocs", "2", "--backend", "cpu", str(bad)],
        env=env, capture_output=True, text=True, timeout=120)
    assert res.returncode == 3


def test_two_process_localsgd():
    """LocalSGD: per-rank local steps on different data, periodic
    parameter averaging — ranks converge to identical params at every
    sync boundary (localsgd_optimizer.py dygraph analog)."""
    res = _launch("localsgd")
    assert res.returncode == 0, f"stdout:\n{res.stdout}\nstderr:\n{res.stderr}"
    assert res.stdout.count("ok localsgd\n") == 2
    assert res.stdout.count("ok localsgd_params_equal") == 2


def test_two_node_simulation():
    """VERDICT r3 missing #7: --nnodes/--nprocs-per-node are distinct —
    a simulated 2x2 job derives rank from (node_rank, local_rank) and
    runs a collective across the 4-rank world."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PADDLE_TRAINER_ID", None)
    env.pop("PADDLE_TRAINERS_NUM", None)
    res = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nnodes", "2", "--nprocs-per-node", "2", "--backend", "cpu",
         WORKER, "twonode"],
        env=env, capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, \
        f"stdout:\n{res.stdout}\nstderr:\n{res.stderr}"
    assert res.stdout.count("WORKER_DONE") == 4
    for node in (0, 1):
        for local in (0, 1):
            assert (f"ok twonode node={node} local={local} "
                    f"rank={node * 2 + local} world=4") in res.stdout, \
                res.stdout


def test_two_process_p2p_send_recv():
    """Host p2p send/recv + batch_isend_irecv over rpc (VERDICT r3 weak
    #4 — the batch_isend_irecv reference surface)."""
    res = _launch("p2p")
    assert res.returncode == 0, \
        f"stdout:\n{res.stdout}\nstderr:\n{res.stderr}"
    assert res.stdout.count("ok p2p") == 2


def test_rpc_master_port_is_job_private():
    """r4 VERDICT weak #4: the rpc rendezvous endpoint is a probed-free
    job-private port (PADDLE_RPC_MASTER), not coordinator+1 — so
    concurrent jobs in the full suite can't collide. The fallback
    convention survives for explicit-master multi-host launches."""
    from paddle_tpu.distributed.spawn import rank_env_overrides

    env = rank_env_overrides(0, 2, "127.0.0.1:5000",
                             rpc_master="127.0.0.1:6001")
    assert env["PADDLE_RPC_MASTER"] == "127.0.0.1:6001"
    senv = rank_env_overrides(0, 2, "127.0.0.1:5000", nservers=1,
                              server_rank=0,
                              rpc_master="127.0.0.1:6001")
    assert senv["PADDLE_RPC_MASTER"] == "127.0.0.1:6001"
    # without the probe the key is emitted as None = UNSET, so a stale
    # endpoint from an enclosing job can't leak into the ranks and the
    # coordinator+1 convention applies
    assert rank_env_overrides(0, 2, "127.0.0.1:5000")[
        "PADDLE_RPC_MASTER"] is None
