"""Profiler scheduler + aggregated statistics (VERDICT r4 missing #4):
make_scheduler drives CLOSED/READY/RECORD cycling across steps, and
summary() aggregates spans per name with calls/total/avg/max plus
device-time attribution from sync-timed op spans.

Reference: python/paddle/profiler/profiler.py:344 (scheduler states),
profiler_statistic.py (summary tables, SortedKeys).
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.profiler as profiler
from paddle_tpu.profiler import ProfilerState, SortedKeys


def test_make_scheduler_state_cycle():
    sched = profiler.make_scheduler(closed=1, ready=1, record=2,
                                    repeat=2, skip_first=1)
    want = [ProfilerState.CLOSED,             # skip_first
            ProfilerState.CLOSED, ProfilerState.READY,
            ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN,
            ProfilerState.CLOSED, ProfilerState.READY,
            ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN,
            ProfilerState.CLOSED, ProfilerState.CLOSED]  # repeat done
    assert [sched(i) for i in range(len(want))] == want


def test_scheduler_gates_recording_across_steps():
    """Only the RECORD windows of the cycle collect op spans."""
    sched = profiler.make_scheduler(closed=1, ready=1, record=2,
                                    repeat=1)
    prof = profiler.Profiler(scheduler=sched)
    prof.start()
    per_step_ops = []
    for step in range(5):
        before = _op_event_count(prof)
        x = paddle.to_tensor(np.ones((4, 4), np.float32))
        (x * 2 + 1).sum()
        prof.step()
        per_step_ops.append(_op_event_count(prof) - before)
    prof.stop()
    # steps 0 (CLOSED) and 1 (READY) record nothing; steps 2-3 RECORD
    assert per_step_ops[0] == 0 and per_step_ops[1] == 0
    assert per_step_ops[2] > 0 and per_step_ops[3] > 0
    assert per_step_ops[4] == 0  # cycle exhausted (repeat=1)


def _op_event_count(prof):
    from paddle_tpu.profiler.profiler import _recorder

    return sum(1 for e in prof._events + _recorder.events
               if e.get("cat") in ("op", "device"))


def test_summary_aggregates_ops_with_stats(capsys):
    prof = profiler.Profiler()
    prof.start()
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(8, 8).astype(np.float32))
    with profiler.RecordEvent("user_block"):
        for _ in range(3):
            y = paddle.matmul(x, x)
    _ = y.numpy()
    prof.stop()
    data = prof.summary()
    printed = capsys.readouterr().out
    # per-op aggregation with counts
    assert "matmul" in data.op_items
    it = data.op_items["matmul"]
    assert it.call == 3
    assert it.cpu_time >= it.max_cpu_time > 0
    assert abs(it.avg_cpu_time - it.cpu_time / 3) < 1e-9
    # user annotation lands in its own section
    assert "user_block" in data.user_items
    assert "Operator summary" in printed and "Calls" in printed


def test_summary_device_attribution_with_tpu_target():
    prof = profiler.Profiler(targets=[profiler.ProfilerTarget.CPU,
                                      profiler.ProfilerTarget.TPU])
    prof.start()
    x = paddle.to_tensor(np.random.RandomState(1)
                         .randn(16, 16).astype(np.float32))
    for _ in range(2):
        x = paddle.tanh(x)
    prof.stop()
    data = prof.summary(sorted_by=SortedKeys.DeviceTotal)
    it = data.op_items["tanh"]
    assert it.call == 2
    assert it.device_time > 0          # sync-timed spans
    assert it.cpu_time == 0            # all attribution is device-side


def test_sorted_keys_order():
    from paddle_tpu.profiler.profiler_statistic import (
        EventItem, StatisticData,
    )

    events = [
        {"name": "a", "dur": 1000, "cat": "op"},
        {"name": "b", "dur": 5000, "cat": "op"},
        {"name": "b", "dur": 100, "cat": "op"},
    ]
    data = StatisticData(events)
    by_total = [i.name for i in data.sorted_ops(SortedKeys.CPUTotal)]
    assert by_total == ["b", "a"]      # 5.1ms vs 1ms
    by_max = [i.name for i in data.sorted_ops(SortedKeys.CPUMax)]
    assert by_max == ["b", "a"]
    by_min = [i.name for i in data.sorted_ops(SortedKeys.CPUMin)]
    assert by_min == ["b", "a"]        # min 0.1ms sorts ascending-first


def test_span_hook_removed_after_stop():
    from paddle_tpu.ops.dispatch import OpStats

    prof = profiler.Profiler()
    prof.start()
    assert OpStats.span_hook is not None
    prof.stop()
    assert OpStats.span_hook is None and OpStats.sync_spans is False


def test_on_trace_ready_fires_once_per_cycle(tmp_path):
    fired = []
    sched = profiler.make_scheduler(closed=0, ready=0, record=2,
                                    repeat=1)
    prof = profiler.Profiler(scheduler=sched,
                             on_trace_ready=lambda p: fired.append(1))
    prof.start()
    for _ in range(3):
        paddle.to_tensor(np.ones(2, np.float32)).sum()
        prof.step()
    prof.stop()  # handler already ran when the cycle closed
    assert len(fired) == 1


def test_traced_ops_not_attributed_to_device():
    """block_until_ready is a no-op on tracers — trace-time dispatches
    must land in the host column, not pollute device attribution."""
    import paddle_tpu.jit as jit

    prof = profiler.Profiler(targets=[profiler.ProfilerTarget.TPU])
    prof.start()
    fn = jit.to_static(lambda a: paddle.tanh(a) * 2)
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    fn(x)  # first call traces: ops dispatch on Tracer arrays
    prof.stop()
    data = prof.summary()
    tanh = data.op_items.get("tanh")
    assert tanh is not None and tanh.call >= 1
    assert tanh.device_time == 0, "trace-time span tagged as device"
    assert tanh.cpu_time > 0  # recorded, as a host span
