"""Pure static-geometry contracts of the Pallas conv suite
(ISSUE 16): the dInput row-grid rounding table, the train-mode
tileability gate over every 3x3 geometry ResNet-50 actually runs
(the contract behind the 52/52 fused-dispatch count), the H-tile
divisor invariant, and the padding-normalization conventions. No
jit, no kernels — these pin the gate logic the training seam and
`tests/test_pallas_conv_bwd.py` rely on."""
import pytest

import paddle_tpu.ops.pallas.conv as C
from paddle_tpu.ops.pallas.conv import conv_train_geometry_tileable


@pytest.mark.parametrize("ho,expected", [
    (1, 0), (8, 0), (16, 0), (120, 0), (128, 0),   # natural tilings
    (17, 7), (29, 3), (58, 6),                     # prime-ish -> next 8
    (126, 2), (127, 1),                            # at the ceiling
    (130, None), (133, None),                      # past 128: dense
])
def test_dx_row_rounding_table(ho, expected):
    """The dInput walk's row-grid round-up: 0 when the natural count
    already tiles within the 16-tile unroll bound, else zero-rows up
    to the next multiple of 8, None past the 128-row ceiling."""
    assert C._dx_row_rounding(ho) == expected


@pytest.mark.parametrize("ho", [1, 2, 7, 12, 17, 24, 56, 58, 128])
def test_pick_h_tile_is_largest_divisor_leq_8(ho):
    th = C._pick_h_tile(ho)
    assert 1 <= th <= 8 and ho % th == 0
    assert not any(ho % d == 0 for d in range(th + 1, 9))


@pytest.mark.parametrize("hw,cin,cout,s", [
    (56, 64, 64, 1),     # layer1 3x3
    (56, 128, 128, 2),   # layer2 downsampling 3x3
    (28, 128, 128, 1),
    (28, 256, 256, 2),   # layer3 downsampling 3x3
    (14, 256, 256, 1),
    (14, 512, 512, 2),   # layer4 downsampling 3x3
    (7, 512, 512, 1),
    (32, 32, 32, 1),     # CIFAR-ish small inputs
    (16, 32, 32, 2),
])
def test_resnet50_3x3_geometries_all_train_tileable(hw, cin, cout, s):
    """Every 3x3 geometry a 224- or 32-input resnet50 actually runs
    must pass the TRAIN gate — this is the fusability contract the
    52/52 dispatch count in the train-step test rests on."""
    assert conv_train_geometry_tileable(3, s, 1, in_hw=(hw, hw),
                                        in_channels=cin,
                                        out_channels=cout)


@pytest.mark.parametrize("hw,s", [(34, 1), (130, 1), (129, 1)])
def test_untileable_3x3_geometries_gate_false(hw, s):
    """Row grids with no divisor <= 8 inside the unroll bound and no
    round-up inside the 128-row ceiling must gate False (the block
    seam then trains dense)."""
    assert not conv_train_geometry_tileable(3, s, 1, in_hw=(hw, hw),
                                            in_channels=8,
                                            out_channels=8)


@pytest.mark.parametrize("k,s", [(1, 1), (1, 2)])
def test_1x1_family_always_train_tileable(k, s):
    for hw in (1, 2, 7, 56, 224, 1024):
        assert conv_train_geometry_tileable(k, s, 0, in_hw=(hw, hw))


@pytest.mark.parametrize("padding,kernel,stride,in_hw,expected", [
    (0, 3, 1, None, ((0, 0), (0, 0))),
    (1, 3, 1, None, ((1, 1), (1, 1))),
    ((1, 2), 3, 1, None, ((1, 1), (2, 2))),
    ((1, 2, 3, 4), 3, 1, None, ((1, 2), (3, 4))),
    (((0, 1), (2, 3)), 3, 1, None, ((0, 1), (2, 3))),
    ("VALID", 3, 1, None, ((0, 0), (0, 0))),
    ("SAME", 3, 1, (16, 16), ((1, 1), (1, 1))),
    ("SAME", 3, 2, (16, 16), ((0, 1), (0, 1))),
])
def test_normalize_conv_padding_conventions(padding, kernel, stride,
                                            in_hw, expected):
    assert C.normalize_conv_padding(padding, kernel, stride,
                                    in_hw=in_hw) == expected


@pytest.mark.parametrize("bad", ["SAME", "circular", (1, 2, 3)])
def test_normalize_conv_padding_rejects(bad):
    # "SAME" without in_hw, unknown strings, and odd-length tuples
    with pytest.raises(ValueError):
        C.normalize_conv_padding(bad, 3, 2, in_hw=None)
