"""Custom-op toolchain tests (SURVEY §2.4 custom-op toolchain row;
reference python/paddle/utils/cpp_extension/ + custom_operator.cc):
g++-compiled C++ host ops through pure_callback with custom VJP, the
device-side custom_op decorator, and the setup.py tier shims.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.utils import cpp_extension

CPP_SRC = r"""
#include "paddle_ext.h"
#include <algorithm>

// relu6(x) = min(max(x, 0), 6)
PT_EXPORT void relu6_f32(const float* x, float* y, int64_t n) {
  for (int64_t i = 0; i < n; ++i)
    y[i] = std::min(std::max(x[i], 0.0f), 6.0f);
}

PT_EXPORT void relu6_grad_f32(const float* x, const float* gy, float* gx,
                              int64_t n) {
  for (int64_t i = 0; i < n; ++i)
    gx[i] = (x[i] > 0.0f && x[i] < 6.0f) ? gy[i] : 0.0f;
}

PT_EXPORT void square_f32(const float* x, float* y, int64_t n) {
  for (int64_t i = 0; i < n; ++i) y[i] = x[i] * x[i];
}
"""


@pytest.fixture(scope="module")
def lib(tmp_path_factory):
    d = tmp_path_factory.mktemp("ext")
    src = os.path.join(str(d), "my_ops.cc")
    with open(src, "w") as f:
        f.write(CPP_SRC)
    return cpp_extension.load("my_ops", [src],
                              build_directory=str(d / "build"))


def test_cpp_elementwise_forward(lib):
    relu6 = lib.wrap_elementwise("relu6_f32", backward="relu6_grad_f32")
    x = np.array([-1.0, 0.5, 3.0, 7.0], np.float32)
    y = relu6(paddle.to_tensor(x))
    np.testing.assert_allclose(y.numpy(), np.clip(x, 0, 6), rtol=1e-6)


def test_cpp_elementwise_gradient(lib):
    relu6 = lib.wrap_elementwise("relu6_f32", backward="relu6_grad_f32")
    x = paddle.to_tensor(np.array([-1.0, 0.5, 3.0, 7.0], np.float32))
    x.stop_gradient = False
    relu6(x).sum().backward()
    np.testing.assert_allclose(
        x.grad.numpy(), np.array([0.0, 1.0, 1.0, 0.0], np.float32))


def test_cpp_elementwise_under_jit(lib):
    """pure_callback survives jit tracing (XLA host callback)."""
    from paddle_tpu import jit

    relu6 = lib.wrap_elementwise("relu6_f32", backward="relu6_grad_f32")

    @jit.to_static
    def f(x):
        return relu6(x) * 2.0

    x = np.array([-2.0, 1.0, 8.0], np.float32)
    out = f(paddle.to_tensor(x))
    np.testing.assert_allclose(out.numpy(), np.clip(x, 0, 6) * 2, rtol=1e-6)


def test_cpp_forward_only_op_stops_gradient(lib):
    sq = lib.wrap_elementwise("square_f32")
    x = paddle.to_tensor(np.array([2.0, 3.0], np.float32))
    x.stop_gradient = False
    y = sq(x)
    np.testing.assert_allclose(y.numpy(), [4.0, 9.0])
    assert y.stop_gradient  # no backward symbol -> non-differentiable


def test_custom_op_decorator_with_custom_vjp():
    """Straight-through estimator: forward rounds, backward passes
    gradients through — the custom grad must win in eager AND jit."""
    import jax.numpy as jnp

    from paddle_tpu import jit
    from paddle_tpu.utils.cpp_extension import custom_op

    @custom_op(name="ste_round",
               fwd=lambda a: (jnp.round(a), None),
               bwd=lambda res, ct: (ct,))
    def ste_round(a):
        return jnp.round(a)

    x = paddle.to_tensor(np.array([0.4, 1.6], np.float32))
    x.stop_gradient = False
    ste_round(x).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [1.0, 1.0])  # not 0

    @jit.to_static
    def f(x):
        return ste_round(x).sum()

    # under jit the custom vjp must also survive (PyLayer ADVICE r2 bug
    # class); check via jax.grad through the traced program
    x2 = paddle.to_tensor(np.array([0.4, 1.6], np.float32))
    x2.stop_gradient = False
    f(x2).backward()
    np.testing.assert_allclose(x2.grad.numpy(), [1.0, 1.0])


def test_custom_op_plain():
    import jax

    from paddle_tpu.utils.cpp_extension import custom_op

    @custom_op()
    def swiglu(a, b):
        return a * jax.nn.sigmoid(a) * b

    a = np.random.RandomState(0).randn(4, 8).astype(np.float32)
    b = np.random.RandomState(1).randn(4, 8).astype(np.float32)
    out = swiglu(paddle.to_tensor(a), paddle.to_tensor(b))
    ref = a * (1 / (1 + np.exp(-a))) * b
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)
    # differentiable through normal AD
    at = paddle.to_tensor(a)
    at.stop_gradient = False
    swiglu(at, paddle.to_tensor(b)).sum().backward()
    assert at.grad is not None


def test_cuda_extension_points_to_pallas():
    with pytest.raises(NotImplementedError, match="Pallas"):
        cpp_extension.CUDAExtension("x", ["y.cu"])


def test_cpp_extension_setuptools_shim():
    ext = cpp_extension.CppExtension("my_ext", [])
    assert cpp_extension.get_include() in ext.include_dirs


def test_build_cache_skips_recompile(lib, tmp_path):
    """Loading the same unchanged sources reuses the built .so."""
    so = lib.so_path
    mtime = os.path.getmtime(so)
    lib2 = cpp_extension.load("my_ops", [os.path.join(
        os.path.dirname(os.path.dirname(so)), "my_ops.cc")],
        build_directory=os.path.dirname(so))
    assert os.path.getmtime(lib2.so_path) == mtime


def test_wrap_elementwise_rejects_wrong_dtype(lib):
    relu6 = lib.wrap_elementwise("relu6_f32", backward="relu6_grad_f32")
    with pytest.raises(TypeError, match="float32"):
        relu6(paddle.to_tensor(np.array([1, 2], np.int32)))


def test_build_flags_are_part_of_cache_key(lib):
    src = os.path.join(os.path.dirname(os.path.dirname(lib.so_path)),
                       "my_ops.cc")
    lib2 = cpp_extension.load("my_ops", [src],
                              build_directory=os.path.dirname(lib.so_path),
                              extra_cflags=["-DSOMETHING"])
    assert lib2.so_path != lib.so_path  # different flags, different binary
