"""Memory introspection (VERDICT r3 missing #3) — analog of
paddle/fluid/memory/stats.h and python/paddle/device/cuda
max_memory_allocated. On the CPU test backend PJRT publishes no
allocator stats, so the live-array accounting path is what's exercised
— same fallback the axon TPU tunnel uses."""
import numpy as np

import paddle_tpu as paddle


def test_memory_allocated_tracks_live_arrays():
    from paddle_tpu import device

    base = device.memory_allocated()
    big = paddle.to_tensor(np.ones((256, 1024), np.float32))
    after = device.memory_allocated()
    assert after >= base + 1024 * 1024, (base, after)
    del big


def test_max_memory_allocated_high_water():
    from paddle_tpu import device

    device.reset_peak_memory_stats()
    t = paddle.to_tensor(np.ones((512, 1024), np.float32))
    peak_with = device.max_memory_allocated()
    assert peak_with >= 2 * 1024 * 1024
    del t
    # after freeing, current drops but the peak stays
    assert device.max_memory_allocated() >= peak_with
    assert device.memory_allocated() < peak_with


def test_memory_stats_shape():
    from paddle_tpu import device

    st = device.memory_stats()
    assert st["source"] in ("pjrt", "live_arrays")
    for k in ("allocated_bytes", "peak_allocated_bytes",
              "reserved_bytes", "peak_reserved_bytes"):
        assert isinstance(st[k], int), st


def test_program_memory_from_compiled():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.device.memory import program_memory

    def f(x):
        return jnp.tanh(x @ x.T).sum()

    compiled = jax.jit(f).lower(jnp.ones((128, 64))).compile()
    pm = program_memory(compiled)
    # CPU backends may not report; when they do, sizes must be sane
    if pm["argument_bytes"] is not None:
        assert pm["argument_bytes"] >= 128 * 64 * 4
    assert set(pm) == {"argument_bytes", "output_bytes", "temp_bytes",
                      "generated_code_bytes", "total_bytes"}
