"""Quantized serving (ISSUE 11): int8 per-block-scaled KV cache +
int8 weights through the backend seam.

The contract, proven the way PR 6/7/8 proved theirs:

- `kv_dtype='int8'` (engine arg + PADDLE_SERVE_KV_DTYPE env) serves
  the standard mixed trace TOKEN-PARITY-WITHIN-TOLERANCE vs the fp
  engine across {dense, pallas} x {chunked cold + warm, bucketed} x
  K in {0, 4} x mp in {1, 2} — and the int8 engine is token-IDENTICAL
  across mesh shapes (the per-block grids are pmax-folded, so mp=2
  quantizes on mp=1's exact grid);
- the fp path stays BIT-identical to pre-PR behavior (the fp engine
  still matches the `generate(use_cache=True)` oracle exactly);
- `decode_traces == 1` per (backend, K, mp, kv_dtype);
- int8 pool bytes (codes + scales) <= 0.55x the fp16/bf16 pool — the
  capacity claim, measurable on CPU;
- COW byte-identity and read-only prefix-block seating under int8:
  shared quantized blocks AND their scales are never mutated by a
  borrower (dense_gather_reference, both backends, mp in {1, 2});
- int8 weights (`weight_dtype='int8'` / engine.quantize_weights())
  ride the compiled steps as (codes, per-channel scale) pairs and
  dequantize inside the step; refresh_weights() requantizes.

Tolerance budget (documented here and in README "Quantized
serving"): greedy token streams must match the fp engine on >= 90%
of tokens over the standard mixed trace (INT8_TOKEN_PARITY_MIN in
bench_ops.py — the bench row enforces the same number), and the
dequantized KV rows must reconstruct the fp rows within 2% of each
block's absmax (the per-block int8 grid's resolution is absmax/127
~= 0.8%; 2% leaves headroom for the write-then-attend feedback).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.jit as jit
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.inference import GenerationEngine

VOCAB = 64
TOKEN_PARITY_MIN = 0.90       # the documented budget (see docstring)


def _model(seed=0):
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(seed)
    cfg = GPTConfig.tiny(vocab=VOCAB, hidden=32, layers=2, heads=4,
                         seq=64)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return _model()


def _reference(model, prompt, max_new):
    out = model.generate(
        Tensor._wrap(np.asarray(prompt, np.int32)[None]),
        max_length=len(prompt) + max_new, use_cache=True)
    return list(map(int, np.asarray(out._array)[0]))


def _mixed_trace(rng, n=4):
    """The standard mixed trace: mixed lengths + a hot shared prefix
    + a block-aligned full-prefix hit (block_size 4)."""
    reqs = [(rng.randint(0, VOCAB, rng.randint(2, 13)).astype(np.int32),
             int(rng.randint(2, 7))) for _ in range(n)]
    shared = rng.randint(0, VOCAB, 8).astype(np.int32)
    reqs += [(np.concatenate([shared, rng.randint(0, VOCAB, 3)])
              .astype(np.int32), 4),
             (shared.copy(), 4)]
    return reqs


def _run_trace(eng, reqs, midrun=True):
    ids = [eng.add_request(p, n) for p, n in reqs[:len(reqs) // 2]]
    if midrun:
        for _ in range(2):
            eng.step()
    ids += [eng.add_request(p, n) for p, n in reqs[len(reqs) // 2:]]
    out = eng.run()
    return [list(map(int, out[rid])) for rid in ids]


def _match_fraction(ref, got):
    from bench_ops import _token_match_fraction

    return _token_match_fraction(ref, got)


# ---------------------------------------------------------------------------
# tentpole: tolerance parity across the whole quantized serving matrix
# ---------------------------------------------------------------------------

def _assert_quantized_matrix(model, backend, K, full=False):
    """One mixed trace served fp (anchored bit-exact to the generate
    oracle — the fp path must be byte-for-byte pre-PR) and int8 at
    mp=1 and mp=2 in (a) chunked cold, (b) same engine warm, (c)
    legacy bucketed — int8 within the tolerance budget vs fp per
    mode, int8 mp=2 token-IDENTICAL to int8 mp=1, decode_traces==1
    per configuration."""
    rng = np.random.RandomState(11)
    reqs = _mixed_trace(rng)

    def serve(mp, kv, bucketed=True):
        def mk(**kw):
            quant = dict(kv_dtype="int8", weight_dtype="int8") \
                if kv else {}
            return GenerationEngine(model, num_slots=3, block_size=4,
                                    num_blocks=64, spec_decode_k=K,
                                    attention_backend=backend,
                                    mp_degree=mp, **quant, **kw)

        eng = mk(prefill_chunk=8)
        out = [_run_trace(eng, reqs),
               _run_trace(eng, reqs, midrun=False)]   # hot cache
        engines = [eng]
        if bucketed:
            eng_b = mk(prefill_buckets=(16, 64))
            out.append(_run_trace(eng_b, reqs))
            engines.append(eng_b)
        assert eng.prefix_hit_tokens > 0
        for e in engines:
            assert e.decode_traces == 1, \
                f"mp={mp} {backend} K={K} kv={e.kv_dtype}: retraced"
        return out

    fp = serve(None, kv=False)
    # fp path bit-identical to pre-PR: still exactly the oracle
    p, n = reqs[0]
    assert fp[0][0] == _reference(model, p, n)
    q1 = serve(None, kv=True)
    # tolerance parity vs fp, per serving mode
    for mode, ref, got in zip(("cold", "warm", "bucketed"), fp, q1):
        frac = _match_fraction(ref, got)
        assert frac >= TOKEN_PARITY_MIN, \
            (f"{backend} K={K} {mode}: int8 matched only {frac:.3f} "
             f"of fp tokens (budget {TOKEN_PARITY_MIN})")
    # int8 across mesh shapes is EXACT (pmax-folded global grids);
    # tier-1 proves the chunked cold+warm legs, the slow-marked
    # full-matrix test adds the bucketed mp=2 cells
    q2 = serve(2, kv=True, bucketed=full)
    assert q2 == (q1 if full else q1[:2]), \
        f"{backend} K={K}: int8 mp=2 diverged from int8 mp=1"


def test_quantized_tolerance_parity_matrix(model, monkeypatch):
    """THE acceptance gate, tier-1 cut: the (dense, K=0) cell across
    mp in {1, 2} x {chunked cold, warm, bucketed} plus the lean
    pallas/K=4 probe below; the remaining (backend, K) cells run in
    the slow-marked full-matrix test — the test_engine_sharded
    precedent for keeping the timed tier-1 window bounded."""
    monkeypatch.delenv("PADDLE_SERVE_KV_DTYPE", raising=False)
    monkeypatch.delenv("PADDLE_SERVE_WEIGHT_DTYPE", raising=False)
    monkeypatch.delenv("PADDLE_SERVE_MP", raising=False)
    monkeypatch.delenv("PADDLE_SPEC_DECODE_K", raising=False)
    monkeypatch.delenv("PADDLE_PAGED_ATTENTION_BACKEND", raising=False)
    _assert_quantized_matrix(model, "dense", 0)


def test_quantized_pallas_spec_decode_tolerance(model, monkeypatch):
    """Lean tier-1 probe for the (pallas, K=4) cell: the int8 verify
    kernel serves the mixed trace cold + warm within the tolerance
    budget vs the fp reference (fp tokens are backend- and
    K-invariant by the PR 3/7 exactness contracts, so the dense fp
    K=0 stream is the oracle here too)."""
    monkeypatch.delenv("PADDLE_SERVE_KV_DTYPE", raising=False)
    monkeypatch.delenv("PADDLE_SPEC_DECODE_K", raising=False)
    monkeypatch.delenv("PADDLE_PAGED_ATTENTION_BACKEND", raising=False)
    rng = np.random.RandomState(11)
    reqs = _mixed_trace(rng)

    def serve(**kw):
        eng = GenerationEngine(model, num_slots=3, block_size=4,
                               num_blocks=64, prefill_chunk=8, **kw)
        out = [_run_trace(eng, reqs),
               _run_trace(eng, reqs, midrun=False)]
        return out, eng

    fp, _ = serve()
    q, eng = serve(kv_dtype="int8", weight_dtype="int8",
                   attention_backend="pallas", spec_decode_k=4)
    assert eng.decode_traces == 1
    for mode, ref, got in zip(("cold", "warm"), fp, q):
        frac = _match_fraction(ref, got)
        assert frac >= TOKEN_PARITY_MIN, \
            (f"pallas K=4 {mode}: int8 matched only {frac:.3f} of fp "
             f"tokens (budget {TOKEN_PARITY_MIN})")


@pytest.mark.slow
@pytest.mark.parametrize("backend,K", [("pallas", 4), ("dense", 4),
                                       ("pallas", 0)])
def test_quantized_tolerance_parity_full_matrix(model, monkeypatch,
                                                backend, K):
    monkeypatch.delenv("PADDLE_SERVE_KV_DTYPE", raising=False)
    monkeypatch.delenv("PADDLE_SERVE_WEIGHT_DTYPE", raising=False)
    monkeypatch.delenv("PADDLE_SERVE_MP", raising=False)
    _assert_quantized_matrix(model, backend, K, full=True)


def test_quantized_backends_agree_token_for_token(model, monkeypatch):
    """dense-int8 and pallas-int8 share one quantization policy and
    one operation order — their token streams must be identical, not
    merely both-within-tolerance."""
    monkeypatch.delenv("PADDLE_SERVE_KV_DTYPE", raising=False)
    monkeypatch.delenv("PADDLE_PAGED_ATTENTION_BACKEND", raising=False)
    rng = np.random.RandomState(5)
    reqs = _mixed_trace(rng, n=3)

    def serve(backend):
        eng = GenerationEngine(model, num_slots=2, block_size=4,
                               num_blocks=64, prefill_chunk=8,
                               kv_dtype="int8",
                               attention_backend=backend)
        return _run_trace(eng, reqs)

    assert serve("dense") == serve("pallas")


# ---------------------------------------------------------------------------
# capacity claim: int8 pool bytes <= 0.55x the fp16/bf16 pool
# ---------------------------------------------------------------------------

def test_int8_pool_bytes_half_of_bf16(model):
    import jax.numpy as jnp

    from paddle_tpu.inference import PagedKVCache

    bf16 = PagedKVCache(2, 32, 8, 4, 16, dtype=jnp.bfloat16)
    int8 = PagedKVCache(2, 32, 8, 4, 16, dtype=jnp.bfloat16,
                        kv_dtype="int8")
    assert int8.pool_spec()[1] == jnp.int8
    assert int8.scale_spec() == ((2, 32, 2), jnp.float32)
    ratio = int8.pool_nbytes() / bf16.pool_nbytes()
    assert ratio <= 0.55, f"int8 pool ratio {ratio:.3f} > 0.55"
    # and the engine-level gauge reports the quantized footprint
    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           num_blocks=16, prefill_chunk=8,
                           kv_dtype="int8")
    snap = eng.metrics_snapshot()
    series = snap["engine_pool_bytes"]["series"]
    assert [s["labels"] for s in series] \
        == [{"shard": "0", "kv_dtype": "int8"}]
    eng.add_request(np.arange(5, dtype=np.int32), 2)
    eng.run()
    snap = eng.metrics_snapshot()
    assert snap["engine_pool_bytes"]["series"][0]["value"] \
        == eng.cache.pool_nbytes()
    with pytest.raises(ValueError, match="kv_dtype"):
        PagedKVCache(2, 8, 4, 4, 8, kv_dtype="fp8")


def test_dtype_info_gauges_and_utilization_labels(model, monkeypatch):
    monkeypatch.delenv("PADDLE_SERVE_KV_DTYPE", raising=False)
    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           num_blocks=16, prefill_chunk=8,
                           kv_dtype="int8", weight_dtype="int8")
    snap = eng.metrics_snapshot()
    assert [s["labels"] for s in
            snap["engine_kv_dtype_info"]["series"]] \
        == [{"kv_dtype": "int8"}]
    assert [s["labels"] for s in
            snap["engine_weight_dtype_info"]["series"]] \
        == [{"weight_dtype": "int8"}]
    assert [s["labels"] for s in
            snap["engine_pool_utilization"]["series"]] \
        == [{"shard": "0", "kv_dtype": "int8"}]
    # the fp engine reports its real dtype, not a missing series
    fp = GenerationEngine(model, num_slots=2, block_size=4,
                          num_blocks=16, prefill_chunk=8)
    snap = fp.metrics_snapshot()
    assert [s["labels"] for s in
            snap["engine_kv_dtype_info"]["series"]] \
        == [{"kv_dtype": "float32"}]
    assert [s["labels"] for s in
            snap["engine_weight_dtype_info"]["series"]] \
        == [{"weight_dtype": "float32"}]


def test_kv_dtype_env_override_wins(model, monkeypatch):
    monkeypatch.setenv("PADDLE_SERVE_KV_DTYPE", "int8")
    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           prefill_chunk=8)
    assert eng.kv_dtype == "int8" and eng.cache.scales is not None
    monkeypatch.setenv("PADDLE_SERVE_KV_DTYPE", "fp8")
    with pytest.raises(ValueError, match="PADDLE_SERVE_KV_DTYPE"):
        GenerationEngine(model, num_slots=2, block_size=4,
                         prefill_chunk=8)
    monkeypatch.setenv("PADDLE_SERVE_KV_DTYPE", "")
    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           prefill_chunk=8, weight_dtype="int8")
    assert eng.kv_dtype is None and eng.weight_dtype == "int8"


# ---------------------------------------------------------------------------
# quantized sharing: COW byte-identity + read-only prefix seating
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("backend,mp", [("dense", 1), ("pallas", 2)])
def test_quantized_cow_keeps_shared_blocks_and_scales(model,
                                                      monkeypatch,
                                                      backend, mp):
    """ISSUE 11 satellite: a borrower decoding off shared quantized
    prefix blocks must never mutate the cached int8 CODES or their
    per-block SCALES — COW promotes (copying scale rows with the
    block) before any write lands. Proven via dense_gather_reference
    over raw codes, raw scale rows, and dequantized values, across
    both backends and mp in {1, 2} (tier-1 runs the diagonal cells;
    the complementary pair is slow-marked below)."""
    monkeypatch.delenv("PADDLE_SERVE_KV_DTYPE", raising=False)
    monkeypatch.delenv("PADDLE_SERVE_MP", raising=False)
    _assert_cow_immutable(model, backend, mp)


@pytest.mark.slow
@pytest.mark.parametrize("backend,mp", [("pallas", 1), ("dense", 2)])
def test_quantized_cow_full_matrix(model, monkeypatch, backend, mp):
    monkeypatch.delenv("PADDLE_SERVE_KV_DTYPE", raising=False)
    monkeypatch.delenv("PADDLE_SERVE_MP", raising=False)
    _assert_cow_immutable(model, backend, mp)


def _assert_cow_immutable(model, backend, mp):
    import jax.numpy as jnp

    from paddle_tpu.ops.paged_attention import dense_gather_reference

    rng = np.random.RandomState(3)
    shared = rng.randint(0, VOCAB, 8).astype(np.int32)   # 2 blocks
    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           num_blocks=32, prefill_chunk=8,
                           kv_dtype="int8", attention_backend=backend,
                           mp_degree=None if mp == 1 else mp)
    rid = eng.add_request(shared, 3)
    first = eng.run()[rid]
    assert eng.cache.num_cached_blocks >= 2
    # snapshot the CACHED blocks' codes + scales before the borrower
    cached_blocks = sorted(eng.cache._hash_of)
    kp0 = np.asarray(eng.cache.kpool)[:, cached_blocks].copy()
    vp0 = np.asarray(eng.cache.vpool)[:, cached_blocks].copy()
    sc0 = np.asarray(eng.cache.scales)[:, cached_blocks].copy()
    # the borrower: full-prefix hit, decodes (COW) off the shared rows
    rid2 = eng.add_request(shared.copy(), 3)
    second = eng.run()[rid2]
    assert eng.prefix_hit_tokens >= len(shared)
    assert list(first) == list(second)      # same prompt, same stream
    assert np.array_equal(
        np.asarray(eng.cache.kpool)[:, cached_blocks], kp0)
    assert np.array_equal(
        np.asarray(eng.cache.vpool)[:, cached_blocks], vp0)
    assert np.array_equal(
        np.asarray(eng.cache.scales)[:, cached_blocks], sc0)
    # dequantized reconstruction through the probe stays within the
    # grid's resolution of the fp engine's rows (the documented 2%-
    # of-block-absmax budget)
    fp = GenerationEngine(model, num_slots=2, block_size=4,
                          num_blocks=32, prefill_chunk=8,
                          attention_backend=backend)
    ridf = fp.add_request(shared, 3)
    fp.run()
    row = np.zeros(fp.max_blocks, np.int32)
    row[:2] = cached_blocks[:2]
    # both engines cached the same prompt's first 2 blocks; rebuild
    # via each engine's own table layout
    qrow = np.zeros(eng.max_blocks, np.int32)
    qrow[:2] = cached_blocks[:2]
    for layer in range(model.config.num_layers):
        gkq, gvq = dense_gather_reference(
            eng.cache.kpool, eng.cache.vpool, layer,
            jnp.asarray(qrow), 8, scales=eng.cache.scales)
        gkf, gvf = dense_gather_reference(
            fp.cache.kpool, fp.cache.vpool, layer, jnp.asarray(row),
            8)
        for q, f in ((gkq, gkf), (gvq, gvf)):
            tol = 0.02 * max(np.abs(np.asarray(f)).max(), 1e-6)
            assert np.abs(np.asarray(q) - np.asarray(f)).max() <= tol


def test_quantized_eviction_under_pressure_stays_consistent(model):
    """A pool tight enough to evict cached quantized blocks mid-trace
    rides the same stall/retry path; allocate() resets recycled
    blocks' scale rows to the floor so a new tenant never quantizes
    on a stale grid."""
    from paddle_tpu.ops.paged_attention import KV_QUANT_EPS

    rng = np.random.RandomState(7)
    reqs = _mixed_trace(rng, n=3)
    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           num_blocks=10, prefill_chunk=8,
                           kv_dtype="int8")
    out1 = _run_trace(eng, reqs) + _run_trace(eng, reqs, midrun=False)
    assert eng.cache.num_free == eng.cache.num_blocks - 1
    # a freshly allocated block's scale rows are back at the floor
    got = eng.cache.allocate(2)
    sc = np.asarray(eng.cache.scales)[:, got]
    assert np.all(sc == np.float32(KV_QUANT_EPS))
    eng.cache.free(got)
    # determinism: the same trace on a fresh engine replays exactly
    eng2 = GenerationEngine(model, num_slots=2, block_size=4,
                            num_blocks=10, prefill_chunk=8,
                            kv_dtype="int8")
    out2 = _run_trace(eng2, reqs) + _run_trace(eng2, reqs,
                                               midrun=False)
    assert out1 == out2


# ---------------------------------------------------------------------------
# int8 weights: quantize_weights / refresh_weights / dequantize(dtype=)
# ---------------------------------------------------------------------------

def test_weight_quantization_state_and_refresh():
    """weight_dtype='int8' swaps qkv/out/fc1/fc2 state entries for
    (int8 codes, per-output-channel scale) pairs; refresh_weights()
    requantizes after a live weight update (the served snapshot is
    weight-stationary, like the mp engine's)."""
    m = _model(seed=3)
    prompt = np.arange(5, dtype=np.int32)
    eng = GenerationEngine(m, num_slots=1, block_size=4,
                           prefill_chunk=8, weight_dtype="int8")
    quantized = [e for e in eng._state_arrays() if isinstance(e, tuple)]
    assert len(quantized) == 4 * m.config.num_layers
    for q, s in quantized:
        assert str(q.dtype) == "int8"
        assert str(s.dtype) == "float32" and s.shape[0] == 1
    rid = eng.add_request(prompt, 4)
    before = list(map(int, eng.run()[rid]))
    fp = GenerationEngine(m, num_slots=1, block_size=4,
                          prefill_chunk=8)
    ridf = fp.add_request(prompt, 4)
    ref = list(map(int, fp.run()[ridf]))
    from bench_ops import _token_match_fraction
    assert _token_match_fraction([ref], [before]) >= TOKEN_PARITY_MIN
    # a live weight update is invisible until requantized...
    w = m.gpt.blocks[0].attn.qkv_proj.weight
    old = w._array
    w._array = -old
    rid = eng.add_request(prompt, 4)
    assert list(map(int, eng.run()[rid])) == before
    # ...and visible after refresh_weights()
    eng.refresh_weights()
    ridf = fp.add_request(prompt, 4)
    want = list(map(int, fp.run()[ridf]))
    rid = eng.add_request(prompt, 4)
    got = list(map(int, eng.run()[rid]))
    assert _token_match_fraction([want], [got]) >= TOKEN_PARITY_MIN
    assert eng.decode_traces == 1      # refresh never retraces
    w._array = old


def test_dequantize_dtype_parameter_regression():
    """ISSUE 11 satellite: dequantize() grows dtype= (default fp32 —
    the legacy contract — regression-tested both ways)."""
    import jax.numpy as jnp

    from paddle_tpu.quantization import dequantize, quantize_absmax

    w = np.linspace(-3, 3, 24, dtype=np.float32).reshape(4, 6)
    q, s = quantize_absmax(w, axis=1)
    legacy = dequantize(q, s)
    assert legacy.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(legacy), w, atol=0.03)
    bf = dequantize(q, s, dtype=jnp.bfloat16)
    assert bf.dtype == jnp.bfloat16      # straight to compute dtype
    np.testing.assert_allclose(
        np.asarray(bf.astype(jnp.float32)), w, atol=0.05)


def test_steady_state_and_donation_with_int8(model, monkeypatch):
    """A warmed int8 engine retraces nothing on churn; the pools stay
    donated ((1, 2) — the scale array rides undonated, it is tiny)."""
    monkeypatch.delenv("PADDLE_SERVE_KV_DTYPE", raising=False)
    rng = np.random.RandomState(9)
    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           num_blocks=64, prefill_chunk=8,
                           kv_dtype="int8", donate=True)
    assert eng._donate_argnums == (1, 2)
    for _ in range(2):
        eng.add_request(rng.randint(0, VOCAB, 6).astype(np.int32), 3)
    eng.run()
    with jit.expect_traces(eng._decode_pure, 0), \
            jit.expect_traces(eng._prefill_pure, 0):
        eng.add_request(rng.randint(0, VOCAB, 9).astype(np.int32), 4)
        eng.run()


# ---------------------------------------------------------------------------
# bench row (CI-scale runner + suite registration)
# ---------------------------------------------------------------------------

def test_offered_load_int8_bench_row(monkeypatch):
    """The gpt_engine_offered_load_int8 SUITE_ROWS runner at test
    scale: serves the same trace fp then int8 (KV + weights), asserts
    tolerance inside the runner, records tokens/s and pool bytes."""
    monkeypatch.delenv("PADDLE_SERVE_KV_DTYPE", raising=False)
    monkeypatch.delenv("PADDLE_SERVE_WEIGHT_DTYPE", raising=False)
    monkeypatch.delenv("PADDLE_SERVE_MP", raising=False)
    monkeypatch.delenv("PADDLE_PAGED_ATTENTION_BACKEND", raising=False)
    import bench_ops
    from paddle_tpu.models import GPTConfig

    cfg = GPTConfig.tiny(vocab=32, hidden=16, layers=1, heads=2,
                         seq=32)
    paddle.seed(0)
    rec = bench_ops._engine_offered_load_case(
        model_cfg=cfg, requests=[(3, 4), (6, 4), (10, 3)],
        num_slots=2, block_size=4, prefill_buckets=(4, 8, 16, 32),
        kv_dtype="int8")()
    assert rec["kv_dtype"] == "int8" and rec["weight_dtype"] == "int8"
    assert rec["tokens_per_s"] > 0 and rec["tokens_per_s_fp"] > 0
    assert rec["token_match_fraction"] >= bench_ops.INT8_TOKEN_PARITY_MIN
    assert rec["pool_bytes_int8"] < rec["pool_bytes_fp"]
    assert rec["pool_bytes_ratio"] <= 0.55
    assert rec["decode_recompiles"] == 0
    assert "gpt_engine_offered_load_int8" in bench_ops.suite_names()
