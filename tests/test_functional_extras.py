"""Functional-surface completion tests (python/paddle/nn/functional/):
the loss family, misc tensor utilities, and CTC — each against a numpy
or analytic reference.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

RS = np.random.RandomState(0)


def _t(a):
    return paddle.to_tensor(np.asarray(a))


def test_normalize_and_sequence_mask():
    x = RS.randn(4, 8).astype(np.float32)
    out = F.normalize(_t(x), p=2, axis=1).numpy()
    np.testing.assert_allclose(np.linalg.norm(out, axis=1), 1.0,
                               rtol=1e-5)
    m = F.sequence_mask(_t(np.array([1, 3])), maxlen=4).numpy()
    np.testing.assert_array_equal(
        m, [[1, 0, 0, 0], [1, 1, 1, 0]])


def test_simple_losses_match_references():
    p = RS.rand(8).astype(np.float32) * 0.9 + 0.05
    y = (RS.rand(8) > 0.5).astype(np.float32)
    np.testing.assert_allclose(
        F.log_loss(_t(p), _t(y)).numpy(),
        -y * np.log(p + 1e-4) - (1 - y) * np.log(1 - p + 1e-4),
        rtol=1e-5)
    a = RS.randn(6).astype(np.float32)
    b = RS.randn(6).astype(np.float32)
    np.testing.assert_allclose(
        F.square_error_cost(_t(a), _t(b)).numpy(), (a - b) ** 2,
        rtol=1e-6)


def test_sigmoid_focal_loss_gamma_zero_is_weighted_bce():
    z = RS.randn(8).astype(np.float32)
    y = (RS.rand(8) > 0.5).astype(np.float32)
    ours = F.sigmoid_focal_loss(_t(z), _t(y), alpha=0.5, gamma=0.0,
                                reduction="none").numpy()
    p = 1 / (1 + np.exp(-z))
    bce = -(y * np.log(p) + (1 - y) * np.log(1 - p))
    np.testing.assert_allclose(ours, 0.5 * bce, rtol=1e-4, atol=1e-5)


def test_dice_loss_perfect_prediction_is_small():
    y = RS.randint(0, 3, (2, 5, 1))
    perfect = np.eye(3, dtype=np.float32)[y.squeeze(-1)]
    loss = float(F.dice_loss(_t(perfect), _t(y)).numpy())
    assert loss < 0.01
    uniform = np.full((2, 5, 3), 1 / 3, np.float32)
    assert float(F.dice_loss(_t(uniform), _t(y)).numpy()) > loss


def test_triplet_and_cosine_embedding_losses():
    a = RS.randn(4, 8).astype(np.float32)
    # positive == anchor, negative far: loss should be ~0 at margin 0
    z = float(F.triplet_margin_loss(_t(a), _t(a), _t(a + 100), margin=0.0)
              .numpy())
    assert z < 1e-3
    # cosine: identical vectors with label 1 -> ~0
    y = np.ones((4,), np.float32)
    c = float(F.cosine_embedding_loss(_t(a), _t(a), _t(y)).numpy())
    assert c < 1e-5


def test_margin_cross_entropy_reduces_target_logit():
    cos = np.full((2, 4), 0.2, np.float32)
    cos[0, 1] = 0.9
    cos[1, 2] = 0.9
    y = np.array([1, 2])
    plain = float(F.margin_cross_entropy(
        _t(cos), _t(y), margin1=1.0, margin2=0.0, margin3=0.0,
        scale=10.0).numpy())
    margined = float(F.margin_cross_entropy(
        _t(cos), _t(y), margin1=1.0, margin2=0.5, margin3=0.0,
        scale=10.0).numpy())
    assert margined > plain  # margin makes the task harder


def test_ctc_loss_against_bruteforce():
    """T=3, C=3 (blank=0), label 'a': sum over all alignments mapping
    to 'a' must equal exp(-nll)."""
    T, B, C = 3, 1, 3
    rng = np.random.RandomState(0)
    logits = rng.randn(T, B, C).astype(np.float32)
    lp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
    nll = float(F.ctc_loss(_t(lp), _t(np.array([[1]])),
                           _t(np.array([3])), _t(np.array([1])),
                           reduction="none").numpy()[0])

    # brute force: all 3^3 paths, collapse (remove blanks+repeats) == [1]
    total = 0.0
    import itertools

    for path in itertools.product(range(C), repeat=T):
        collapsed = []
        prev = None
        for s in path:
            if s != 0 and s != prev:
                collapsed.append(s)
            prev = s
        if collapsed == [1]:
            total += np.exp(sum(lp[t, 0, s] for t, s in enumerate(path)))
    np.testing.assert_allclose(np.exp(-nll), total, rtol=1e-4)


def test_ctc_loss_is_differentiable_and_batched():
    T, B, C, S = 6, 3, 5, 2
    rng = np.random.RandomState(1)
    logits = _t(rng.randn(T, B, C).astype(np.float32))
    logits.stop_gradient = False
    labels = _t(rng.randint(1, C, (B, S)))
    loss = F.ctc_loss(logits, labels, _t(np.array([6, 5, 4])),
                      _t(np.array([2, 2, 1])))
    assert np.isfinite(float(loss))
    loss.backward()
    assert logits.grad is not None
    assert np.isfinite(np.asarray(logits.grad._array)).all()


def test_misc_activations_and_pools():
    x = RS.randn(2, 3, 4, 4).astype(np.float32)
    out = F.pixel_unshuffle(_t(x), 2)
    assert out.shape == [2, 12, 2, 2]
    back = F.pixel_shuffle(out, 2)
    np.testing.assert_allclose(back.numpy(), x, rtol=1e-6)
    t = _t(np.array([-1.0, 0.5, 2.0], np.float32))
    np.testing.assert_allclose(F.thresholded_relu(t, 1.0).numpy(),
                               [0, 0, 2], rtol=1e-6)
    r_eval = F.rrelu(t, training=False).numpy()
    mid = (1 / 8 + 1 / 3) / 2
    np.testing.assert_allclose(r_eval, [-mid, 0.5, 2.0], rtol=1e-5)
    x5 = RS.randn(1, 2, 4, 4, 4).astype(np.float32)
    assert F.max_pool3d(_t(x5), 2).shape == [1, 2, 2, 2, 2]
    d = F.dropout3d(_t(x5), p=0.5, training=True).numpy()
    # whole channels are zeroed or scaled
    per_chan = d.reshape(2, -1)
    for c in range(2):
        vals = per_chan[c]
        assert (vals == 0).all() or not (vals == 0).any()
