"""tpu-race unit tests: per-rule fixtures (exact file:line), inline
suppressions, baseline round-trip, stable finding IDs, branch-fork
effect modeling, the fixed/annotated real-file regressions, and the
CLI surface."""
import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

import paddle_tpu.analysis.race as R
from paddle_tpu.analysis.findings import assign_ids

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = Path(__file__).parent / "fixtures" / "tpu_race"
RACE = os.path.join(REPO, "tools", "tpu_race.py")


def analyze(name):
    findings, _mod = R.analyze_file(str(FIXTURES / name))
    return assign_ids(findings)


def hits(findings, rule):
    """(line, suppressed) pairs for one rule, in line order."""
    return [(f.line, f.suppressed) for f in findings if f.rule == rule]


# -- per-rule fixtures: >=1 positive and >=1 negative, exact lines --------

@pytest.mark.parametrize("rule,pos,neg,lines", [
    ("TPU201", "tpu201_pos.py", "tpu201_neg.py", [11]),
    ("TPU202", "tpu202_pos.py", "tpu202_neg.py", [16, 31]),
    ("TPU203", "tpu203_pos.py", "tpu203_neg.py", [17]),
    ("TPU204", "tpu204_pos.py", "tpu204_neg.py", [20, 24, 28]),
    ("TPU205", "tpu205_pos.py", "tpu205_neg.py", [15]),
])
def test_rule_fixture(rule, pos, neg, lines):
    findings = analyze(pos)
    assert hits(findings, rule) == [(ln, False) for ln in lines], \
        [f.render() for f in findings]
    # the positive fixture must not trip OTHER rules (fixture isolation)
    assert {f.rule for f in findings} == {rule}
    neg_findings = analyze(neg)
    assert hits(neg_findings, rule) == [], \
        [f.render() for f in neg_findings]


def test_unparseable_file_is_reported_not_skipped():
    findings = analyze("unparseable.py")
    assert [f.rule for f in findings] == ["TPU200"]
    assert "unparseable" in findings[0].message


# -- suppressions ---------------------------------------------------------

def test_inline_suppression_same_line_only():
    findings = analyze("suppressed.py")
    assert hits(findings, "TPU202") == [(15, True), (18, False)]


def test_race_tag_does_not_leak_into_tpu_lint_suppressions():
    """`# tpu-race: disable=...` must not suppress tpu-lint findings
    and vice versa — the tags are separate namespaces."""
    from paddle_tpu.analysis.findings import parse_suppressions
    src = ("x = 1  # tpu-race: disable=TPU202\n"
           "y = 2  # tpu-lint: disable=TPU005\n")
    assert parse_suppressions(src) == {2: {"TPU005"}}
    assert parse_suppressions(src, tag="tpu-race") == {1: {"TPU202"}}


# -- branch-fork effect modeling (the engine false-positive shapes) -------

def test_early_return_arm_does_not_leak_its_dispatch():
    """The `step()` shape: an `if` arm that RETURNS after dispatching
    (async core) must not make the serial fall-through path's
    allocations read as free-before-complete."""
    src = (
        "class E:\n"
        "    def step(self):\n"
        "        if self.async_core:\n"
        "            return self._step_async()\n"
        "        return self.cache.allocate(1)\n"
        "    def _step_async(self):\n"
        "        self._dispatch_ahead()\n"
        "    def _dispatch_ahead(self):\n"
        "        pass\n")
    findings, _ = R.analyze_file("e.py", src)
    assert [f for f in findings if f.rule == "TPU203"] == [], \
        [f.render() for f in findings]


def test_exclusive_if_arms_do_not_see_each_others_dispatch():
    """The `_dispatch_ahead()` shape: a dispatch on the spec arm and a
    release on the else arm are exclusive, not ordered. The linear
    `bad()` ordering is the positive control — same calls, one path."""
    src = (
        "class E:\n"
        "    def go(self, spec):\n"
        "        if spec:\n"
        "            self._spec_dispatch()\n"
        "        else:\n"
        "            self.pool.release(1)\n"
        "    def bad(self):\n"
        "        self._spec_dispatch()\n"
        "        self.pool.release(1)\n")
    findings, _ = R.analyze_file("e.py", src)
    assert [(f.rule, f.line) for f in findings] == [("TPU203", 9)], \
        [f.render() for f in findings]


def test_conditional_complete_is_pessimistic():
    """A complete wrapped in `if` (not the early-return guard idiom)
    leaves a no-complete path — the release after the merge fires."""
    src = (
        "import jax\n"
        "class E:\n"
        "    def f(self, x, b):\n"
        "        self._plain_dispatch(x)\n"
        "        if self.flag:\n"
        "            jax.block_until_ready(x)\n"
        "        self.cache.free(b)\n"
        "    def _plain_dispatch(self, x):\n"
        "        pass\n")
    findings, _ = R.analyze_file("e.py", src)
    assert [(f.rule, f.line) for f in findings] == [("TPU203", 7)], \
        [f.render() for f in findings]


def test_getattr_default_lock_idiom_is_a_lock():
    """`with getattr(self, "_lock", threading.Lock()):` (core/random)
    still names the lock for the discipline rules."""
    src = (
        "import threading\n"
        "class G:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def seed(self, s):\n"
        "        with getattr(self, '_lock', threading.Lock()):\n"
        "            self._seed = s\n"
        "    def reseed(self, s):\n"
        "        with self._lock:\n"
        "            self._seed = s\n")
    findings, _ = R.analyze_file("g.py", src)
    assert findings == [], [f.render() for f in findings]


# -- real-file regressions for the first self-run's findings --------------

def _analyze_repo_file(rel):
    path = os.path.join(REPO, rel)
    src = Path(path).read_text()
    findings, _ = R.analyze_file(path, src)
    return src, findings


def test_ssd_table_lru_touch_is_locked_regression():
    """PR-19 true positive: SSDSparseTable._touch mutated the LRU
    OrderedDict without _db_lock while _maybe_evict popped it under
    the lock (table ops run on PS rpc handler threads). Fixed by
    locking _touch; dropping the lock must re-fire TPU202."""
    rel = "paddle_tpu/distributed/ps/table.py"
    src, findings = _analyze_repo_file(rel)
    assert [f for f in findings if f.rule == "TPU202"] == [], \
        [f.render() for f in findings]
    unlocked = src.replace(
        "        with self._db_lock:\n"
        "            self._lru.pop(i, None)\n"
        "            self._lru[i] = None",
        "        self._lru.pop(i, None)\n"
        "        self._lru[i] = None")
    assert unlocked != src, "table.py _touch no longer matches"
    broken, _ = R.analyze_file(rel, unlocked)
    assert any(f.rule == "TPU202" and "_lru" in f.message
               for f in broken), [f.render() for f in broken]


@pytest.mark.parametrize("rel", [
    "paddle_tpu/observability/metrics.py",
    "paddle_tpu/distributed/launch/elastic.py",
])
def test_guarded_by_annotations_are_load_bearing(rel):
    """metrics._zero / elastic._prune are caller-holds-lock helpers:
    clean WITH the guarded-by annotations, TPU202 findings without
    them — the annotations assert a real contract, not decoration."""
    src, findings = _analyze_repo_file(rel)
    assert "# guarded-by: _lock" in src
    assert [f for f in findings if f.rule == "TPU202"] == [], \
        [f.render() for f in findings]
    stripped = src.replace("# guarded-by: _lock", "")
    broken, _ = R.analyze_file(rel, stripped)
    assert any(f.rule == "TPU202" for f in broken)


# -- stable finding ids ---------------------------------------------------

def test_finding_ids_survive_line_shifts():
    src = (FIXTURES / "tpu202_pos.py").read_text()
    base, _ = R.analyze_file("k.py", src)
    assign_ids(base)
    shifted, _ = R.analyze_file("k.py", "# a comment\n\n" + src)
    assign_ids(shifted)
    assert [f.id for f in base] == [f.id for f in shifted]
    assert [f.line + 2 for f in base] == [f.line for f in shifted]


def test_finding_ids_change_when_the_hazard_line_changes():
    src = (FIXTURES / "tpu202_pos.py").read_text()
    base, _ = R.analyze_file("k.py", src)
    assign_ids(base)
    edited, _ = R.analyze_file(
        "k.py", src.replace("self._total = 0.0\n\n\nclass TwoLocks",
                            "self._total = -0.0\n\n\nclass TwoLocks"))
    assign_ids(edited)
    assert base[0].id != edited[0].id  # grandfathering invalidated


# -- baseline round-trip --------------------------------------------------

def test_baseline_round_trip(tmp_path):
    res = R.analyze_paths([str(FIXTURES / "tpu202_pos.py")])
    assert len(res.new_findings()) == 2
    bpath = tmp_path / "baseline.json"
    R.write_baseline(str(bpath), res.new_findings())
    # skeleton entries have empty justifications: loader must refuse
    with pytest.raises(R.BaselineError, match="justification"):
        R.load_baseline(str(bpath))
    doc = json.loads(bpath.read_text())
    for e in doc["entries"]:
        e["justification"] = "test grandfathering"
    doc["entries"].append({"id": "TPU209:deadbeef00", "rule": "TPU209",
                           "path": "gone.py",
                           "justification": "stale on purpose"})
    bpath.write_text(json.dumps(doc))
    baseline = R.load_baseline(str(bpath))
    res2 = R.analyze_paths([str(FIXTURES / "tpu202_pos.py")],
                           baseline=baseline)
    assert res2.new_findings() == []
    assert sum(1 for f in res2.findings if f.baselined) == 2
    assert res2.stale_baseline == ["TPU209:deadbeef00"]


# -- CLI ------------------------------------------------------------------

def _run_race(args, cwd=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run([sys.executable, RACE] + args, env=env,
                          capture_output=True, text=True, timeout=300,
                          cwd=cwd)


def test_cli_json_format_and_exit_code():
    res = _run_race([str(FIXTURES / "tpu204_pos.py"),
                     "--baseline", "none", "--format", "json"])
    assert res.returncode == 1
    doc = json.loads(res.stdout)
    assert [f["line"] for f in doc["findings"]] == [20, 24, 28]
    assert all(f["rule"] == "TPU204" for f in doc["findings"])
    assert doc["files"] == 1
    res = _run_race([str(FIXTURES / "tpu204_neg.py"),
                     "--baseline", "none"])
    assert res.returncode == 0, res.stdout + res.stderr
    assert "tpu-race clean" in res.stdout


def test_cli_stats_reports_counts_and_unparseable():
    res = _run_race([str(FIXTURES), "--baseline", "none", "--stats"])
    assert res.returncode == 1
    out = res.stdout
    assert "files analyzed: 12" in out
    assert "UNPARSEABLE files: 1" in out
    assert "unparseable.py" in out
    for rule, n in [("TPU200", 1), ("TPU201", 1), ("TPU202", 4),
                    ("TPU203", 1), ("TPU204", 3), ("TPU205", 1)]:
        assert any(line.startswith(rule)
                   and line.rstrip().endswith(str(n))
                   for line in out.splitlines()), (rule, n, out)
    assert "suppressed inline: 1" in out


def test_cli_list_rules_covers_all_six():
    res = _run_race(["--list-rules"])
    assert res.returncode == 0
    for rule in ["TPU20%d" % i for i in range(6)]:
        assert rule in res.stdout
