"""RNN family tests (reference python/paddle/nn/layer/rnn.py): cells vs
numpy recurrence, stacked/bidirectional LSTM/GRU/SimpleRNN, sequence
masking, gradients, and jit compatibility.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _np_lstm_step(wih, whh, bih, bhh, x, h, c):
    g = x @ wih.T + bih + h @ whh.T + bhh
    i, f, gg, o = np.split(g, 4, axis=-1)
    sig = lambda a: 1 / (1 + np.exp(-a))
    i, f, o = sig(i), sig(f), sig(o)
    c2 = f * c + i * np.tanh(gg)
    h2 = o * np.tanh(c2)
    return h2, c2


def _np_gru_step(wih, whh, bih, bhh, x, h):
    sig = lambda a: 1 / (1 + np.exp(-a))
    xg = x @ wih.T + bih
    hg = h @ whh.T + bhh
    xr, xz, xc = np.split(xg, 3, axis=-1)
    hr, hz, hc = np.split(hg, 3, axis=-1)
    r, z = sig(xr + hr), sig(xz + hz)
    c = np.tanh(xc + r * hc)
    return (1 - z) * c + z * h


def _cell_weights(cell):
    return [np.asarray(p._array) for p in cell._params()]


def test_lstm_cell_matches_numpy():
    paddle.seed(0)
    cell = nn.LSTMCell(8, 16)
    x = np.random.RandomState(0).randn(4, 8).astype(np.float32)
    out, (h, c) = cell(paddle.to_tensor(x))
    wih, whh, bih, bhh = _cell_weights(cell)
    h_ref, c_ref = _np_lstm_step(wih, whh, bih, bhh, x,
                                 np.zeros((4, 16), np.float32),
                                 np.zeros((4, 16), np.float32))
    np.testing.assert_allclose(np.asarray(out._array), h_ref, atol=1e-5)
    np.testing.assert_allclose(np.asarray(c._array), c_ref, atol=1e-5)


def test_gru_cell_matches_numpy():
    paddle.seed(0)
    cell = nn.GRUCell(8, 16)
    x = np.random.RandomState(1).randn(4, 8).astype(np.float32)
    out, h = cell(paddle.to_tensor(x))
    wih, whh, bih, bhh = _cell_weights(cell)
    ref = _np_gru_step(wih, whh, bih, bhh, x,
                       np.zeros((4, 16), np.float32))
    np.testing.assert_allclose(np.asarray(out._array), ref, atol=1e-5)


def test_simple_rnn_cell_and_rnn_wrapper():
    paddle.seed(0)
    cell = nn.SimpleRNNCell(8, 16)
    rnn = nn.RNN(cell)
    x = np.random.RandomState(2).randn(4, 5, 8).astype(np.float32)
    outs, final = rnn(paddle.to_tensor(x))
    assert outs.shape == [4, 5, 16]
    wih, whh, bih, bhh = _cell_weights(cell)
    h = np.zeros((4, 16), np.float32)
    for t in range(5):
        h = np.tanh(x[:, t] @ wih.T + bih + h @ whh.T + bhh)
    np.testing.assert_allclose(np.asarray(outs._array)[:, -1], h,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(final._array), h, atol=1e-5)


def test_lstm_full_sequence_matches_numpy():
    paddle.seed(0)
    lstm = nn.LSTM(8, 16)
    x = np.random.RandomState(3).randn(2, 6, 8).astype(np.float32)
    outs, (hN, cN) = lstm(paddle.to_tensor(x))
    wih, whh, bih, bhh = _cell_weights(lstm.cell_0_0)
    h = c = np.zeros((2, 16), np.float32)
    refs = []
    for t in range(6):
        h, c = _np_lstm_step(wih, whh, bih, bhh, x[:, t], h, c)
        refs.append(h)
    np.testing.assert_allclose(np.asarray(outs._array),
                               np.stack(refs, 1), atol=1e-5)
    np.testing.assert_allclose(np.asarray(hN._array)[0], h, atol=1e-5)
    np.testing.assert_allclose(np.asarray(cN._array)[0], c, atol=1e-5)


def test_bidirectional_gru_shapes_and_reverse_semantics():
    paddle.seed(0)
    gru = nn.GRU(8, 16, direction="bidirect")
    x = np.random.RandomState(4).randn(3, 5, 8).astype(np.float32)
    outs, hN = gru(paddle.to_tensor(x))
    assert outs.shape == [3, 5, 32]  # fwd+bwd concat
    assert hN.shape == [2, 3, 16]   # L*ndir
    # stacked-bidirect shape check
    gru2 = nn.GRU(8, 16, num_layers=2, direction="bidirect")
    o2, h2 = gru2(paddle.to_tensor(x))
    assert o2.shape == [3, 5, 32] and h2.shape == [4, 3, 16]
    # the backward direction's output at t=0 must depend on the LAST
    # input step (reverse recurrence)
    x2 = x.copy()
    x2[:, -1] += 1.0
    outs2, _ = gru(paddle.to_tensor(x2))
    d = np.abs(np.asarray(outs2._array) - np.asarray(outs._array))
    assert d[:, 0, 16:].max() > 1e-6   # bwd out at t=0 changed
    assert d[:, 0, :16].max() < 1e-7   # fwd out at t=0 unchanged


def test_sequence_length_masks_final_state():
    paddle.seed(0)
    lstm = nn.LSTM(4, 8)
    x = np.random.RandomState(5).randn(2, 6, 4).astype(np.float32)
    seq = np.array([3, 6], np.int64)
    outs, (hN, _) = lstm(paddle.to_tensor(x),
                         sequence_length=paddle.to_tensor(seq))
    # sample 0's final state == running only its first 3 steps
    outs3, (h3, _) = lstm(paddle.to_tensor(x[:, :3]))
    np.testing.assert_allclose(np.asarray(hN._array)[0, 0],
                               np.asarray(h3._array)[0, 0], atol=1e-5)
    # padded steps emit zeros
    np.testing.assert_allclose(np.asarray(outs._array)[0, 3:], 0.0)


def test_rnn_gradients_flow():
    paddle.seed(0)
    lstm = nn.LSTM(4, 8, num_layers=2)
    x = paddle.to_tensor(
        np.random.RandomState(6).randn(2, 5, 4).astype(np.float32))
    x.stop_gradient = False
    outs, _ = lstm(x)
    outs.sum().backward()
    assert x.grad is not None
    for p in lstm.parameters():
        assert p.grad is not None, "every cell weight gets a gradient"


def test_lstm_trains_under_trainstep():
    from paddle_tpu.jit import TrainStep
    import paddle_tpu.nn.functional as F

    paddle.seed(0)

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.lstm = nn.LSTM(4, 16)
            self.head = nn.Linear(16, 2)

        def forward(self, x):
            outs, (h, _) = self.lstm(x)
            return self.head(h[0])

    net = Net()
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=net.parameters())
    step = TrainStep(net, opt, F.cross_entropy)
    rng = np.random.RandomState(0)
    x = rng.randn(16, 8, 4).astype(np.float32)
    y = (x.sum(axis=(1, 2)) > 0).astype(np.int64)
    losses = [float(step(paddle.to_tensor(x), label=paddle.to_tensor(y)))
              for _ in range(20)]
    assert losses[-1] < losses[0] * 0.9


def test_time_major_layout():
    paddle.seed(0)
    gru = nn.GRU(4, 8, time_major=True)
    x = np.random.RandomState(7).randn(5, 3, 4).astype(np.float32)  # [T,B,I]
    outs, _ = gru(paddle.to_tensor(x))
    assert outs.shape == [5, 3, 8]
    paddle.seed(0)
    gru2 = nn.GRU(4, 8, time_major=False)
    outs2, _ = gru2(paddle.to_tensor(np.swapaxes(x, 0, 1)))
    np.testing.assert_allclose(np.asarray(outs._array),
                               np.swapaxes(np.asarray(outs2._array), 0, 1),
                               atol=1e-6)


def test_learnable_initial_state_gets_gradient():
    from paddle_tpu.core.tensor import Parameter

    paddle.seed(0)
    lstm = nn.LSTM(4, 8)
    h0 = Parameter(np.zeros((1, 2, 8), np.float32))
    c0 = Parameter(np.zeros((1, 2, 8), np.float32))
    x = paddle.to_tensor(
        np.random.RandomState(8).randn(2, 5, 4).astype(np.float32))
    outs, _ = lstm(x, initial_states=(h0, c0))
    outs.sum().backward()
    assert h0.grad is not None and c0.grad is not None
    assert float(np.abs(np.asarray(h0.grad._array)).sum()) > 0
    # cell-level learnable state too
    cell = nn.GRUCell(4, 8)
    s0 = Parameter(np.zeros((2, 8), np.float32))
    out, _ = cell(paddle.to_tensor(
        np.random.RandomState(9).randn(2, 4).astype(np.float32)), s0)
    out.sum().backward()
    assert s0.grad is not None
