"""Tensor-op parity batch (closing the paddle.* surface gap): special
functions, complex accessors, index/search ops, splits, linalg extras —
each checked against its numpy/scipy reference.
"""
import numpy as np
import pytest
import scipy.special as sps

import paddle_tpu as paddle

RS = np.random.RandomState(0)


def _t(a):
    return paddle.to_tensor(np.asarray(a))


def test_special_functions_match_scipy():
    x = RS.rand(32).astype(np.float32) * 0.8 + 0.1
    np.testing.assert_allclose(paddle.digamma(_t(x)).numpy(),
                               sps.digamma(x), rtol=1e-4)
    np.testing.assert_allclose(paddle.lgamma(_t(x)).numpy(),
                               sps.gammaln(x), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(paddle.i0(_t(x)).numpy(), sps.i0(x),
                               rtol=1e-4)
    np.testing.assert_allclose(paddle.erfinv(_t(x)).numpy(),
                               sps.erfinv(x), rtol=1e-3)
    np.testing.assert_allclose(
        paddle.polygamma(_t(x), 1).numpy(), sps.polygamma(1, x), rtol=1e-3)
    np.testing.assert_allclose(paddle.logit(_t(x)).numpy(),
                               sps.logit(x), rtol=1e-4)


def test_elementwise_binary_parity():
    a = RS.randn(16).astype(np.float32)
    b = RS.randn(16).astype(np.float32) + 0.1
    for name in ["copysign", "nextafter", "heaviside", "hypot",
                 "logaddexp", "fmod", "remainder"]:
        ours = getattr(paddle, name)(_t(a), _t(b)).numpy()
        ref = getattr(np, name)(a, b)
        np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-6,
                                   err_msg=name)
    np.testing.assert_allclose(paddle.frac(_t(a)).numpy(),
                               a - np.trunc(a), rtol=1e-5)
    np.testing.assert_allclose(paddle.sinc(_t(a)).numpy(), np.sinc(a),
                               rtol=1e-4, atol=1e-5)
    assert (paddle.signbit(_t(a)).numpy() == np.signbit(a)).all()


def test_complex_accessors():
    r = RS.randn(8).astype(np.float32)
    i = RS.randn(8).astype(np.float32)
    c = paddle.complex(_t(r), _t(i))
    np.testing.assert_allclose(paddle.real(c).numpy(), r, rtol=1e-6)
    np.testing.assert_allclose(paddle.imag(c).numpy(), i, rtol=1e-6)
    np.testing.assert_allclose(paddle.conj(c).numpy(), r - 1j * i,
                               rtol=1e-6)
    np.testing.assert_allclose(paddle.angle(c).numpy(),
                               np.angle(r + 1j * i), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(paddle.sgn(c).numpy(),
                               (r + 1j * i) / np.abs(r + 1j * i),
                               rtol=1e-4)


def test_take_modes():
    x = _t(np.arange(12, dtype=np.float32).reshape(3, 4))
    np.testing.assert_allclose(
        paddle.take(x, np.array([0, 5, -1])).numpy(), [0, 5, 11])
    np.testing.assert_allclose(
        paddle.take(x, np.array([13]), mode="wrap").numpy(), [1])
    np.testing.assert_allclose(
        paddle.take(x, np.array([13]), mode="clip").numpy(), [11])
    with pytest.raises(IndexError):
        paddle.take(x, np.array([100]))


def test_searchsorted_and_bucketize():
    seq = np.array([1.0, 3.0, 5.0, 7.0], np.float32)
    vals = np.array([0.0, 3.0, 6.0, 9.0], np.float32)
    np.testing.assert_array_equal(
        paddle.searchsorted(_t(seq), _t(vals)).numpy(),
        np.searchsorted(seq, vals))
    np.testing.assert_array_equal(
        paddle.searchsorted(_t(seq), _t(vals), right=True).numpy(),
        np.searchsorted(seq, vals, side="right"))
    np.testing.assert_array_equal(
        paddle.bucketize(_t(vals), _t(seq)).numpy(),
        np.searchsorted(seq, vals))


def test_as_strided_and_diff():
    x = np.arange(12, dtype=np.float32)
    out = paddle.as_strided(_t(x), [3, 4], [4, 1]).numpy()
    np.testing.assert_allclose(out, x.reshape(3, 4))
    # overlapping windows: classic stride trick
    win = paddle.as_strided(_t(x), [5, 3], [2, 1]).numpy()
    ref = np.lib.stride_tricks.as_strided(x, (5, 3), (8, 4))
    np.testing.assert_allclose(win, ref)
    d = RS.randn(4, 6).astype(np.float32)
    np.testing.assert_allclose(paddle.diff(_t(d)).numpy(),
                               np.diff(d), rtol=1e-6)
    np.testing.assert_allclose(paddle.diff(_t(d), n=2, axis=0).numpy(),
                               np.diff(d, n=2, axis=0), rtol=1e-5)


def test_scatter_nd():
    idx = np.array([[1], [3], [1]], np.int64)
    upd = np.array([9.0, 10.0, 11.0], np.float32)
    out = paddle.scatter_nd(_t(idx), _t(upd), [6]).numpy()
    np.testing.assert_allclose(out, [0, 20, 0, 10, 0, 0])  # adds collide


def test_splits_and_swaps():
    x = RS.randn(4, 6, 8).astype(np.float32)
    vs = paddle.vsplit(_t(x), 2)
    assert len(vs) == 2 and vs[0].shape == [2, 6, 8]
    hs = paddle.hsplit(_t(x), 3)
    assert hs[0].shape == [4, 2, 8]
    ds = paddle.dsplit(_t(x), 4)
    assert ds[0].shape == [4, 6, 2]
    np.testing.assert_allclose(paddle.swapaxes(_t(x), 0, 2).numpy(),
                               np.swapaxes(x, 0, 2))


def test_linalg_extras():
    a = RS.randn(3, 4).astype(np.float32)
    b = RS.randn(4, 5).astype(np.float32)
    np.testing.assert_allclose(
        paddle.einsum("ij,jk->ik", _t(a), _t(b)).numpy(), a @ b,
        rtol=1e-4, atol=1e-5)
    base = RS.randn(2, 3, 5).astype(np.float32)
    x3 = RS.randn(2, 3, 4).astype(np.float32)
    y3 = RS.randn(2, 4, 5).astype(np.float32)
    np.testing.assert_allclose(
        paddle.baddbmm(_t(base), _t(x3), _t(y3), beta=0.5,
                       alpha=2.0).numpy(),
        0.5 * base + 2.0 * (x3 @ y3), rtol=1e-4, atol=1e-5)
    m = RS.randn(4, 16).astype(np.float32)
    np.testing.assert_allclose(paddle.corrcoef(_t(m)).numpy(),
                               np.corrcoef(m), rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(paddle.cov(_t(m)).numpy(), np.cov(m),
                               rtol=1e-3, atol=1e-4)
    rn = paddle.renorm(_t(m), 2.0, 0, 1.0).numpy()
    norms = np.linalg.norm(rn, axis=1)
    assert (norms <= 1.0 + 1e-4).all()


def test_reduction_extras_and_misc():
    x = RS.randn(4, 5).astype(np.float32)
    x[0, 0] = np.nan
    np.testing.assert_allclose(paddle.nanmedian(_t(x)).numpy(),
                               np.nanmedian(x), rtol=1e-6)
    y = RS.randn(8).astype(np.float32)
    np.testing.assert_allclose(paddle.trapezoid(_t(y), dx=0.5).numpy(),
                               np.trapz(y, dx=0.5), rtol=1e-5)
    assert bool(paddle.equal_all(_t(y), _t(y)).numpy())
    assert bool(paddle.allclose(_t(y), _t(y + 1e-9)).numpy())
    assert not bool(paddle.equal_all(_t(y), _t(y + 1)).numpy())
    np.testing.assert_allclose(paddle.logspace(0, 3, 4).numpy(),
                               [1, 10, 100, 1000], rtol=1e-4)
    np.testing.assert_allclose(
        paddle.vander(_t(np.array([1.0, 2.0, 3.0], np.float32))).numpy(),
        np.vander([1, 2, 3]), rtol=1e-6)
    np.testing.assert_allclose(
        paddle.diagflat(_t(np.array([1.0, 2.0], np.float32))).numpy(),
        np.diagflat([1, 2]), rtol=1e-6)
    t = _t(np.array([2.0, 3.0], np.float32))
    r = paddle.multiply_(t, _t(np.array([4.0, 5.0], np.float32)))
    assert r is t
    np.testing.assert_allclose(t.numpy(), [8, 15])


def test_new_ops_differentiable():
    x = _t(RS.rand(8).astype(np.float32) * 0.8 + 0.1)
    x.stop_gradient = False
    (paddle.digamma(x).sum() + paddle.logit(x).sum() +
     paddle.frac(x).sum()).backward()
    assert x.grad is not None
    a = _t(RS.randn(3, 4).astype(np.float32))
    a.stop_gradient = False
    paddle.einsum("ij->j", a).sum().backward()
    np.testing.assert_allclose(np.asarray(a.grad._array), 1.0)


def test_split_family_index_semantics():
    """vsplit/hsplit/dsplit take split INDICES (numpy/paddle), not
    section sizes."""
    x = np.arange(24, dtype=np.float32).reshape(6, 4)
    parts = paddle.vsplit(_t(x), [2, 4])
    assert [p.shape[0] for p in parts] == [2, 2, 2]
    np.testing.assert_allclose(parts[1].numpy(), x[2:4])
    # hsplit works on 1-D (splits axis 0), dsplit requires 3-D
    one_d = paddle.hsplit(_t(np.arange(6, dtype=np.float32)), 2)
    assert [p.shape[0] for p in one_d] == [3, 3]
    with pytest.raises(ValueError, match="3-D"):
        paddle.dsplit(_t(x), 2)


def test_multiply_inplace_guards_grad():
    t = _t(np.array([2.0], np.float32))
    t.stop_gradient = False
    with pytest.raises(RuntimeError, match="in-place"):
        paddle.multiply_(t, _t(np.array([3.0], np.float32)))


def test_complex_broadcasts():
    r = np.ones((3, 1), np.float32)
    i = np.zeros((3, 4), np.float32)
    c = paddle.complex(_t(r), _t(i))
    assert c.shape == [3, 4]


def test_ops_accept_name_kwarg():
    x = _t(np.array([0.5], np.float32))
    paddle.lgamma(x, name="lg")
    paddle.frac(x, name="f")
    paddle.abs(x, name="a")


def test_take_invalid_mode_and_trapezoid_xor():
    x = _t(np.arange(4, dtype=np.float32))
    with pytest.raises(ValueError, match="invalid mode"):
        paddle.take(x, np.array([0]), mode="rise")
    with pytest.raises(ValueError, match="not both"):
        paddle.trapezoid(x, x=_t(np.arange(4, dtype=np.float32)), dx=0.5)


def test_new_ops_available_as_tensor_methods():
    x = _t(np.array([1.7, -0.3], np.float32))
    np.testing.assert_allclose(x.frac().numpy(), [0.7, -0.3], rtol=1e-5)
    np.testing.assert_allclose(
        x.hypot(_t(np.array([1.0, 1.0], np.float32))).numpy(),
        np.hypot([1.7, -0.3], 1.0), rtol=1e-5)
    m = _t(np.arange(12, dtype=np.float32).reshape(3, 4))
    assert m.take(np.array([5])).numpy()[0] == 5
    assert m.swapaxes(0, 1).shape == [4, 3]
    assert bool(m.allclose(m).numpy())
