"""MoE expert-parallel tests (VERDICT r2 #5): the ep>1 path must run a
REAL lax.all_to_all token exchange inside shard_map, and ep=2 training
must match ep=1 when capacity doesn't bind.

Reference analogs: incubate/distributed/models/moe/moe_layer.py:260,
operators/collective/global_scatter_op.cu.cc.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import (HybridCommunicateGroup,
                                    set_hybrid_communicate_group)
from paddle_tpu.distributed.moe import MoELayer


E = 4  # experts; capacity_factor=E -> capacity == tokens, nothing drops


def _mk_layer(ep_degree, seed=0):
    set_hybrid_communicate_group(HybridCommunicateGroup(ep=ep_degree))
    paddle.seed(seed)
    layer = MoELayer(d_model=16, d_hidden=32, num_experts=E,
                     capacity_factor=float(E))
    return layer


def _state(layer):
    return {k: np.asarray(v._array) for k, v in layer.state_dict().items()}


def test_ep2_forward_parity():
    """Same weights, same input: ep=2 output == ep=1 output (no token
    drops at capacity_factor=E)."""
    x_np = np.random.RandomState(0).uniform(-1, 1, (2, 8, 16)).astype(np.float32)

    l1 = _mk_layer(1, seed=3)
    w = _state(l1)
    y1 = l1(paddle.to_tensor(x_np))
    aux1 = float(l1.aux_loss._array if hasattr(l1.aux_loss, "_array")
                 else l1.aux_loss)

    l2 = _mk_layer(2, seed=3)
    l2.set_state_dict(w)
    y2 = l2(paddle.to_tensor(x_np))
    aux2 = float(l2.aux_loss._array if hasattr(l2.aux_loss, "_array")
                 else l2.aux_loss)

    set_hybrid_communicate_group(HybridCommunicateGroup())  # reset
    np.testing.assert_allclose(np.asarray(y1._array), np.asarray(y2._array),
                               rtol=1e-4, atol=1e-5)
    # ep gating runs per shard: aux is the mean of per-shard losses, not
    # identical to the global one — but should be close for uniform data
    assert abs(aux1 - aux2) < 0.5


def test_ep2_contains_all_to_all():
    """The claim under test: ep>1 dispatch really compiles to all-to-all
    collectives (not annotation-only)."""
    import jax

    l2 = _mk_layer(2, seed=1)
    x = paddle.to_tensor(
        np.random.uniform(-1, 1, (2, 8, 16)).astype(np.float32))

    def f(xa, w1, b1, w2, b2, gw):
        l2.gate_proj.weight._array = gw
        l2.w1._array, l2.b1._array = w1, b1
        l2.w2._array, l2.b2._array = w2, b2
        from paddle_tpu.core.tensor import Tensor

        return l2(Tensor._wrap(xa))._array

    hlo = jax.jit(f).lower(
        x._array, l2.w1._array, l2.b1._array, l2.w2._array, l2.b2._array,
        l2.gate_proj.weight._array).as_text()
    set_hybrid_communicate_group(HybridCommunicateGroup())
    assert "all_to_all" in hlo or "all-to-all" in hlo, \
        "ep>1 MoE must lower to all_to_all"


class TinyMoENet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.inp = nn.Linear(8, 16)
        self.moe = MoELayer(d_model=16, d_hidden=32, num_experts=E,
                            capacity_factor=float(E))
        self.out = nn.Linear(16, 4)

    def forward(self, x):
        h = F.relu(self.inp(x))
        h = self.moe(h.reshape([h.shape[0], 1, 16]))
        return self.out(h.reshape([h.shape[0], 16]))


def test_ep2_training_parity():
    """ep=2 DistributedTrainStep loss trace == ep=1 TrainStep loss trace
    (the hybrid_parallel parity-test pattern, test_dist_base.py style)."""
    import paddle_tpu.jit as jit
    from paddle_tpu.distributed import DistributedTrainStep

    rng = np.random.RandomState(7)
    xs = rng.uniform(-1, 1, (4, 8, 8)).astype(np.float32)
    ys = rng.randint(0, 4, (4, 8)).astype(np.int64)

    def loss_fn(logits, label):
        return F.cross_entropy(logits, label)

    def run(ep_degree):
        hcg = HybridCommunicateGroup(ep=ep_degree)
        set_hybrid_communicate_group(hcg)
        paddle.seed(0)
        net = TinyMoENet()
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=net.parameters())
        if ep_degree > 1:
            step = DistributedTrainStep(net, opt, loss_fn, hcg=hcg,
                                        batch_axes=("dp",))
        else:
            step = jit.TrainStep(net, opt, loss_fn)
        losses = []
        for i in range(4):
            losses.append(float(step(paddle.to_tensor(xs[i]),
                                     paddle.to_tensor(ys[i]))))
        return losses

    base = run(1)
    ep2 = run(2)
    set_hybrid_communicate_group(HybridCommunicateGroup())
    np.testing.assert_allclose(base, ep2, rtol=2e-4, atol=1e-5)


def test_switch_gate_ep2():
    x_np = np.random.RandomState(1).uniform(-1, 1, (2, 8, 16)).astype(np.float32)
    set_hybrid_communicate_group(HybridCommunicateGroup(ep=2))
    paddle.seed(5)
    layer = MoELayer(d_model=16, d_hidden=32, num_experts=E, gate="switch",
                     capacity_factor=float(E))
    y = layer(paddle.to_tensor(x_np))
    set_hybrid_communicate_group(HybridCommunicateGroup())
    assert y.shape == [2, 8, 16]
    assert np.all(np.isfinite(np.asarray(y._array)))
