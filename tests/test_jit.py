"""jit/to_static tests — eager vs compiled parity (the dy2static test
pattern, unittests/dygraph_to_static/ analog)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_to_static_matches_eager():
    @paddle.jit.to_static
    def fn(x, y):
        return paddle.tanh(x @ y) * 2.0

    a, b = paddle.randn([3, 4]), paddle.randn([4, 5])
    out = fn(a, b)
    expect = np.tanh(a.numpy() @ b.numpy()) * 2
    np.testing.assert_allclose(out.numpy(), expect, rtol=1e-4)


def test_to_static_cache():
    calls = []

    @paddle.jit.to_static
    def fn(x):
        calls.append(1)
        return x * 2.0

    fn(paddle.randn([2, 2]))
    fn(paddle.randn([2, 2]))  # same spec: no retrace
    assert len(calls) == 1
    fn(paddle.randn([3, 2]))  # new shape: retrace
    assert len(calls) == 2
    assert len(fn.concrete_programs) == 2


def test_to_static_python_control_flow_static_branch():
    @paddle.jit.to_static
    def fn(x, flag):
        if flag:  # static python value — baked per cache entry
            return x + 1.0
        return x - 1.0

    x = paddle.zeros([2])
    np.testing.assert_allclose(fn(x, True).numpy(), [1, 1])
    np.testing.assert_allclose(fn(x, False).numpy(), [-1, -1])


def test_to_static_layer_forward():
    layer = nn.Linear(4, 2)
    eager_out = layer(paddle.ones([1, 4]))
    st = paddle.jit.to_static(layer)
    out = st(paddle.ones([1, 4]))
    np.testing.assert_allclose(out.numpy(), eager_out.numpy(), rtol=1e-5)


def test_grad_inside_to_static():
    """Whole fwd+bwd collapses into one XLA computation."""

    @paddle.jit.to_static
    def loss_and_grad(x, w):
        w.stop_gradient = False
        loss = ((x @ w) ** 2.0).sum()
        (gw,) = paddle.grad(loss, w)
        return loss, gw

    x = paddle.randn([3, 4])
    w = paddle.randn([4, 2])
    loss, gw = loss_and_grad(x, w)
    # reference grad: d/dw sum((xw)^2) = 2 x^T (xw)
    expect = 2 * x.numpy().T @ (x.numpy() @ w.numpy())
    np.testing.assert_allclose(gw.numpy(), expect, rtol=1e-4)


def test_train_step_compiled():
    paddle.seed(1)
    net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
    opt = paddle.optimizer.Adam(learning_rate=0.05, parameters=net.parameters())
    step = paddle.jit.TrainStep(net, opt, lambda out, y: F.mse_loss(out, y))

    x = paddle.randn([16, 4])
    y = (x @ paddle.to_tensor([[1.0], [2.0], [-1.0], [0.5]]))
    losses = [float(step(x, y)) for _ in range(30)]
    assert losses[-1] < losses[0] * 0.2


def test_train_step_matches_eager():
    """Compiled TrainStep must produce the same params as eager loop."""

    def build():
        paddle.seed(3)
        net = nn.Linear(3, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
        return net, opt

    x = paddle.randn([8, 3])
    y = paddle.randn([8, 1])

    net1, opt1 = build()
    for _ in range(3):
        loss = F.mse_loss(net1(x), y)
        loss.backward()
        opt1.step()
        opt1.clear_grad()

    net2, opt2 = build()
    step = paddle.jit.TrainStep(net2, opt2, lambda o, t: F.mse_loss(o, t))
    for _ in range(3):
        step(x, y)

    np.testing.assert_allclose(net1.weight.numpy(), net2.weight.numpy(), rtol=1e-4)
    np.testing.assert_allclose(net1.bias.numpy(), net2.bias.numpy(), rtol=1e-4)


def test_jit_save_load(tmp_path):
    layer = nn.Linear(4, 2)
    path = str(tmp_path / "model")
    paddle.jit.save(layer, path)
    loaded = paddle.jit.load(path)
    fresh = nn.Linear(4, 2)
    loaded.load_into(fresh)
    np.testing.assert_allclose(fresh.weight.numpy(), layer.weight.numpy())


def test_paddle_save_load(tmp_path):
    net = nn.Sequential(nn.Linear(2, 3), nn.Linear(3, 2))
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=net.parameters())
    p = net.parameters()[0]
    p.grad = paddle.ones_like(p)
    opt.step()

    paddle.save(net.state_dict(), str(tmp_path / "model.pdparams"))
    paddle.save(opt.state_dict(), str(tmp_path / "opt.pdopt"))

    net2 = nn.Sequential(nn.Linear(2, 3), nn.Linear(3, 2))
    net2.set_state_dict(paddle.load(str(tmp_path / "model.pdparams")))
    opt2 = paddle.optimizer.Adam(learning_rate=0.01, parameters=net2.parameters())
    opt2.set_state_dict(paddle.load(str(tmp_path / "opt.pdopt")))

    np.testing.assert_allclose(
        net2.parameters()[0].numpy(), net.parameters()[0].numpy())
    assert opt2._step_count == 1


def test_bf16_save_load_roundtrip(tmp_path):
    t = paddle.to_tensor([1.5, 2.5], dtype="bfloat16")
    paddle.save({"w": t}, str(tmp_path / "bf16.pd"))
    loaded = paddle.load(str(tmp_path / "bf16.pd"))
    assert loaded["w"].dtype == "bfloat16"
    np.testing.assert_allclose(
        loaded["w"].astype("float32").numpy(), [1.5, 2.5])
