"""Tensor-parallel sharded serving (ISSUE 8).

ONE logical GenerationEngine scheduler driving shard_map-compiled
steps over an mp-axis device mesh (virtual CPU devices in CI — the
conftest forces --xla_force_host_platform_device_count=8, so the REAL
mp=2/mp=4 programs compile and run here). The contract, proven the
way PR 3/6/7 proved theirs:

- token-EXACT parity vs the mp=1 engine across
  {dense, pallas} x {chunked, bucketed} x {cold, warm prefix cache}
  x K in {0, 4}, with mid-run admissions and cache evictions in the
  trace — exactness by construction (column-parallel sharding: every
  dot stays full length, activations reassembled by exact gathers),
  not by tolerance;
- `decode_traces == 1` per (backend, K, mesh shape) and steady-state
  `expect_traces(0)`; donation of the sharded pools wires up;
- the serving-mesh helper fails loudly on indivisible shapes;
- mesh/shard observability: `engine_mesh_info`, shard-labeled pool
  gauges, and exact per-shard folding through merge_snapshots.
"""
import copy

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.jit as jit
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.inference import GenerationEngine
from paddle_tpu.observability.metrics import merge_snapshots, \
    series_total

VOCAB = 64          # divisible by mp in {2, 4}


def _model(seed=0):
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(seed)
    cfg = GPTConfig.tiny(vocab=VOCAB, hidden=32, layers=2, heads=4,
                         seq=64)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return _model()


def _reference(model, prompt, max_new):
    out = model.generate(
        Tensor._wrap(np.asarray(prompt, np.int32)[None]),
        max_length=len(prompt) + max_new, use_cache=True)
    return list(map(int, np.asarray(out._array)[0]))


def _mixed_trace(rng, n=4):
    """Mixed lengths + a hot shared prefix + a block-aligned
    full-prefix hit (block_size 4)."""
    reqs = [(rng.randint(0, VOCAB, rng.randint(2, 13)).astype(np.int32),
             int(rng.randint(2, 7))) for _ in range(n)]
    shared = rng.randint(0, VOCAB, 8).astype(np.int32)
    reqs += [(np.concatenate([shared, rng.randint(0, VOCAB, 3)])
              .astype(np.int32), 4),
             (shared.copy(), 4)]
    return reqs


def _run_trace(eng, reqs, midrun=True):
    ids = [eng.add_request(p, n) for p, n in reqs[:len(reqs) // 2]]
    if midrun:
        for _ in range(2):
            eng.step()                 # admissions land mid-decode
    ids += [eng.add_request(p, n) for p, n in reqs[len(reqs) // 2:]]
    out = eng.run()
    return [list(map(int, out[rid])) for rid in ids]


# ---------------------------------------------------------------------------
# tentpole: token-exact parity across the whole serving matrix
# ---------------------------------------------------------------------------

def _assert_parity_matrix(model, backend, K):
    """One mixed trace (shared prefixes, a full-prefix hit, mid-run
    admissions) served at mp=1, mp=2 and mp=4 in (a) chunked + prefix
    cache cold, (b) same engine warm, (c) legacy bucketed prefill —
    all token-identical across mesh shapes, with ONE decode trace per
    (backend, K, mesh shape)."""
    rng = np.random.RandomState(11)
    reqs = _mixed_trace(rng)

    def serve(mp):
        def mk(**kw):
            return GenerationEngine(model, num_slots=3, block_size=4,
                                    num_blocks=64, spec_decode_k=K,
                                    attention_backend=backend,
                                    mp_degree=mp, **kw)

        eng = mk(prefill_chunk=8)
        cold = _run_trace(eng, reqs)
        warm = _run_trace(eng, reqs, midrun=False)   # hot cache
        eng_b = mk(prefill_buckets=(16, 64))
        bucketed = _run_trace(eng_b, reqs)
        assert eng.prefix_hit_tokens > 0
        for e in (eng, eng_b):
            assert e.decode_traces == 1, \
                f"mp={mp} {backend} K={K}: decode retraced"
        return cold, warm, bucketed

    ref = serve(None)
    for mp in (2, 4):
        assert serve(mp) == ref, \
            f"mp={mp} {backend} K={K}: output diverged from mp=1"
    # anchor the mp=1 reference itself against the compiled-decode
    # oracle (the cheaper spec/prefix suites prove this exhaustively)
    p, n = reqs[0]
    assert ref[0][0] == _reference(model, p, n)


@pytest.mark.parametrize("backend,K", [("dense", 0), ("pallas", 4)])
def test_sharded_token_identical_across_modes(model, monkeypatch,
                                              backend, K):
    """THE acceptance gate, tier-1 cut: both backends and both K
    values across mp in {1, 2, 4} x {chunked cold, warm, bucketed}.
    The two complementary (backend, K) cells run in the slow-marked
    full-matrix test below — together the 2x2 product is covered."""
    monkeypatch.delenv("PADDLE_SERVE_MP", raising=False)
    monkeypatch.delenv("PADDLE_SPEC_DECODE_K", raising=False)
    monkeypatch.delenv("PADDLE_PAGED_ATTENTION_BACKEND", raising=False)
    _assert_parity_matrix(model, backend, K)


@pytest.mark.slow
@pytest.mark.parametrize("backend,K", [("dense", 4), ("pallas", 0)])
def test_sharded_token_identical_full_matrix(model, monkeypatch,
                                             backend, K):
    """The remaining (backend, K) cells of the acceptance matrix —
    identical machinery, kept out of the timed tier-1 window."""
    monkeypatch.delenv("PADDLE_SERVE_MP", raising=False)
    monkeypatch.delenv("PADDLE_SPEC_DECODE_K", raising=False)
    monkeypatch.delenv("PADDLE_PAGED_ATTENTION_BACKEND", raising=False)
    _assert_parity_matrix(model, backend, K)


def test_sharded_eviction_under_pressure_stays_exact(model,
                                                     monkeypatch):
    """A pool tight enough to evict cached prefix blocks mid-trace
    (the PR-6 pressure path) behaves identically on the sharded
    engine: same outputs, same host-side allocator story, stalls
    surfaced on the shard-labeled counter."""
    monkeypatch.delenv("PADDLE_SERVE_MP", raising=False)
    rng = np.random.RandomState(7)
    reqs = _mixed_trace(rng, n=3)

    def serve(mp):
        eng = GenerationEngine(model, num_slots=2, block_size=4,
                               num_blocks=10, prefill_chunk=8,
                               mp_degree=mp)
        outs = _run_trace(eng, reqs) + _run_trace(eng, reqs,
                                                  midrun=False)
        assert eng.cache.num_free == eng.cache.num_blocks - 1
        return outs, eng

    ref, _ = serve(None)
    got, eng2 = serve(2)
    assert got == ref
    snap = eng2.metrics_snapshot()
    for s in snap["engine_block_stalls_total"]["series"]:
        assert s["labels"]["shard"] == "0"


# ---------------------------------------------------------------------------
# trace stability + donation on the sharded step
# ---------------------------------------------------------------------------

def test_sharded_steady_state_and_donated_pools(model, monkeypatch):
    """A warmed mp=2 engine retraces NOTHING on further churn, and the
    donated sharded pools compile and run (donation demands matching
    input/output shardings — this is the aliasing contract check the
    virtual mesh can express)."""
    monkeypatch.delenv("PADDLE_SERVE_MP", raising=False)
    rng = np.random.RandomState(3)
    reqs = [(rng.randint(0, VOCAB, 6).astype(np.int32), 4)
            for _ in range(3)]
    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           num_blocks=64, prefill_chunk=8,
                           mp_degree=2, donate=True)
    assert eng._donate_argnums == (1, 2)     # pools stay donated
    ids = [eng.add_request(p, n) for p, n in reqs]
    out = eng.run()
    for (p, n), rid in zip(reqs, ids):
        assert list(map(int, out[rid])) == _reference(model, p, n)
    with jit.expect_traces(eng._decode_pure, 0), \
            jit.expect_traces(eng._prefill_pure, 0):
        eng.add_request(rng.randint(0, VOCAB, 9).astype(np.int32), 5)
        eng.run()


def test_refresh_weights_resnapshots_the_sharded_state():
    """The tensor-parallel engine serves a weight-stationary SNAPSHOT
    (placed on the mesh once); refresh_weights() re-shards after a
    live weight update — without it the mp engine intentionally keeps
    serving the placed weights."""
    m = _model(seed=3)
    prompt = np.arange(5, dtype=np.int32)
    eng = GenerationEngine(m, num_slots=1, block_size=4,
                           prefill_chunk=8, mp_degree=2)
    rid = eng.add_request(prompt, 4)
    before = list(map(int, eng.run()[rid]))
    assert before == _reference(m, prompt, 4)
    # perturb the embedding enough to change the greedy stream
    w = m.gpt.wte.weight
    w._array = -w._array
    want = _reference(m, prompt, 4)
    eng.refresh_weights()
    rid = eng.add_request(prompt, 4)
    assert list(map(int, eng.run()[rid])) == want


# ---------------------------------------------------------------------------
# satellite: serving-mesh construction + validation
# ---------------------------------------------------------------------------

def test_serving_mesh_and_divisibility_validation(model, monkeypatch):
    import jax

    from paddle_tpu.distributed import serving_mesh
    from paddle_tpu.distributed.topology import HybridCommunicateGroup
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    monkeypatch.delenv("PADDLE_SERVE_MP", raising=False)
    mesh = serving_mesh(2)
    assert mesh.axis_names == ("mp",) and mesh.size == 2
    # the convenience topology builds without a dp/pp/sharding launch
    hcg = HybridCommunicateGroup.for_serving(2)
    assert hcg.get_model_parallel_world_size() == 2
    # clear errors UP FRONT, not deep inside a reshape
    with pytest.raises(ValueError, match="num_heads"):
        serving_mesh(3, num_heads=4)
    with pytest.raises(ValueError, match="vocab"):
        serving_mesh(4, num_heads=4, vocab_size=62)
    with pytest.raises(ValueError, match="devices"):
        serving_mesh(2 * len(jax.devices()))
    # an explicitly passed mesh is validated too
    paddle.seed(1)
    cfg = GPTConfig.tiny(vocab=63, hidden=32, heads=2, layers=1,
                         seq=32)
    odd = GPTForCausalLM(cfg)
    odd.eval()
    with pytest.raises(ValueError, match="vocab"):
        GenerationEngine(odd, mesh=serving_mesh(2))
    cfg2 = GPTConfig.tiny(vocab=VOCAB, hidden=32, heads=4, layers=1,
                          seq=32)
    cfg2.intermediate_size = 50
    mlp_odd = GPTForCausalLM(cfg2)
    mlp_odd.eval()
    with pytest.raises(ValueError, match="intermediate_size"):
        GenerationEngine(mlp_odd, mp_degree=4)
    # a mesh without an mp axis is rejected
    from jax.sharding import Mesh

    with pytest.raises(ValueError, match="'mp' axis"):
        GenerationEngine(model, mesh=Mesh(
            np.asarray(jax.devices()[:2]), ("dp",)))


def test_serve_mp_env_override_wins(model, monkeypatch):
    monkeypatch.setenv("PADDLE_SERVE_MP", "2")
    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           prefill_chunk=8)
    assert eng.mp_degree == 2 and eng.mesh is not None
    # env conflicting with an explicit mesh fails loudly
    from paddle_tpu.distributed import serving_mesh

    with pytest.raises(ValueError, match="PADDLE_SERVE_MP"):
        GenerationEngine(model, mesh=serving_mesh(4))
    monkeypatch.setenv("PADDLE_SERVE_MP", "x")
    with pytest.raises(ValueError, match="PADDLE_SERVE_MP"):
        GenerationEngine(model, prefill_chunk=8)
    monkeypatch.delenv("PADDLE_SERVE_MP")
    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           prefill_chunk=8, mp_degree=1)
    assert eng.mp_degree == 1 and eng.mesh is None


def test_pool_spec_is_the_single_source_of_truth(model):
    """ISSUE 8 satellite (latent-bug fix): both pool constructors
    derive `[L, B, bs, H, D]`/dtype from pool_spec(), so the sharded
    and unsharded layouts cannot drift."""
    from paddle_tpu.distributed import serving_mesh
    from paddle_tpu.inference import PagedKVCache

    import jax.numpy as jnp

    plain = PagedKVCache(2, 8, 4, 4, 8, dtype=jnp.float32)
    shard = PagedKVCache(2, 8, 4, 4, 8, dtype=jnp.float32,
                         mesh=serving_mesh(2))
    assert plain.pool_spec() == shard.pool_spec()
    for c in (plain, shard):
        shape, dt = c.pool_spec()
        assert tuple(c.kpool.shape) == shape == (2, 8, 4, 4, 8)
        assert c.vpool.dtype == dt
    assert str(plain.pool_pspec()) == "PartitionSpec()"
    assert shard.pool_pspec()[3] == "mp"
    with pytest.raises(ValueError, match="num_heads"):
        PagedKVCache(2, 8, 4, 3, 8, mesh=serving_mesh(2))


# ---------------------------------------------------------------------------
# satellite: mesh/shard observability (the engine-metrics test at mp=2)
# ---------------------------------------------------------------------------

def test_engine_metrics_on_the_mp2_virtual_mesh(model, monkeypatch):
    """The PR-2 engine-metrics contract re-proven on the sharded
    engine, plus the mesh-info gauge and shard-labeled pool series;
    merge_snapshots folds two shards' snapshots EXACTLY (side-by-side
    series, summed counters)."""
    monkeypatch.delenv("PADDLE_SERVE_MP", raising=False)
    rng = np.random.RandomState(5)
    reqs = [(rng.randint(0, VOCAB, rng.randint(2, 9)).astype(np.int32),
             int(rng.randint(2, 6))) for _ in range(4)]
    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           num_blocks=32, prefill_chunk=8,
                           mp_degree=2)
    for p, n in reqs:
        eng.add_request(p, n)
    eng.run()
    snap = eng.metrics_snapshot()
    # core serving contract holds under the mesh
    new_tokens = sum(n for _, n in reqs)
    assert series_total(snap, "engine_admissions_total") == len(reqs)
    assert series_total(snap, "engine_tokens_generated_total") \
        == new_tokens
    ttft = snap["engine_ttft_seconds"]["series"][0]
    assert ttft["count"] == len(reqs) and ttft["sum"] > 0
    assert series_total(snap, "engine_decode_recompiles_total") == 0
    assert snap["engine_decode_traces"]["series"][0]["value"] == 1
    # mesh info: one series naming the degree and device count
    mesh_info = snap["engine_mesh_info"]["series"]
    assert [s["labels"] for s in mesh_info] \
        == [{"mp_degree": "2", "devices": "2"}]
    assert mesh_info[0]["value"] == 1
    # pool gauges are shard-labeled
    used = snap["engine_pool_used_blocks"]["series"]
    assert [s["labels"] for s in used] == [{"shard": "0"}]
    assert snap["engine_pool_used_high_water_blocks"]["series"][0][
        "labels"] == {"shard": "0"}
    # two shards' snapshots fold EXACTLY: distinct shard labels stay
    # side-by-side (no cross-shard min/max/mean blur), counters sum
    other = copy.deepcopy(snap)
    for fam in other.values():
        for s in fam.get("series", []):
            if "shard" in s.get("labels", {}):
                s["labels"]["shard"] = "1"
    merged = merge_snapshots([snap, other])
    used = {s["labels"]["shard"]: s for s in
            merged["engine_pool_used_blocks"]["series"]}
    assert set(used) == {"0", "1"}
    hw = {s["labels"]["shard"]: s for s in
          merged["engine_pool_used_high_water_blocks"]["series"]}
    assert hw["0"]["min"] == hw["0"]["max"] \
        == snap["engine_pool_used_high_water_blocks"]["series"][0][
            "value"]
    assert series_total(merged, "engine_tokens_generated_total") \
        == 2 * new_tokens
    # prometheus exposition renders the new labels
    text = eng.metrics.render_prometheus()
    assert 'engine_mesh_info{mp_degree="2",devices="2"} 1' in text
    assert 'engine_pool_used_blocks{shard="0"}' in text


# ---------------------------------------------------------------------------
# satellite: bench row (CI-scale runner + suite registration)
# ---------------------------------------------------------------------------

def test_offered_load_mp2_bench_row(monkeypatch):
    """The gpt_engine_offered_load_mp2 SUITE_ROWS runner at test
    scale: serves the same trace at mp=1 then mp=2, asserts the
    outputs identical inside the runner, and records both tokens/s."""
    monkeypatch.delenv("PADDLE_SERVE_MP", raising=False)
    monkeypatch.delenv("PADDLE_PAGED_ATTENTION_BACKEND", raising=False)
    import bench_ops
    from paddle_tpu.models import GPTConfig

    cfg = GPTConfig.tiny(vocab=32, hidden=16, layers=1, heads=2,
                         seq=32)
    paddle.seed(0)
    rec = bench_ops._engine_offered_load_case(
        model_cfg=cfg, requests=[(3, 4), (6, 4), (10, 3)],
        num_slots=2, block_size=4, prefill_buckets=(4, 8, 16, 32),
        mp_degree=2)()
    assert rec["mp_degree"] == 2 and rec["devices"] == 2
    assert rec["tokens_per_s"] > 0 and rec["tokens_per_s_mp1"] > 0
    assert rec["requests"] == 3
    assert rec["decode_recompiles"] == 0
    assert "gpt_engine_offered_load_mp2" in bench_ops.suite_names()
