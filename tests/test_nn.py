"""nn layer tests vs numpy references (OpTest pattern, SURVEY §4)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_linear():
    layer = nn.Linear(4, 3)
    x = paddle.randn([2, 4])
    y = layer(x)
    assert y.shape == [2, 3]
    expect = x.numpy() @ layer.weight.numpy() + layer.bias.numpy()
    np.testing.assert_allclose(y.numpy(), expect, rtol=1e-5)


def test_linear_no_bias():
    layer = nn.Linear(4, 3, bias_attr=False)
    assert layer.bias is None
    y = layer(paddle.randn([2, 4]))
    assert y.shape == [2, 3]


def test_layer_registration():
    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(4, 8)
            self.fc2 = nn.Linear(8, 2)

        def forward(self, x):
            return self.fc2(F.relu(self.fc1(x)))

    m = M()
    params = m.parameters()
    assert len(params) == 4
    names = [n for n, _ in m.named_parameters()]
    assert "fc1.weight" in names and "fc2.bias" in names
    y = m(paddle.randn([3, 4]))
    assert y.shape == [3, 2]


def test_state_dict_roundtrip():
    m = nn.Linear(3, 3)
    sd = m.state_dict()
    m2 = nn.Linear(3, 3)
    m2.set_state_dict({k: v.numpy() for k, v in sd.items()})
    np.testing.assert_allclose(m2.weight.numpy(), m.weight.numpy())


def test_conv2d():
    conv = nn.Conv2D(3, 8, 3, padding=1)
    x = paddle.randn([2, 3, 16, 16])
    y = conv(x)
    assert y.shape == [2, 8, 16, 16]
    # stride 2
    conv2 = nn.Conv2D(3, 8, 3, stride=2, padding=1)
    assert conv2(x).shape == [2, 8, 8, 8]


def test_conv2d_matches_numpy():
    # 1x1 conv == matmul over channels
    conv = nn.Conv2D(3, 5, 1, bias_attr=False)
    x = paddle.randn([1, 3, 4, 4])
    y = conv(x).numpy()
    w = conv.weight.numpy().reshape(5, 3)
    expect = np.einsum("oc,nchw->nohw", w, x.numpy())
    np.testing.assert_allclose(y, expect, rtol=1e-4, atol=1e-5)


def test_batchnorm_train_eval():
    bn = nn.BatchNorm2D(4)
    x = paddle.randn([8, 4, 5, 5])
    bn.train()
    y = bn(x)
    out = y.numpy()
    np.testing.assert_allclose(out.mean(axis=(0, 2, 3)), 0.0, atol=1e-5)
    np.testing.assert_allclose(out.std(axis=(0, 2, 3)), 1.0, atol=1e-2)
    # running stats moved
    assert not np.allclose(bn._mean.numpy(), 0.0)
    bn.eval()
    y2 = bn(x)
    assert y2.shape == [8, 4, 5, 5]


def test_layernorm():
    ln = nn.LayerNorm(8)
    x = paddle.randn([2, 3, 8])
    y = ln(x).numpy()
    np.testing.assert_allclose(y.mean(-1), 0.0, atol=1e-5)
    np.testing.assert_allclose(y.std(-1), 1.0, atol=1e-2)


def test_embedding():
    emb = nn.Embedding(10, 4)
    ids = paddle.to_tensor([[1, 2], [3, 4]])
    y = emb(ids)
    assert y.shape == [2, 2, 4]
    np.testing.assert_allclose(y.numpy()[0, 0], emb.weight.numpy()[1])


def test_dropout():
    drop = nn.Dropout(0.5)
    x = paddle.ones([1000])
    drop.train()
    y = drop(x)
    kept = (y.numpy() != 0).mean()
    assert 0.3 < kept < 0.7
    np.testing.assert_allclose(y.numpy()[y.numpy() != 0], 2.0)
    drop.eval()
    np.testing.assert_allclose(drop(x).numpy(), x.numpy())


def test_activations():
    x = paddle.to_tensor([-2.0, -0.5, 0.0, 0.5, 2.0])
    np.testing.assert_allclose(F.relu(x).numpy(), [0, 0, 0, 0.5, 2])
    np.testing.assert_allclose(F.sigmoid(x).numpy(), 1 / (1 + np.exp(-x.numpy())), rtol=1e-5)
    np.testing.assert_allclose(F.tanh(x).numpy(), np.tanh(x.numpy()), rtol=1e-4)
    s = F.softmax(x).numpy()
    np.testing.assert_allclose(s.sum(), 1.0, rtol=1e-5)
    assert F.gelu(x).shape == [5]
    assert F.leaky_relu(x).numpy()[0] == pytest.approx(-0.02)


def test_losses():
    logits = paddle.randn([4, 10])
    labels = paddle.to_tensor([1, 2, 3, 4])
    loss = F.cross_entropy(logits, labels)
    assert loss.shape == []
    # manual CE
    lg = logits.numpy()
    p = np.exp(lg) / np.exp(lg).sum(-1, keepdims=True)
    expect = -np.log(p[np.arange(4), labels.numpy()]).mean()
    np.testing.assert_allclose(float(loss), expect, rtol=1e-4)

    a, b = paddle.randn([3, 2]), paddle.randn([3, 2])
    np.testing.assert_allclose(
        float(F.mse_loss(a, b)), ((a.numpy() - b.numpy()) ** 2).mean(), rtol=1e-5)
    np.testing.assert_allclose(
        float(F.l1_loss(a, b)), np.abs(a.numpy() - b.numpy()).mean(), rtol=1e-5)


def test_pooling():
    x = paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    mp_ = nn.MaxPool2D(2, 2)(x)
    np.testing.assert_allclose(mp_.numpy()[0, 0], [[5, 7], [13, 15]])
    ap = nn.AvgPool2D(2, 2)(x)
    np.testing.assert_allclose(ap.numpy()[0, 0], [[2.5, 4.5], [10.5, 12.5]])
    gap = nn.AdaptiveAvgPool2D(1)(x)
    np.testing.assert_allclose(gap.numpy()[0, 0, 0, 0], 7.5)


def test_sequential_and_layerlist():
    seq = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    y = seq(paddle.randn([2, 4]))
    assert y.shape == [2, 2]
    assert len(seq) == 3

    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    ll.append(nn.Linear(2, 2))
    assert len(ll) == 4
    assert len(nn.Sequential(*ll).parameters()) == 8


def test_multihead_attention():
    mha = nn.MultiHeadAttention(16, 4)
    x = paddle.randn([2, 5, 16])
    y = mha(x)
    assert y.shape == [2, 5, 16]


def test_transformer_encoder():
    layer = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
    enc = nn.TransformerEncoder(layer, 2)
    x = paddle.randn([2, 6, 16])
    y = enc(x)
    assert y.shape == [2, 6, 16]
    # layers are independent copies
    p0 = enc.layers[0].linear1.weight
    p1 = enc.layers[1].linear1.weight
    assert p0 is not p1


def test_grad_clip_global_norm():
    clip = nn.ClipGradByGlobalNorm(1.0)
    p = paddle.ones([4])
    g = paddle.to_tensor([10.0, 0.0, 0.0, 0.0])
    from paddle_tpu.core.tensor import Tensor

    out = clip([(p, g)])
    np.testing.assert_allclose(np.linalg.norm(out[0][1].numpy()), 1.0, rtol=1e-5)


def test_train_loop_converges():
    """End-to-end: tiny regression must reduce loss (the dist-test loss
    parity pattern, test_dist_base.py analog for single device)."""
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 16), nn.Tanh(), nn.Linear(16, 1))
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    x = paddle.randn([32, 4])
    w_true = paddle.to_tensor([[1.0], [-2.0], [0.5], [3.0]])
    y_true = x @ w_true

    losses = []
    for _ in range(50):
        loss = F.mse_loss(net(x), y_true)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.1, losses[::10]
