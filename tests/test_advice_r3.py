"""Regression tests for ADVICE round-2 findings.

1 (high): PyLayer custom backward must survive jax tracing (TrainStep /
   to_static) via jax.custom_vjp instead of being silently replaced by AD
   of the forward.
2 (medium): PipelineStack.forward records a tape node so eager
   loss.backward() reaches stacked params and upstream layers.
3 (low): version-counter only tracks requires-grad inputs.
4 (low): pipeline dropout folds slot/tick indices into the PRNG key.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.jit as jit
from paddle_tpu.autograd import PyLayer
from paddle_tpu.distributed.pipeline import LayerDesc, PipelineStack


class _ZeroGrad(PyLayer):
    @staticmethod
    def forward(ctx, x):
        return x * 1.0

    @staticmethod
    def backward(ctx, dy):
        return dy * 0.0


class _CusTanh(PyLayer):
    @staticmethod
    def forward(ctx, x):
        y = paddle.tanh(x)
        ctx.save_for_backward(y)
        return y

    @staticmethod
    def backward(ctx, dy):
        (y,) = ctx.saved_tensor()
        return dy * (1.0 - paddle.square(y))


class _PLNet(nn.Layer):
    def __init__(self, pl_cls):
        super().__init__()
        self.lin = nn.Linear(4, 4)
        self.pl_cls = pl_cls

    def forward(self, x):
        return self.pl_cls.apply(self.lin(x)).sum()


def test_pylayer_custom_backward_respected_under_trainstep():
    """A PyLayer whose backward kills the gradient must freeze weights
    under the compiled TrainStep exactly as it does in eager."""
    paddle.seed(0)
    m = _PLNet(_ZeroGrad)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    step = jit.TrainStep(m, opt, lambda out, y: out)
    w0 = m.lin.weight.numpy().copy()
    x = paddle.randn([2, 4])
    step(x, x)
    np.testing.assert_allclose(m.lin.weight.numpy(), w0)


def test_pylayer_grad_parity_eager_vs_to_static():
    x_np = np.random.RandomState(0).randn(2, 4).astype(np.float32)

    def run(static):
        paddle.seed(0)
        m = _PLNet(_CusTanh)
        f = jit.to_static(m) if static else m
        loss = f(paddle.to_tensor(x_np))
        loss.backward()
        return m.lin.weight.grad.numpy()

    np.testing.assert_allclose(run(False), run(True), atol=1e-5)


def test_pylayer_saved_tensors_under_trace():
    """ctx.save_for_backward round-trips through custom_vjp residuals."""
    paddle.seed(0)
    m = _PLNet(_CusTanh)
    opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=m.parameters())
    step = jit.TrainStep(m, opt, lambda out, y: out)
    x = paddle.randn([2, 4])
    l0 = float(step(x, x))
    l1 = float(step(x, x))
    assert l1 < l0  # gradient actually descends through the custom vjp


# -- ADVICE #2: PipelineStack eager backward --------------------------------

class _Body(nn.Layer):
    def __init__(self, d):
        super().__init__()
        self.fc = nn.Linear(d, d)

    def forward(self, x):
        return paddle.tanh(self.fc(x))


def test_pipeline_stack_eager_backward_reaches_params():
    paddle.seed(0)
    d = 6
    pre = nn.Linear(d, d)
    stack = PipelineStack(LayerDesc(_Body, d), total_layers=4, num_stages=2)
    x = paddle.randn([4, d])
    out = stack(pre(x), pipelined=False)
    out.sum().backward()
    assert pre.weight.grad is not None, "upstream layer got no gradient"
    for p in stack.parameters():
        assert p.grad is not None, "stacked body param got no gradient"
        assert float(np.abs(p.grad.numpy()).sum()) > 0


def test_pipeline_stack_eager_backward_matches_unrolled():
    """Eager grads through the stacked scan == grads of the equivalent
    unrolled sequential computation."""
    paddle.seed(3)
    d = 4
    stack = PipelineStack(LayerDesc(_Body, d), total_layers=2, num_stages=1)
    x_np = np.random.RandomState(1).randn(3, d).astype(np.float32)

    x = paddle.to_tensor(x_np)
    out = stack(x, pipelined=False)
    out.sum().backward()
    got = [p.grad.numpy().copy() for p in stack.parameters()]

    # unrolled reference: same math via per-slot matmuls
    w = stack._stacked[0].numpy()  # [S=1, k=2, d, d] -> weight
    b = stack._stacked[1].numpy()
    wt = paddle.to_tensor(w.reshape(2, d, d))
    wt.stop_gradient = False
    bt = paddle.to_tensor(b.reshape(2, d))
    bt.stop_gradient = False
    h = paddle.to_tensor(x_np)
    for i in range(2):
        h = paddle.tanh(paddle.matmul(h, wt[i]) + bt[i])
    h.sum().backward()
    np.testing.assert_allclose(got[0].reshape(2, d, d), wt.grad.numpy(),
                               atol=1e-5)
    np.testing.assert_allclose(got[1].reshape(2, d), bt.grad.numpy(),
                               atol=1e-5)


# -- ADVICE #3: version counter only tracks requires-grad inputs ------------

def test_mutating_nongrad_input_after_use_ok():
    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
    x.stop_gradient = False
    m = paddle.to_tensor(np.array([5.0, 5.0, 5.0], np.float32))  # no grad
    y = (x + m).sum()
    m[0] = 0.0  # mutating a non-requires-grad input must NOT raise
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [1.0, 1.0, 1.0])


def test_mutating_grad_input_after_use_still_raises():
    w = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    w.stop_gradient = False
    x = w * 2.0
    y = x.sum()
    x[0] = 0.0
    with pytest.raises(RuntimeError, match="mutated in"):
        y.backward()


# -- ADVICE #4: pipeline dropout PRNG varies per slot ------------------------

class _DropBody(nn.Layer):
    def __init__(self, d):
        super().__init__()
        self.d = d

    def forward(self, x):
        return F.dropout(x, p=0.5, training=True)


def test_pipeline_dropout_masks_differ_per_slot():
    paddle.seed(0)
    d = 64
    stack = PipelineStack(LayerDesc(_DropBody, d), total_layers=4,
                          num_stages=2)
    stack.train()
    x = paddle.ones([2, d])
    with paddle.no_grad():
        out = stack(x, pipelined=False).numpy()
    # 4 layers of dropout(p=.5) on ones: if all 4 slots reused ONE mask,
    # every surviving element would be exactly 2^4 = 16; distinct masks
    # give a mix of zeros and 16s with survival ~ .5^4 per element.
    survivors = out[out != 0]
    assert survivors.size > 0
    # with a shared mask, survival rate would be ~0.5 (one mask applied
    # 4x keeps the same half alive); with independent masks ~0.0625
    rate = survivors.size / out.size
    assert rate < 0.3, f"dropout masks look identical across slots (rate={rate})"
