"""Optimizer tests: update rules vs hand-computed references + state dict."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _param(v):
    from paddle_tpu.core.tensor import Parameter

    return Parameter(np.asarray(v, np.float32))


def test_sgd_matches_formula():
    p = _param([1.0, 2.0])
    p.grad = paddle.to_tensor([0.5, 0.5])
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])
    opt.step()
    np.testing.assert_allclose(p.numpy(), [0.95, 1.95], rtol=1e-6)


def test_momentum():
    p = _param([1.0])
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9, parameters=[p])
    p.grad = paddle.to_tensor([1.0])
    opt.step()
    np.testing.assert_allclose(p.numpy(), [0.9], rtol=1e-6)
    p.grad = paddle.to_tensor([1.0])
    opt.step()
    # velocity = 0.9*1 + 1 = 1.9 ; p = 0.9 - 0.1*1.9
    np.testing.assert_allclose(p.numpy(), [0.71], rtol=1e-6)


def test_adam_matches_reference():
    p = _param([1.0])
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=[p])
    m = v = 0.0
    val = 1.0
    for t in range(1, 4):
        g = val * 2  # pretend grad = 2*p
        p.grad = paddle.to_tensor([g])
        opt.step()
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * g * g
        mhat = m / (1 - 0.9 ** t)
        vhat = v / (1 - 0.999 ** t)
        val = val - 0.01 * mhat / (np.sqrt(vhat) + 1e-8)
        np.testing.assert_allclose(p.numpy(), [val], rtol=1e-5)


def test_adamw_decoupled_decay():
    p = _param([1.0])
    opt = paddle.optimizer.AdamW(learning_rate=0.1, weight_decay=0.5, parameters=[p])
    p.grad = paddle.to_tensor([0.0])
    opt.step()
    # grad=0 -> only decay term: p - lr*wd*p = 1 - 0.1*0.5
    np.testing.assert_allclose(p.numpy(), [0.95], rtol=1e-5)


def test_clear_grad_and_none_grads():
    p1, p2 = _param([1.0]), _param([2.0])
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p1, p2])
    p1.grad = paddle.to_tensor([1.0])
    opt.step()  # p2 has no grad: untouched
    np.testing.assert_allclose(p2.numpy(), [2.0])
    opt.clear_grad()
    assert p1.grad is None


def test_lr_scheduler():
    sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1, step_size=2, gamma=0.5)
    p = _param([1.0])
    opt = paddle.optimizer.SGD(learning_rate=sched, parameters=[p])
    lrs = []
    for _ in range(5):
        lrs.append(opt.get_lr())
        sched.step()
    np.testing.assert_allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025])


def test_warmup_scheduler():
    sched = paddle.optimizer.lr.LinearWarmup(
        learning_rate=0.1, warmup_steps=4, start_lr=0.0, end_lr=0.1)
    vals = []
    for _ in range(6):
        vals.append(sched())
        sched.step()
    np.testing.assert_allclose(vals[:4], [0.0, 0.025, 0.05, 0.075])
    np.testing.assert_allclose(vals[4:], [0.1, 0.1])


def test_lars_matches_formula():
    p = _param([3.0, 4.0])  # ||w|| = 5
    opt = paddle.optimizer.Lars(learning_rate=0.1, momentum=0.9,
                                lars_coeff=0.001,
                                lars_weight_decay=0.0005, parameters=[p])
    p.grad = paddle.to_tensor([0.6, 0.8])  # ||g|| = 1
    opt.step()
    w_norm, g_norm = 5.0, 1.0
    local_lr = 0.001 * w_norm / (g_norm + 0.0005 * w_norm + 1e-9)
    v = 0.1 * local_lr * (np.array([0.6, 0.8])
                          + 0.0005 * np.array([3.0, 4.0]))
    np.testing.assert_allclose(p.numpy(), np.array([3.0, 4.0]) - v,
                               rtol=1e-6)
    # second step: hand-compute momentum accumulation
    w1 = p.numpy().copy()
    p.grad = paddle.to_tensor([0.6, 0.8])
    opt.step()
    g = np.array([0.6, 0.8])
    w_norm1 = np.linalg.norm(w1)
    g_norm1 = np.linalg.norm(g)
    local_lr2 = 0.001 * w_norm1 / (g_norm1 + 0.0005 * w_norm1 + 1e-9)
    v2 = 0.9 * v + 0.1 * local_lr2 * (g + 0.0005 * w1)
    np.testing.assert_allclose(p.numpy(), w1 - v2, rtol=1e-5)


def test_lars_exclude_from_weight_decay():
    p = _param([3.0, 4.0])
    p.name = "layer.bias"
    opt = paddle.optimizer.Lars(learning_rate=0.1, momentum=0.0,
                                lars_coeff=0.001, lars_weight_decay=0.5,
                                exclude_from_weight_decay=["bias"],
                                parameters=[p])
    p.grad = paddle.to_tensor([0.6, 0.8])
    opt.step()
    # decay excluded -> wd=0 in both local_lr and the update
    local_lr = 0.001 * 5.0 / (1.0 + 1e-9)
    want = np.array([3.0, 4.0]) - 0.1 * local_lr * np.array([0.6, 0.8])
    np.testing.assert_allclose(p.numpy(), want, rtol=1e-6)


def test_lars_trains_under_trainstep():
    import paddle_tpu.jit as jit
    import paddle_tpu.nn.functional as F

    paddle.seed(0)
    net = nn.Linear(8, 4)
    opt = paddle.optimizer.Lars(learning_rate=0.5, parameters=net.parameters())
    step = jit.TrainStep(net, opt, F.mse_loss)
    rs = np.random.RandomState(0)
    x = paddle.to_tensor(rs.randn(16, 8).astype(np.float32))
    y = paddle.to_tensor(rs.randn(16, 4).astype(np.float32))
    losses = [float(step(x, y)) for _ in range(20)]
    assert losses[-1] < losses[0]


def test_optimizer_state_dict_roundtrip():
    p = _param([1.0, 2.0])
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=[p])
    p.grad = paddle.to_tensor([0.1, 0.2])
    opt.step()
    sd = opt.state_dict()

    p2 = _param([1.0, 2.0])
    opt2 = paddle.optimizer.Adam(learning_rate=0.01, parameters=[p2])
    opt2.set_state_dict(sd)
    assert opt2._step_count == 1
    np.testing.assert_allclose(
        np.asarray(opt2._accumulators["moment1"][0]),
        np.asarray(opt._accumulators["moment1"][0]))


def test_grad_clip_in_optimizer():
    p = _param([1.0])
    opt = paddle.optimizer.SGD(
        learning_rate=1.0, parameters=[p],
        grad_clip=nn.ClipGradByGlobalNorm(0.1))
    p.grad = paddle.to_tensor([100.0])
    opt.step()
    np.testing.assert_allclose(p.numpy(), [0.9], rtol=1e-4)


def test_minimize():
    x = paddle.to_tensor([3.0])
    x.stop_gradient = False
    from paddle_tpu.core.tensor import Parameter

    p = _param([3.0])
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])
    loss = (p * p).sum()
    opt.minimize(loss)
    np.testing.assert_allclose(p.numpy(), [3.0 - 0.1 * 6.0], rtol=1e-5)
    assert p.grad is None


def test_adam_multi_precision_moment_dtypes():
    """Reference optimizer/adam.py multi_precision semantics: True
    (default) keeps fp32 moments for bf16 params (master-precision
    training); False stores moments in the param dtype (half the
    optimizer HBM traffic, a numerics trade)."""
    import jax.numpy as jnp

    import paddle_tpu.jit as jit

    def make(mp):
        paddle.seed(0)
        net = nn.Linear(8, 8)
        net.to(dtype="bfloat16")
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=net.parameters(),
                                    multi_precision=mp)
        return net, opt

    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(4, 8).astype(np.float32)).astype("bfloat16")

    net1, opt1 = make(True)
    loss = (net1(x) ** 2).mean()
    loss.backward()
    opt1.step()
    assert opt1._accumulators["moment1"][0].dtype == jnp.float32

    net2, opt2 = make(False)
    loss = (net2(x) ** 2).mean()
    loss.backward()
    opt2.step()
    assert opt2._accumulators["moment1"][0].dtype == jnp.bfloat16
    # both regimes still train (and a compiled step keeps stable
    # state dtypes across iterations)
    step = jit.TrainStep(net2, opt2, lambda o, y: ((o - y) ** 2).mean())
    y = paddle.zeros([4, 8], dtype="bfloat16")
    l0 = float(step(x, y))
    for _ in range(5):
        ln = float(step(x, y))
    assert ln < l0


def test_adamw_multi_precision_false_keeps_state_dtype_in_trainstep():
    """AdamW's own update must also return moments at the storage
    dtype — otherwise the compiled step silently drifts bf16
    accumulators to f32 after one step."""
    import jax.numpy as jnp

    import paddle_tpu.jit as jit

    paddle.seed(0)
    net = nn.Linear(8, 8)
    net.to(dtype="bfloat16")
    opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                 parameters=net.parameters(),
                                 multi_precision=False)
    step = jit.TrainStep(net, opt, lambda o, y: ((o - y) ** 2).mean())
    x = paddle.zeros([4, 8], dtype="bfloat16")
    y = paddle.zeros([4, 8], dtype="bfloat16")
    step(x, y)
    step(x, y)
    assert opt._accumulators["moment1"][0].dtype == jnp.bfloat16


def test_state_dict_coerces_to_configured_moment_dtype():
    """Resuming a multi_precision=True checkpoint into a
    multi_precision=False optimizer (or vice versa) adopts THIS
    optimizer's storage dtype instead of pinning the checkpoint's."""
    import jax.numpy as jnp

    paddle.seed(0)
    net = nn.Linear(4, 4)
    net.to(dtype="bfloat16")

    def one_step(mp):
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=net.parameters(),
                                    multi_precision=mp)
        loss = (net(paddle.zeros([2, 4], dtype="bfloat16")) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return opt

    opt_f32 = one_step(True)
    sd = opt_f32.state_dict()
    opt_bf16 = paddle.optimizer.Adam(learning_rate=0.01,
                                     parameters=net.parameters(),
                                     multi_precision=False)
    opt_bf16.set_state_dict(sd)
    assert opt_bf16._accumulators["moment1"][0].dtype == jnp.bfloat16
    opt_back = paddle.optimizer.Adam(learning_rate=0.01,
                                     parameters=net.parameters(),
                                     multi_precision=True)
    opt_back.set_state_dict(opt_bf16.state_dict())
    assert opt_back._accumulators["moment1"][0].dtype == jnp.float32
