"""Inference path tests — the AnalysisPredictor analog (VERDICT r2 #3).

save → (new process, no model class) → load → infer parity, plus the
bf16 mixed-precision convert option (reference:
inference/analysis/passes/convert_to_mixed_precision.cc).
"""
import json
import os
import pickle
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.jit as jit
import paddle_tpu.nn as nn


class SmallNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 32)
        self.bn = nn.BatchNorm1D(32)
        self.fc2 = nn.Linear(32, 4)

    def forward(self, x):
        import paddle_tpu.nn.functional as F

        return self.fc2(F.relu(self.bn(self.fc1(x))))


def _build_and_save(path, convert=None):
    paddle.seed(7)
    net = SmallNet()
    net.eval()
    x = paddle.randn([4, 8])
    ref = net(x)
    jit.save(net, path, input_spec=[jit.InputSpec([4, 8], "float32")],
             convert=convert)
    return np.asarray(x._array), np.asarray(ref._array)


def test_save_load_executable_same_process(tmp_path):
    path = str(tmp_path / "model")
    x, ref = _build_and_save(path)
    predictor = jit.load(path)
    out = predictor(paddle.to_tensor(x))
    np.testing.assert_allclose(np.asarray(out._array), ref,
                               rtol=1e-5, atol=1e-5)


def test_save_load_executable_new_process(tmp_path):
    """The key predictor property: a fresh process that never imports
    the model's Python class can load + execute the saved program."""
    path = str(tmp_path / "model")
    x, ref = _build_and_save(path)
    np.save(str(tmp_path / "x.npy"), x)
    runner = tmp_path / "runner.py"
    runner.write_text(
        "import sys, numpy as np\n"
        "import paddle_tpu as paddle\n"
        "import paddle_tpu.jit as jit\n"
        "predictor = jit.load(sys.argv[1])\n"
        "x = np.load(sys.argv[2])\n"
        "out = predictor(paddle.to_tensor(x))\n"
        "np.save(sys.argv[3], np.asarray(out._array))\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__)) \
        + os.pathsep + env.get("PYTHONPATH", "")
    subprocess.run(
        [sys.executable, str(runner), path, str(tmp_path / "x.npy"),
         str(tmp_path / "out.npy")],
        check=True, env=env, timeout=300)
    out = np.load(str(tmp_path / "out.npy"))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_save_convert_bf16(tmp_path):
    path = str(tmp_path / "model_bf16")
    x, ref = _build_and_save(path, convert="bfloat16")
    # stored float params are bf16
    with open(path + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    import jax.numpy as jnp

    assert state["fc1.weight"].dtype == jnp.bfloat16
    meta = json.load(open(path + ".json"))
    assert meta["convert"] == "bfloat16"
    predictor = jit.load(path)
    out = predictor(paddle.to_tensor(x))
    # fp32 in/out boundary, bf16 compute inside
    assert "float32" in str(out.dtype) and "bfloat16" not in str(out.dtype)
    np.testing.assert_allclose(np.asarray(out._array), ref, rtol=0.05,
                               atol=0.05)


def test_weights_only_load_still_works(tmp_path):
    paddle.seed(1)
    net = SmallNet()
    path = str(tmp_path / "weights_only")
    jit.save(net, path)  # no input_spec
    loaded = jit.load(path)
    with pytest.raises(RuntimeError, match="input_spec"):
        loaded(paddle.randn([4, 8]))
    net2 = SmallNet()
    loaded.load_into(net2)
    x = paddle.randn([4, 8])
    net.eval(), net2.eval()
    np.testing.assert_allclose(np.asarray(net(x)._array),
                               np.asarray(net2(x)._array), rtol=1e-6)


def test_dynamic_batch_dim(tmp_path):
    """InputSpec None dims export symbolically: the predictor accepts any
    batch size (paddle.static.InputSpec dynamic-batch contract)."""
    paddle.seed(7)
    net = SmallNet()
    net.eval()
    path = str(tmp_path / "dyn")
    jit.save(net, path, input_spec=[jit.InputSpec([None, 8], "float32")])
    predictor = jit.load(path)
    for b in (1, 4, 9):
        x = paddle.randn([b, 8])
        out = predictor(x)
        assert out.shape == [b, 4]
        np.testing.assert_allclose(np.asarray(out._array),
                                   np.asarray(net(x)._array),
                                   rtol=1e-5, atol=1e-5)


def test_convert_predictor_weight_swap(tmp_path):
    """fp32 weights swapped into a bf16-converted predictor are cast to
    match the exported program's avals."""
    path = str(tmp_path / "model_bf16_swap")
    x, _ = _build_and_save(path, convert="bfloat16")
    predictor = jit.load(path)
    paddle.seed(99)
    net2 = SmallNet()
    predictor.set_state_dict(net2.state_dict())  # fp32 weights
    out = predictor(paddle.to_tensor(x))  # must not dtype-mismatch
    assert np.all(np.isfinite(np.asarray(out._array)))


def test_predictor_weight_swap(tmp_path):
    """set_state_dict swaps weights without retracing (zero-copy-ish
    serving update)."""
    path = str(tmp_path / "model")
    x, ref = _build_and_save(path)
    predictor = jit.load(path)
    paddle.seed(123)
    net2 = SmallNet()
    net2.eval()
    xt = paddle.to_tensor(x)
    ref2 = net2(xt)
    predictor.set_state_dict(net2.state_dict())
    out2 = predictor(xt)
    np.testing.assert_allclose(np.asarray(out2._array),
                               np.asarray(ref2._array), rtol=1e-5, atol=1e-5)
