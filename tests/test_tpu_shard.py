"""tpu-shard unit tests: per-rule golden fixtures (a minimal traced
program that FIRES each TPU30x rule and a minimal one that must NOT,
with the exact finding anchor file:line asserted), byte-drift snapshot
round-trip + stale detection, finding-ID stability under line shifts,
suppression-tag disjointness against the sibling tiers (both
directions), the CLI's json/stats modes through its program-injection
seam, and the no-backend import smoke.

Fixtures build TracedProgram records from tiny local shard_map
functions exactly the way the harvester does; contracts anchor at the
committed fixture files under tests/fixtures/tpu_shard/ so the
file-level suppression scan reads real text.
"""
import json
import os
import subprocess
import sys

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.analysis.findings import (Finding, assign_ids,
                                          parse_suppressions)
from paddle_tpu.analysis.shard import (analyze_programs,
                                       compare_snapshot,
                                       load_shard_baseline,
                                       snapshot_of,
                                       write_shard_baseline)
from paddle_tpu.analysis.shard.cli import main as shard_main
from paddle_tpu.analysis.shard.model import (build_record,
                                             parse_main_shardings)
from paddle_tpu.analysis.shard.rules import (check_tpu301, check_tpu302,
                                             check_tpu303, check_tpu304,
                                             check_tpu305)
from paddle_tpu.analysis.trace.contracts import (CollectiveBudget,
                                                 TraceContract)
from paddle_tpu.analysis.trace.rules import TracedProgram
from paddle_tpu.jit.introspect import AxisCollectiveBudget

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CLEAN_AT = "tests/fixtures/tpu_shard/clean_step.py"
BROKEN_AT = "tests/fixtures/tpu_shard/broken_step.py"
SUPPRESSED_AT = "tests/fixtures/tpu_shard/suppressed_step.py"
FOREIGN_AT = "tests/fixtures/tpu_shard/foreign_tags.py"

#: fixture serving geometry the payload bounds evaluate over
GEOM = dict(tokens=2, hidden=8)


def _budget(axes=(("mp", "ici"),), entries=(
        ("mp", "all_gather", 0, 1, "tokens * hidden * 4"),
        ("mp", "psum", 0, 1, "tokens * hidden * 4"))):
    return AxisCollectiveBudget(axes=axes, entries=entries)


def _contract(**kw):
    kw.setdefault("name", "fixture_step")
    kw.setdefault("declared_at", BROKEN_AT)
    kw.setdefault("collective_budget", _budget())
    return TraceContract(**kw)


def _mesh(axis="mp", n=2):
    return jax.sharding.Mesh(np.array(jax.devices()[:n]), (axis,))


def shard_prog(fn, args, contract, mp=2, num_layers=1,
               in_shardings=None, out_shardings=None, declared_in=None,
               declared_out=None, geometry=GEOM):
    """Build a TracedProgram the way the harvester does — make_jaxpr +
    jit(...).lower — plus the declared-layout/geometry fields the
    tpu-shard tier consumes."""
    kw = {}
    if in_shardings is not None:
        kw["in_shardings"] = in_shardings
    if out_shardings is not None:
        kw["out_shardings"] = out_shardings
    lowered = jax.jit(fn, **kw).lower(*args)
    return TracedProgram(
        contract=contract, config="fixture", mp=mp,
        num_layers=num_layers, jaxpr=jax.make_jaxpr(fn)(*args),
        lowered_text=lowered.as_text(), donated_leaves=0,
        declared_in_specs=declared_in, declared_out_specs=declared_out,
        geometry=dict(geometry) if geometry else None)


def _gather_fn(n_gathers, axis="mp"):
    def body(x):
        for _ in range(n_gathers):
            x = jax.lax.all_gather(x, axis, axis=0,
                                   tiled=True).reshape(2, -1)[0]
        return x

    return shard_map(body, mesh=_mesh(axis), in_specs=(P(axis),),
                     out_specs=P(axis), check_rep=False)


def _rec(fn, args, contract, **kw):
    return build_record(shard_prog(fn, args, contract, **kw))


# -- TPU301 undeclared-resharding ---------------------------------------

def test_tpu301_positive_count_exceeded():
    prog = shard_prog(_gather_fn(2), (jnp.ones((4,)),), _contract())
    found = check_tpu301(build_record(prog))
    assert [(f.rule, f.path, f.line) for f in found] \
        == [("TPU301", BROKEN_AT, 1)]
    assert "all_gather crosses axis 'mp' 2x" in found[0].message \
        and "allowed 1" in found[0].message


def test_tpu301_positive_bytes_exceed_payload_cap():
    """Count inside the budget but the moved bytes outgrow the
    declared payload bound: one 8-byte-shard gather against a
    2-byte bound (cap = 1 x 2 x 1 peer)."""
    c = _contract(collective_budget=_budget(entries=(
        ("mp", "all_gather", 0, 1, "tokens"),)))
    found = check_tpu301(_rec(_gather_fn(1), (jnp.ones((4,)),), c))
    assert [(f.rule, f.path, f.line) for f in found] \
        == [("TPU301", BROKEN_AT, 1)]
    assert "moves 8 bytes" in found[0].message \
        and "caps 2" in found[0].message


def test_tpu301_positive_undeclared_axis():
    c = _contract(collective_budget=_budget(
        axes=(("dp", "ici"),), entries=()))
    found = check_tpu301(_rec(_gather_fn(1), (jnp.ones((4,)),), c))
    assert [f.rule for f in found] == ["TPU301"]
    assert "mesh axis 'mp' which the budget does not declare" \
        in found[0].message


def test_tpu301_positive_no_axis_budget():
    """A legacy count-only CollectiveBudget declares no axes — every
    collective is an undeclared resharding under the per-axis gate."""
    c = _contract(collective_budget=CollectiveBudget(
        fixed=(("all_gather", 1),)))
    found = check_tpu301(_rec(_gather_fn(1), (jnp.ones((4,)),), c))
    assert [f.rule for f in found] == ["TPU301"]
    assert "declares no per-axis collective budget" in found[0].message


def test_tpu301_negative_within_budget():
    c = _contract(declared_at=CLEAN_AT)
    rec = _rec(_gather_fn(1), (jnp.ones((4,)),), c)
    assert check_tpu301(rec) == []
    # and per-layer budgets scale with the layer count
    c = _contract(declared_at=CLEAN_AT, collective_budget=_budget(
        entries=(("mp", "all_gather", 1, 0, "tokens * hidden * 4"),)))
    rec = _rec(_gather_fn(3), (jnp.ones((4,)),), c, num_layers=3)
    assert check_tpu301(rec) == []


def test_tpu301_negative_no_collectives():
    rec = _rec(lambda x: x * 2.0, (jnp.ones((4,)),),
               _contract(declared_at=CLEAN_AT))
    assert check_tpu301(rec) == []


# -- TPU302 replicated-large-buffer -------------------------------------

def test_tpu302_positive_sharded_plan_lowered_replicated():
    """A 4 KiB buffer the declared layout shards over mp but the
    lowering pinned `{replicated}` — every chip pays full HBM."""
    mesh = _mesh()
    prog = shard_prog(
        lambda w: w + 1.0, (jnp.ones((16, 64)),), _contract(),
        in_shardings=(NamedSharding(mesh, P()),),
        declared_in=(("mp", None),))
    found = check_tpu302(build_record(prog))
    assert [(f.rule, f.path, f.line) for f in found] \
        == [("TPU302", BROKEN_AT, 1)]
    assert "4096 bytes" in found[0].message \
        and "declared P('mp', None)" in found[0].message \
        and "lowered replicated" in found[0].message


def test_tpu302_negative_lowered_sharded_as_declared():
    mesh = _mesh()
    prog = shard_prog(
        lambda w: w + 1.0, (jnp.ones((16, 64)),),
        _contract(declared_at=CLEAN_AT),
        in_shardings=(NamedSharding(mesh, P("mp")),),
        declared_in=(("mp", None),))
    rec = build_record(prog)
    assert check_tpu302(rec) == []
    assert check_tpu303(rec) == []     # and the layout matches too


def test_tpu302_negative_small_buffer_replicates_by_design():
    mesh = _mesh()
    prog = shard_prog(
        lambda w: w + 1.0, (jnp.ones((4,)),),      # 16 bytes
        _contract(declared_at=CLEAN_AT),
        in_shardings=(NamedSharding(mesh, P()),), declared_in=((),))
    assert check_tpu302(build_record(prog)) == []


# -- TPU303 pspec-layout drift ------------------------------------------

def test_tpu303_positive_sharded_on_wrong_dim():
    mesh = _mesh()
    prog = shard_prog(
        lambda w: w + 1.0, (jnp.ones((16, 64)),), _contract(),
        in_shardings=(NamedSharding(mesh, P(None, "mp")),),
        declared_in=(("mp", None),))
    found = check_tpu303(build_record(prog))
    assert [(f.rule, f.path, f.line) for f in found] \
        == [("TPU303", BROKEN_AT, 1)]
    assert "expects split 2x1" in found[0].message \
        and "lowered split 1x2" in found[0].message


def test_tpu303_positive_declared_replicated_lowered_sharded():
    mesh = _mesh()
    prog = shard_prog(
        lambda w: w + 1.0, (jnp.ones((16, 64)),), _contract(),
        in_shardings=(NamedSharding(mesh, P("mp")),),
        declared_in=((),))
    found = check_tpu303(build_record(prog))
    assert [f.rule for f in found] == ["TPU303"]
    assert "expects replicated" in found[0].message


def test_tpu303_negative_plan_matches_lowering():
    mesh = _mesh()
    prog = shard_prog(
        lambda w, s: w * s, (jnp.ones((16, 64)), jnp.ones((64,))),
        _contract(declared_at=CLEAN_AT),
        in_shardings=(NamedSharding(mesh, P("mp")),
                      NamedSharding(mesh, P())),
        declared_in=(("mp", None), ()))
    assert check_tpu303(build_record(prog)) == []


def test_tpu303_skips_undeclared_and_host_leaves():
    prog = shard_prog(
        lambda w, t: w * t, (jnp.ones((16, 64)), jnp.ones((64,))),
        _contract(declared_at=CLEAN_AT),
        declared_in=(None, None))     # host args: no declared layout
    assert check_tpu303(build_record(prog)) == []


# -- TPU304 axis-unsafe collective shape --------------------------------

def test_tpu304_positive_payload_scales_with_mesh():
    """The gathered GLOBAL payload (16 bytes) lands above a bound
    declared over serving geometry only (tokens = 2 bytes) — the
    signature of a payload that grows with axis size."""
    c = _contract(collective_budget=_budget(entries=(
        ("mp", "all_gather", 0, 1, "tokens"),)))
    found = check_tpu304(_rec(_gather_fn(1), (jnp.ones((4,)),), c))
    assert [(f.rule, f.path, f.line) for f in found] \
        == [("TPU304", BROKEN_AT, 1)]
    assert "16-byte global payload" in found[0].message \
        and "declared bound 2" in found[0].message


def test_tpu304_negative_payload_within_bound():
    rec = _rec(_gather_fn(1), (jnp.ones((4,)),),
               _contract(declared_at=CLEAN_AT))
    assert check_tpu304(rec) == []


# -- TPU305 dcn-hostile collective --------------------------------------

def _pp_budget():
    return _budget(axes=(("pp", "dcn"),), entries=(
        ("pp", "all_gather", 0, 1, "tokens * hidden * 4"),))


def test_tpu305_positive_per_token_over_dcn():
    c = _contract(collective_budget=_pp_budget(), per_token=True)
    found = check_tpu305(
        _rec(_gather_fn(1, axis="pp"), (jnp.ones((4,)),), c))
    assert [(f.rule, f.path, f.line) for f in found] \
        == [("TPU305", BROKEN_AT, 1)]
    assert "slow axis 'pp'" in found[0].message \
        and "per-token step" in found[0].message


def test_tpu305_positive_on_device_loop_body():
    def body(x):
        def step(c, _):
            return c + jax.lax.psum(x, "pp"), None
        out, _ = jax.lax.scan(step, x, None, length=2)
        return out

    fn = shard_map(body, mesh=_mesh("pp"), in_specs=(P("pp"),),
                   out_specs=P("pp"), check_rep=False)
    c = _contract(collective_budget=_budget(
        axes=(("pp", "dcn"),),
        entries=(("pp", "psum", 2, 0, "tokens * hidden * 4"),)))
    found = check_tpu305(_rec(fn, (jnp.ones((4,)),), c))
    assert {f.rule for f in found} == {"TPU305"}
    assert "on-device loop body" in found[0].message


def test_tpu305_negative_per_admission_prefill():
    """Same DCN crossing from a per-admission program (per_token
    False, not in a loop): tolerable, TPU305 stays quiet."""
    c = _contract(declared_at=CLEAN_AT,
                  collective_budget=_pp_budget())
    found = check_tpu305(
        _rec(_gather_fn(1, axis="pp"), (jnp.ones((4,)),), c))
    assert found == []


def test_tpu305_negative_fast_ici_axis():
    c = _contract(declared_at=CLEAN_AT, per_token=True)
    rec = _rec(_gather_fn(1), (jnp.ones((4,)),), c)
    assert check_tpu305(rec) == []


# -- TPU300 drift snapshot + parse errors -------------------------------

def _clean_prog():
    return shard_prog(_gather_fn(1), (jnp.ones((4,)),),
                      _contract(declared_at=CLEAN_AT))


def test_shard_baseline_round_trip(tmp_path):
    prog = _clean_prog()
    path = str(tmp_path / "SHARD_BASELINE.json")
    assert write_shard_baseline(path, [build_record(prog)]) == 1
    res = analyze_programs([prog], shard_baseline=path)
    assert res.new_findings() == [] and res.stale_shard_baseline == []


def test_shard_baseline_drift_missing_and_stale():
    prog = _clean_prog()
    rec = build_record(prog)
    base = snapshot_of([rec])
    # exact totals -> clean
    drift, stale = compare_snapshot([rec], base)
    assert drift == [] and stale == []
    # any byte movement fails loudly
    mutated = json.loads(json.dumps(base))
    mutated[rec.key]["axes"]["mp"]["all_gather"]["moved_bytes"] += 8
    drift, _ = compare_snapshot([rec], mutated)
    assert [(f.rule, f.path, f.line) for f in drift] \
        == [("TPU300", CLEAN_AT, 1)]
    assert "drifted" in drift[0].message \
        and "mp/all_gather 1x/16B -> 1x/8B" in drift[0].message
    # a program with no entry fails; a ghost entry is reported stale
    drift, stale = compare_snapshot([rec], {"ghost[cfg]": {"axes": {}}})
    assert [f.rule for f in drift] == ["TPU300"]
    assert "no SHARD_BASELINE.json entry" in drift[0].message
    assert stale == ["ghost[cfg]"]


def test_unparseable_lowering_is_tpu300():
    prog = _clean_prog()
    prog.lowered_text = "not a module"
    prog.declared_in_specs = (("mp",),)
    res = analyze_programs([prog], shard_baseline=None)
    rules = [f.rule for f in res.findings]
    assert "TPU300" in rules
    f = next(f for f in res.findings if f.rule == "TPU300")
    assert "did not parse" in f.message and f.path == CLEAN_AT


def test_tpu300_drift_is_never_grandfatherable():
    """A drift finding's stable ID hashes the program key, not the
    drift content — a findings-baseline entry would mask every FUTURE
    drift too, so analyze_programs refuses to honor one (it surfaces
    stale and the finding stays live)."""
    prog = _clean_prog()
    rec = build_record(prog)
    mutated = json.loads(json.dumps(snapshot_of([rec])))
    mutated[rec.key]["axes"]["mp"]["all_gather"]["count"] += 1
    res = analyze_programs([prog], shard_baseline=mutated)
    drift = [f for f in res.findings if f.rule == "TPU300"]
    assert len(drift) == 1
    baseline = {drift[0].id: {"id": drift[0].id,
                              "justification": "x" * 20}}
    res = analyze_programs([prog], baseline=baseline,
                           shard_baseline=mutated)
    drift = [f for f in res.findings if f.rule == "TPU300"]
    assert drift and not drift[0].baselined
    assert drift[0] in res.new_findings()
    assert res.stale_baseline == sorted(baseline)


def test_findings_baseline_grandfathers_tpu301(tmp_path):
    prog = shard_prog(_gather_fn(2), (jnp.ones((4,)),), _contract())
    res = analyze_programs([prog], shard_baseline=None)
    assert [f.rule for f in res.new_findings()] == ["TPU301"]
    baseline = {f.id: {"id": f.id, "justification": "fixture: " * 3}
                for f in res.new_findings()}
    res = analyze_programs([prog], baseline=baseline,
                           shard_baseline=None)
    assert res.new_findings() == [] \
        and [f.baselined for f in res.findings] == [True]


# -- IDs, suppressions, tag disjointness --------------------------------

def test_finding_ids_stable_under_line_shifts():
    """IDs hash the line-free identity (rule|path|qualname|source|
    occurrence) — moving the anchor line must not orphan a baseline
    entry."""
    def ids(line):
        fs = [Finding(rule="TPU303", path=BROKEN_AT, line=line, col=0,
                      qualname="fixture_step", source="fixture",
                      message="m")]
        return [f.id for f in assign_ids(fs)]

    assert ids(1) == ids(500)
    # and the end-to-end path is deterministic across reruns
    one = analyze_programs([_clean_prog(),
                            shard_prog(_gather_fn(2), (jnp.ones((4,)),),
                                       _contract())],
                           shard_baseline=None)
    two = analyze_programs([shard_prog(_gather_fn(2), (jnp.ones((4,)),),
                                       _contract()), _clean_prog()],
                           shard_baseline=None)
    assert [f.id for f in one.findings] == [f.id for f in two.findings]


def test_inline_suppression_tpu_shard_tag():
    prog = shard_prog(_gather_fn(2), (jnp.ones((4,)),),
                      _contract(declared_at=SUPPRESSED_AT))
    res = analyze_programs([prog], shard_baseline=None)
    tpu301 = [f for f in res.findings if f.rule == "TPU301"]
    assert tpu301 and all(f.suppressed for f in tpu301)
    assert res.new_findings() == []


def test_sibling_tier_tags_do_not_suppress_shard_findings():
    """foreign_tags.py line 1 disables TPU301 under the tpu-lint tag
    (and tpu-race on line 2) — the tpu-shard scan must not honor
    either."""
    prog = shard_prog(_gather_fn(2), (jnp.ones((4,)),),
                      _contract(declared_at=FOREIGN_AT))
    res = analyze_programs([prog], shard_baseline=None)
    assert [f.rule for f in res.new_findings()] == ["TPU301"]


def test_shard_tag_invisible_to_sibling_tiers():
    """Direction two of the disjointness: a `# tpu-shard: disable=`
    line parses under the tpu-shard tag ONLY — the tpu-lint and
    tpu-race parsers must not see it (and vice versa)."""
    src = ("# tpu-shard: disable=TPU301\n"
           "# tpu-lint: disable=TPU019\n"
           "# tpu-race: disable=TPU201\n")
    assert parse_suppressions(src, tag="tpu-shard") == {1: {"TPU301"}}
    assert parse_suppressions(src, tag="tpu-lint") == {2: {"TPU019"}}
    assert parse_suppressions(src, tag="tpu-race") == {3: {"TPU201"}}


def test_contract_waiver_suppresses_shard_rule():
    c = _contract(waive=(("TPU301", "fixture: proving waiver "
                          "plumbing for the shard tier"),))
    prog = shard_prog(_gather_fn(2), (jnp.ones((4,)),), c)
    res = analyze_programs([prog], shard_baseline=None)
    tpu301 = [f for f in res.findings if f.rule == "TPU301"]
    assert tpu301 and all(f.suppressed for f in tpu301)


# -- signature parser ---------------------------------------------------

def test_parse_main_shardings_decodes_counts():
    text = ('module @x { func.func public @main('
            '%arg0: tensor<2x9x8x4x8xi8> {mhlo.sharding = '
            '"{devices=[1,1,1,2,1]<=[2]}"}, '
            '%arg1: tensor<32x64xf32> {mhlo.sharding = '
            '"{replicated}"}, '
            '%arg2: tensor<4xi32>) -> (tensor<2x32xf32>, '
            'tensor<8xbf16> {mhlo.sharding = '
            '"{devices=[2,4]<=[8] last_tile_dim_replicate}"}) { } }')
    args, results = parse_main_shardings(text)
    assert [(a[0], a[3]) for a in args] == [
        ((2, 9, 8, 4, 8), (1, 1, 1, 2, 1)),
        ((32, 64), ()), ((4,), None)]
    assert args[0][2] == 2 * 9 * 8 * 4 * 8       # i8 bytes
    assert [(r[0], r[3]) for r in results] == [
        ((2, 32), None), ((8,), (2,))]
    assert results[1][2] == 16                   # bf16 bytes


# -- CLI (through the program-injection seam) ---------------------------

def _cli(args, programs, capsys):
    code = shard_main(args, programs=programs)
    out = capsys.readouterr().out
    return code, out


def test_cli_clean_and_finding_exit_codes(capsys, tmp_path):
    clean, broken = _clean_prog(), shard_prog(
        _gather_fn(2), (jnp.ones((4,)),), _contract())
    code, out = _cli(["--shard-baseline", "none"], [clean], capsys)
    assert code == 0 and "tpu-shard clean: 1 programs" in out
    code, out = _cli(["--shard-baseline", "none"], [broken], capsys)
    assert code == 1 and "TPU301" in out


def test_cli_json_and_stats(capsys):
    prog = shard_prog(_gather_fn(2), (jnp.ones((4,)),), _contract())
    code, out = _cli(["--format", "json", "--shard-baseline", "none"],
                     [prog], capsys)
    assert code == 1
    doc = json.loads(out)
    assert [f["rule"] for f in doc["findings"]] == ["TPU301"]
    assert doc["programs"] == [prog.key]
    code, out = _cli(["--stats", "--shard-baseline", "none"], [prog],
                     capsys)
    assert code == 1 and "programs analyzed: 1" in out \
        and "TPU301 undeclared-resharding" in out


def test_cli_shard_baseline_round_trip(capsys, tmp_path):
    prog = _clean_prog()
    path = str(tmp_path / "snap.json")
    code, out = _cli(["--write-shard-baseline", path], [prog], capsys)
    assert code == 0 and "snapshotted 1 programs" in out
    assert set(load_shard_baseline(path)) == {prog.key}
    code, out = _cli(["--shard-baseline", path], [prog], capsys)
    assert code == 0 and "clean" in out
    # drift: same program, one more gather
    drifted = shard_prog(
        _gather_fn(2), (jnp.ones((4,)),),
        _contract(declared_at=CLEAN_AT, collective_budget=_budget(
            entries=(("mp", "all_gather", 0, 2,
                      "tokens * hidden * 4"),))))
    code, out = _cli(["--shard-baseline", path], [drifted], capsys)
    assert code == 1 and "TPU300" in out and "drifted" in out


def test_cli_path_filter_and_usage_errors(capsys):
    progs = [_clean_prog(),
             shard_prog(_gather_fn(2), (jnp.ones((4,)),), _contract())]
    # only the broken program's declaring file selected -> 1 finding
    code, out = _cli([os.path.join(REPO, BROKEN_AT),
                      "--shard-baseline", "none"], progs, capsys)
    assert code == 1 and "TPU301" in out
    # only the clean one -> clean over exactly 1 program
    code, out = _cli([os.path.join(REPO, CLEAN_AT),
                      "--shard-baseline", "none"], progs, capsys)
    assert code == 0 and "clean: 1 programs" in out
    assert shard_main(["definitely/not/a/path.py"], programs=progs) == 2
    assert shard_main(["--baseline", "/nonexistent.json"],
                      programs=progs) == 2
    assert shard_main(["--shard-baseline", "/nonexistent.json"],
                      programs=progs) == 2


def test_cli_list_rules(capsys):
    assert shard_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in ("TPU300", "TPU301", "TPU302", "TPU303", "TPU304",
                 "TPU305"):
        assert rule in out


# -- import smoke -------------------------------------------------------

def test_shard_import_has_no_backend_init():
    """Importing the shard tier (and its rule table) must not
    initialize a JAX backend — only the harvest may."""
    code = (
        "import paddle_tpu.analysis.shard as S\n"
        "from jax._src import xla_bridge\n"
        "assert not xla_bridge._backends, 'import initialized a backend'\n"
        "assert len(S.SHARD_RULES) == 6\n"
        "assert S.SUPPRESS_TAG == 'tpu-shard'\n"
        "print('SHARD_SMOKE_OK')\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    res = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "SHARD_SMOKE_OK" in res.stdout
