"""Sharded checkpoint tests (VERDICT item 41: no sharded/per-host
checkpoint): save a stage-3 sharded model's shards, reload replicated,
reload onto a DIFFERENT sharding, bf16 roundtrip.
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import (HybridCommunicateGroup,
                                    set_hybrid_communicate_group)
from paddle_tpu.framework.sharded_io import load_sharded, save_sharded


def test_sharded_roundtrip_and_reshard(tmp_path):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    hcg = HybridCommunicateGroup(sharding=8)
    set_hybrid_communicate_group(hcg)
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(16, 64), nn.Tanh(), nn.Linear(64, 16))
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=net.parameters())
    step = dist.DistributedTrainStep(net, opt,
                                     lambda o, t: F.mse_loss(o, t),
                                     hcg=hcg, sharding_stage=3)
    x = paddle.randn([8, 16])
    y = paddle.randn([8, 16])
    step(x, y)  # params now sharded over 'sharding'
    ref = {k: np.asarray(v._array) for k, v in net.state_dict().items()}
    assert any("sharding" in str(v._array.sharding.spec)
               for v in net.state_dict().values())

    ck = str(tmp_path / "ck")
    save_sharded(net.state_dict(), ck)

    # plain reload: full numpy arrays
    loaded = load_sharded(ck)
    for k, v in ref.items():
        np.testing.assert_array_equal(np.asarray(loaded[k]), v)

    # reshard-on-load: different layout (axis-1 sharding of the weights)
    mesh = hcg.mesh
    shardings = {k: NamedSharding(mesh, P(None, "sharding"))
                 if np.ndim(ref[k]) == 2 and ref[k].shape[1] % 8 == 0
                 else NamedSharding(mesh, P())
                 for k in ref}
    res = load_sharded(ck, shardings=shardings)
    for k, v in ref.items():
        np.testing.assert_array_equal(np.asarray(res[k]), v)
    w0 = res["0.weight"]
    assert "sharding" in str(w0.sharding.spec)
    set_hybrid_communicate_group(HybridCommunicateGroup())


def test_sharded_bf16_roundtrip(tmp_path):
    paddle.seed(1)
    net = nn.Linear(8, 8)
    net.to(dtype="bfloat16")
    ck = str(tmp_path / "ckbf")
    save_sharded(net.state_dict(), ck)
    loaded = load_sharded(ck)
    for k, v in net.state_dict().items():
        assert str(loaded[k].dtype) == "bfloat16"
        np.testing.assert_array_equal(
            np.asarray(loaded[k], np.float32),
            np.asarray(v._array, np.float32), err_msg=k)
