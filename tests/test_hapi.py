"""hapi Model.fit tier tests (VERDICT r2 #8): Model(net).fit(train_ds)
converges; evaluate/predict/save/load; callbacks (EarlyStopping,
ModelCheckpoint); MNIST/Cifar dataset parsers on synthetic files in the
real formats.

Reference analogs: python/paddle/hapi/model.py:1039,
python/paddle/hapi/callbacks.py, python/paddle/vision/datasets/.
"""
import gzip
import os
import pickle
import struct
import tarfile

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.io import Dataset
from paddle_tpu.metric import Accuracy
from paddle_tpu.vision.datasets import Cifar10, MNIST


# -- synthetic files in the real formats --------------------------------
def _write_mnist(tmp, n=256, seed=0):
    rs = np.random.RandomState(seed)
    labels = rs.randint(0, 10, n).astype(np.uint8)
    # images: a bright square whose position encodes the label (learnable)
    imgs = np.zeros((n, 28, 28), np.uint8)
    for i, y in enumerate(labels):
        r, c = divmod(int(y), 5)
        imgs[i, 4 + r * 10:12 + r * 10, 2 + c * 5:8 + c * 5] = 255
    ip = os.path.join(tmp, "images.idx3-ubyte.gz")
    lp = os.path.join(tmp, "labels.idx1-ubyte.gz")
    with gzip.open(ip, "wb") as f:
        f.write(struct.pack(">IIII", 0x803, n, 28, 28))
        f.write(imgs.tobytes())
    with gzip.open(lp, "wb") as f:
        f.write(struct.pack(">II", 0x801, n))
        f.write(labels.tobytes())
    return ip, lp, imgs, labels


def _write_cifar10(tmp, n_per_batch=20):
    path = os.path.join(tmp, "cifar-10-python.tar.gz")
    rs = np.random.RandomState(1)
    with tarfile.open(path, "w:gz") as tf:
        import io as _io

        def add(name, d):
            raw = pickle.dumps(d)
            info = tarfile.TarInfo(f"cifar-10-batches-py/{name}")
            info.size = len(raw)
            tf.addfile(info, _io.BytesIO(raw))

        for b in range(1, 6):
            add(f"data_batch_{b}", {
                b"data": rs.randint(0, 256, (n_per_batch, 3072), np.uint8),
                b"labels": rs.randint(0, 10, n_per_batch).tolist()})
        add("test_batch", {
            b"data": rs.randint(0, 256, (n_per_batch, 3072), np.uint8),
            b"labels": rs.randint(0, 10, n_per_batch).tolist()})
    return path


def test_mnist_dataset_parses_idx(tmp_path):
    ip, lp, imgs, labels = _write_mnist(str(tmp_path), n=32)
    ds = MNIST(image_path=ip, label_path=lp)
    assert len(ds) == 32
    img, y = ds[5]
    assert img.shape == (28, 28, 1) and img.dtype == np.float32
    assert img.max() <= 1.0 and int(y) == int(labels[5])
    np.testing.assert_array_equal((img[..., 0] * 255).astype(np.uint8),
                                  imgs[5])
    # transform applied
    ds2 = MNIST(image_path=ip, label_path=lp,
                transform=lambda im: im.reshape(-1))
    assert ds2[0][0].shape == (784,)
    with pytest.raises(RuntimeError, match="egress"):
        MNIST(download=True)


def test_cifar10_dataset_parses_tar(tmp_path):
    path = _write_cifar10(str(tmp_path))
    tr = Cifar10(data_file=path, mode="train")
    te = Cifar10(data_file=path, mode="test")
    assert len(tr) == 100 and len(te) == 20
    img, y = tr[3]
    assert img.shape == (32, 32, 3) and img.dtype == np.float32
    assert 0 <= int(y) < 10


class _MnistNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.flatten = nn.Flatten(1)
        self.fc1 = nn.Linear(784, 64)
        self.fc2 = nn.Linear(64, 10)

    def forward(self, x):
        h = F.relu(self.fc1(self.flatten(x)))
        return self.fc2(h)


def _fit_model(tmp_path, epochs=3, callbacks=None, eval_ds=True, **kw):
    ip, lp, _, _ = _write_mnist(str(tmp_path), n=256)
    ds = MNIST(image_path=ip, label_path=lp)
    paddle.seed(0)
    model = paddle.Model(_MnistNet())
    model.prepare(
        paddle.optimizer.Adam(learning_rate=1e-3,
                              parameters=model.parameters()),
        nn.CrossEntropyLoss(),
        metrics=[Accuracy()])
    model.fit(ds, ds if eval_ds else None, epochs=epochs, batch_size=64,
              verbose=0, callbacks=callbacks, **kw)
    return model, ds


def test_model_fit_converges(tmp_path):
    model, ds = _fit_model(tmp_path, epochs=4)
    logs = model.evaluate(ds, batch_size=64, verbose=0)
    acc = logs["acc"]
    assert (acc[0] if isinstance(acc, (list, tuple)) else acc) > 0.9, logs
    assert logs["loss"] < 1.0
    preds = model.predict(ds, batch_size=64)
    assert preds[0].shape == (256, 10)


def test_model_save_load_roundtrip(tmp_path):
    model, ds = _fit_model(tmp_path, epochs=1)
    path = str(tmp_path / "ckpt" / "m")
    model.save(path)
    assert os.path.exists(path + ".pdparams")
    assert os.path.exists(path + ".pdopt")

    paddle.seed(123)
    m2 = paddle.Model(_MnistNet())
    m2.prepare(None, nn.CrossEntropyLoss(), metrics=[Accuracy()])
    m2.load(path, reset_optimizer=True)
    a = model.predict(ds, batch_size=64)[0]
    b = m2.predict(ds, batch_size=64)[0]
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_model_checkpoint_and_early_stopping(tmp_path):
    save_dir = str(tmp_path / "ckpts")
    es = paddle.callbacks.EarlyStopping(monitor="loss", patience=0,
                                        baseline=0.0)  # nothing beats 0
    model, _ = _fit_model(
        tmp_path, epochs=5,
        callbacks=[paddle.callbacks.ModelCheckpoint(1, save_dir), es])
    # stopped after the first eval (epoch 0), not after 5 epochs
    assert es.stopped_epoch
    assert model.stop_training
    assert os.path.exists(os.path.join(save_dir, "0.pdparams"))
    assert os.path.exists(os.path.join(save_dir, "final.pdparams"))
    assert not os.path.exists(os.path.join(save_dir, "4.pdparams"))


def test_model_save_inference_then_load_predictor(tmp_path):
    """Model.save(training=False) -> jit predictor parity (the deploy
    handoff: fit with hapi, serve without the Python class)."""
    from paddle_tpu.jit.api import InputSpec

    model, ds = _fit_model(tmp_path, epochs=1)
    model._inputs = [InputSpec([None, 28, 28, 1], "float32")]
    path = str(tmp_path / "deploy" / "m")
    model.save(path, training=False)

    import paddle_tpu.jit as jit

    pred = jit.load(path)
    x = np.asarray(ds[0][0])[None]
    want = model.predict_batch([paddle.to_tensor(x)])
    want = np.asarray(want[0] if isinstance(want, (list, tuple)) else want)
    got = pred(x)
    got = np.asarray(got[0] if isinstance(got, (list, tuple)) else got)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    # dynamic batch honored by the exported program
    got3 = pred(np.repeat(x, 3, axis=0))
    got3 = np.asarray(got3[0] if isinstance(got3, (list, tuple)) else got3)
    assert got3.shape[0] == 3


def test_summary_counts_params(capsys):
    net = _MnistNet()
    info = paddle.summary(net, (2, 28, 28, 1))
    want = 784 * 64 + 64 + 64 * 10 + 10
    assert info["total_params"] == want
    assert info["trainable_params"] == want
    out = capsys.readouterr().out
    assert "fc1 (Linear)" in out and "Total params" in out
    assert f"{want:,}" in out


def test_summary_arg_forms():
    import pytest as _pytest

    net = _MnistNet()
    want = 784 * 64 + 64 + 64 * 10 + 10
    # None batch dim (paddle idiom) and InputSpec both work
    assert paddle.summary(net, (None, 28, 28, 1))["total_params"] == want
    from paddle_tpu.static import InputSpec

    assert paddle.summary(
        net, [InputSpec([-1, 28, 28, 1], "float32")])["total_params"] \
        == want
    # bare InputSpec form
    assert paddle.summary(
        net, InputSpec([None, 28, 28, 1], "float32"))["total_params"] \
        == want
    # incubate path parity reachable from the root package
    assert paddle.incubate.MoELayer is not None
    with _pytest.raises(ValueError, match="input_size"):
        paddle.summary(net)
    with _pytest.raises(ValueError, match="dtypes"):
        paddle.summary(net, [(2, 28, 28, 1)], dtypes=["float32", "int64"])


def test_static_namespace():
    from paddle_tpu.static import InputSpec, device_guard, name_scope

    s = InputSpec([None, 4], "float32")
    assert s.shape == (None, 4)
    with device_guard("gpu:0"), name_scope("blk"):
        pass  # source-compat no-ops


def test_lr_scheduler_steps_in_fit(tmp_path):
    ip, lp, _, _ = _write_mnist(str(tmp_path), n=64)
    ds = MNIST(image_path=ip, label_path=lp)
    sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1, step_size=2,
                                          gamma=0.5)
    model = paddle.Model(_MnistNet())
    model.prepare(paddle.optimizer.SGD(learning_rate=sched,
                                       parameters=model.parameters()),
                  nn.CrossEntropyLoss())
    model.fit(ds, epochs=1, batch_size=32, verbose=0)
    # 2 steps (64/32) at step_size=2 -> one decay boundary crossed
    assert sched.last_epoch >= 2
    assert model._optimizer.get_lr() < 0.1
