"""Fused Pallas conv training suite (ISSUE 16): interpreter-mode
gradient parity of the `fused_conv_bn_relu_train` custom_vjp op vs
`jax.vjp` of the dense differentiable composition
(`conv_bn_relu_train_reference`) across the nine ResNet-50 sweep
shapes and both strides, the stride/ReLU/dtype matrix, forced
W-tiling, the ConvBNReLU training seam (running stats, dense
fallback bit-identity, use_global_stats), the resnet50 train-step
dispatch count, and the ISSUE-16 bench runners at tiny shapes.

Gradient checks flow a fixed random cotangent through `jax.vjp` of
the y output only — the mean/var outputs feed stop-gradient
consumers in the block (running-stat updates), which is exactly how
the op is differentiated in a train step."""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.ops.pallas.conv as C
from paddle_tpu.ops.pallas.conv import (
    CONV_PATH_STATS, conv_bn_relu_train_reference,
    conv_train_geometry_tileable, fused_conv_bn_relu_train,
    reset_conv_path_stats,
)

import bench_ops

SWEEP = list(bench_ops.CONV_SWEEP_SHAPES)
assert len(SWEEP) == 9

# ISSUE-16 stated budgets: fp32 near-exact in Linf (~1e-5 — only
# reduction order differs; both paths accumulate fp32); bf16 within
# the bench budget in relative L2 — the gradient metric: bf16
# rounding feeds sign-cancelling sums in dInput/dWeight, so Linf
# deviations run ~10x the aggregate error for the DENSE backward
# too (both paths sit the same L2 distance from the fp32 truth;
# DESIGN_DECISIONS r19, bench_ops._conv_rel_err_l2)
FP32_GRAD_TOL = 1e-5
BF16_GRAD_TOL = bench_ops.CONV_FUSED_REL_TOL


def _rel_err(got, ref):
    g = np.asarray(got, np.float32)
    r = np.asarray(ref, np.float32)
    return np.max(np.abs(g - r)) / max(np.max(np.abs(r)), 1e-6)


def _rel_err_l2(got, ref):
    g = np.asarray(got, np.float32)
    r = np.asarray(ref, np.float32)
    return np.linalg.norm(g - r) / max(np.linalg.norm(r), 1e-6)


def _case(hw, cin, cout, k, s, dtype, n=1, seed=0):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(n, hw, hw, cin).astype(np.float32)) \
        .astype(dtype)
    w = jnp.asarray((rng.randn(k, k, cin, cout) * 0.1)
                    .astype(np.float32)).astype(dtype)
    gamma = jnp.asarray((rng.rand(cout) + 0.5).astype(np.float32))
    beta = jnp.asarray(rng.randn(cout).astype(np.float32))
    ho = (hw + s - 1) // s
    dy = jnp.asarray(rng.randn(n, ho, ho, cout).astype(np.float32))
    return x, w, gamma, beta, dy


def _grads(fn, x, w, gamma, beta, dy):
    """(y, mean, var, dx, dw, dgamma, dbeta) of the y-only vjp: the
    mean/var cotangents are zero, as in a real train step."""
    (y, mean, var), vjp = jax.vjp(fn, x, w, gamma, beta)
    return (y, mean, var) + vjp(
        (dy, jnp.zeros_like(mean), jnp.zeros_like(var)))


def _check_grads(hw, cin, cout, k, s, dtype, tol, relu=True, n=1,
                 padding="SAME", seed=0):
    x, w, gamma, beta, dy = _case(hw, cin, cout, k, s, dtype, n=n,
                                  seed=seed)
    got = _grads(
        lambda *a: fused_conv_bn_relu_train(*a, stride=s,
                                            padding=padding,
                                            relu=relu, interpret=True),
        x, w, gamma, beta, dy)
    ref = _grads(
        lambda *a: conv_bn_relu_train_reference(*a, stride=s,
                                                padding=padding,
                                                relu=relu),
        x, w, gamma, beta, dy)
    labels = ("y", "mean", "var", "dx", "dw", "dgamma", "dbeta")
    metric = _rel_err if dtype == jnp.float32 else _rel_err_l2
    for name, g, r in zip(labels, got, ref):
        assert g.shape == r.shape and g.dtype == r.dtype, name
        err = metric(g, r)
        assert err <= tol, f"{name}: rel err {err:.2e} > {tol}"


@pytest.mark.parametrize("name,hw,cin,cout,k,s", SWEEP,
                         ids=[r[0] for r in SWEEP])
def test_bwd_sweep_grad_parity_fp32(name, hw, cin, cout, k, s):
    """Acceptance: every sweep shape at its native stride, all
    gradients of the fused custom_vjp vs the dense composition, fp32
    under the CPU interpreter (the forward suite's tiering: the
    forced-other-stride matrix rides the slow tier below)."""
    _check_grads(hw, cin, cout, k, s, jnp.float32, FP32_GRAD_TOL)


@pytest.mark.slow
@pytest.mark.parametrize("name,hw,cin,cout,k,s", SWEEP,
                         ids=[r[0] for r in SWEEP])
def test_bwd_sweep_grad_parity_fp32_both_strides(name, hw, cin, cout,
                                                 k, s):
    """Acceptance: every sweep shape at BOTH strides, all gradients of
    the fused custom_vjp vs the dense composition, fp32 under the CPU
    interpreter (1x1/s2 skips odd hw — the downsample slice needs an
    even grid, matching the forward matrix)."""
    for stride in (1, 2):
        if k == 1 and stride == 2 and hw % 2:
            continue
        if not conv_train_geometry_tileable(k, stride, "SAME",
                                            in_hw=(hw, hw),
                                            in_channels=cin,
                                            out_channels=cout):
            # the forced non-native stride can push the mirrored dX
            # walk past the row-tile bound (e.g. 28x28/s2 -> a prime
            # 29-row grid): the block seam resolves such configs
            # dense; the raw op must reject them loudly
            x, w, gamma, beta, _ = _case(hw, cin, cout, k, stride,
                                         jnp.float32)
            with pytest.raises(ValueError, match="dense composition"):
                fused_conv_bn_relu_train(x, w, gamma, beta,
                                         stride=stride, padding="SAME",
                                         interpret=True)
            continue
        _check_grads(hw, cin, cout, k, stride, jnp.float32,
                     FP32_GRAD_TOL)


@pytest.mark.parametrize("k,cin,cout", [(1, 32, 64), (3, 32, 32)])
@pytest.mark.parametrize("s", [1, 2])
@pytest.mark.parametrize("relu", [True, False])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_bwd_stride_relu_dtype_matrix(k, cin, cout, s, relu, dtype):
    """Both kernel families x stride {1,2} x {with,without ReLU} x
    {fp32, bf16} at a small shape — the relu mask gates dz, so the
    no-relu branch exercises a genuinely different backward."""
    tol = FP32_GRAD_TOL if dtype == jnp.float32 else BF16_GRAD_TOL
    # 16x16/s2/"SAME" also exercises the dX row-grid rounding (a
    # prime 17-row walk padded up to 24)
    _check_grads(16, cin, cout, k, s, dtype, tol, relu=relu, n=2)


@pytest.mark.slow
def test_bwd_padding_and_odd_geometries():
    """Symmetric padding=1 at stride 2 over odd hw dilates dOut into
    an under-covering grid (the zero-pad completion path), and the
    asymmetric "SAME" halo rides the mirrored tap walk — both must
    match the dense vjp."""
    for hw in (7, 9):
        _check_grads(hw, 16, 16, 3, 2, jnp.float32, FP32_GRAD_TOL,
                     padding=1)
    _check_grads(14, 16, 16, 3, 2, jnp.float32, FP32_GRAD_TOL,
                 padding="SAME")
    _check_grads(4, 16, 16, 3, 1, jnp.float32, FP32_GRAD_TOL,
                 padding=1)


@pytest.mark.slow
def test_wtiled_geometry_grad_parity():
    """Forcing a tiny VMEM budget splits the 3x3 row slab into W
    tiles (ISSUE-16: resolutions that used to fall back dense become
    tileable) — the tiled walk must stay grad-exact. The cached vjp
    builders capture geometry, so the cache is cleared around the
    budget override."""
    old = C._VMEM_SLAB_BYTES
    try:
        C._VMEM_SLAB_BYTES = 16 * 1024
        C._train_vjp.cache_clear()
        geo = C._conv3x3_geometry(20, 20, 16)
        assert geo is not None and geo[8] > 1, \
            "budget override must actually force W-tiling"
        _check_grads(20, 16, 16, 3, 1, jnp.float32, FP32_GRAD_TOL,
                     padding=1)
        _check_grads(20, 16, 16, 3, 2, jnp.float32, FP32_GRAD_TOL,
                     padding="SAME")
    finally:
        C._VMEM_SLAB_BYTES = old
        C._train_vjp.cache_clear()
    assert C._conv3x3_geometry(20, 20, 16)[8] == 1


def test_train_geometry_gate_and_loud_rejection():
    """`conv_train_geometry_tileable` ANDs the forward gate with the
    backward dX walk's own tileability (its row grid rounds up to a
    tileable count — the ResNet stage-1 56x56 class trains fused);
    calling the train op on an unsupported shape is a loud
    ValueError, never silence."""
    assert conv_train_geometry_tileable(1, 1, 0, in_hw=(34, 34),
                                        in_channels=8, out_channels=8)
    assert not conv_train_geometry_tileable(3, 1, 1, in_hw=(34, 34),
                                            in_channels=8,
                                            out_channels=8)
    assert conv_train_geometry_tileable(3, 1, 1, in_hw=(32, 32),
                                        in_channels=8, out_channels=8)
    assert conv_train_geometry_tileable(3, 1, 1, in_hw=(56, 56),
                                        in_channels=64,
                                        out_channels=64)
    # forward-tileable but past the 128-row dX rounding ceiling:
    # the TRAIN gate alone says dense (eval still fuses)
    from paddle_tpu.ops.pallas.conv import conv_geometry_tileable

    assert conv_geometry_tileable(3, 1, 1, in_hw=(128, 128))
    assert not conv_train_geometry_tileable(3, 1, 1, in_hw=(128, 128),
                                            in_channels=8,
                                            out_channels=8)
    with pytest.raises(ValueError, match="dense composition"):
        fused_conv_bn_relu_train(jnp.zeros((1, 16, 16, 3)),
                                 jnp.zeros((7, 7, 3, 64)),
                                 jnp.ones(64), jnp.zeros(64),
                                 stride=2, padding=3, interpret=True)


def test_convbnrelu_train_running_stats_and_grad_parity():
    """The block-level training seam: a pallas-resolved ConvBNReLU in
    train mode dispatches the fused op (counted under `pallas_train`),
    matches the dense block's output AND parameter gradients, and
    updates the BN running mean/variance identically (momentum rule,
    unbiased variance)."""
    import paddle_tpu.nn as nn

    paddle.seed(0)
    blk_p = nn.ConvBNReLU(16, 32, 3, padding=1, backend="pallas")
    paddle.seed(0)
    blk_d = nn.ConvBNReLU(16, 32, 3, padding=1, backend="dense")
    blk_p.train()
    blk_d.train()
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(2, 16, 8, 8).astype(np.float32))
    reset_conv_path_stats()
    out_p = blk_p(x)
    assert CONV_PATH_STATS["pallas_train"] == 1
    assert CONV_PATH_STATS["dense_train"] == 0
    out_d = blk_d(x)
    assert CONV_PATH_STATS["dense_train"] == 1
    assert _rel_err(out_p.numpy(), out_d.numpy()) <= FP32_GRAD_TOL
    (out_p * out_p).mean().backward()
    (out_d * out_d).mean().backward()
    for p, d in ((blk_p.conv.weight, blk_d.conv.weight),
                 (blk_p.bn.weight, blk_d.bn.weight),
                 (blk_p.bn.bias, blk_d.bn.bias)):
        assert p.grad is not None
        assert _rel_err(p.grad.numpy(), d.grad.numpy()) <= FP32_GRAD_TOL
    assert _rel_err(blk_p.bn._mean.numpy(),
                    blk_d.bn._mean.numpy()) <= FP32_GRAD_TOL
    assert _rel_err(blk_p.bn._variance.numpy(),
                    blk_d.bn._variance.numpy()) <= FP32_GRAD_TOL


def test_train_fallbacks_stay_bit_identical_to_composition():
    """Dense-resolved training configs must stay BIT-identical to the
    pre-suite composition: an untileable train geometry (34x34 3x3)
    and a use_global_stats BN both route a pallas-resolved block
    through `_compose` (counted under `dense_train`), byte-for-byte
    the dense backend's output."""
    import paddle_tpu.nn as nn

    paddle.seed(0)
    blk_p = nn.ConvBNReLU(8, 8, 3, padding=1, backend="pallas")
    paddle.seed(0)
    blk_d = nn.ConvBNReLU(8, 8, 3, padding=1, backend="dense")
    blk_p.train()
    blk_d.train()
    x = paddle.to_tensor(np.random.RandomState(1)
                         .randn(1, 8, 34, 34).astype(np.float32))
    reset_conv_path_stats()
    out = blk_p(x)                        # must not raise
    assert CONV_PATH_STATS["dense_train"] == 1
    assert CONV_PATH_STATS["pallas_train"] == 0
    np.testing.assert_array_equal(out.numpy(), blk_d(x).numpy())

    # frozen-stats BN is eval-normalization inside a train-mode
    # block: not the batch-stat op's contract -> composition
    paddle.seed(0)
    blk_g = nn.ConvBNReLU(16, 16, 3, padding=1, backend="pallas")
    blk_g.bn._use_global_stats = True
    blk_g.train()
    x2 = paddle.to_tensor(np.random.RandomState(2)
                          .randn(1, 16, 8, 8).astype(np.float32))
    reset_conv_path_stats()
    blk_g(x2)
    assert CONV_PATH_STATS["dense_train"] == 1
    assert CONV_PATH_STATS["pallas_train"] == 0


@pytest.mark.slow
def test_resnet50_train_step_fused_dispatch_and_parity():
    """Acceptance: a compiled resnet50 TrainStep through the pallas
    backend dispatches all 52 bottleneck/downsample convs through the
    fused custom_vjp (counted at trace time) and its loss matches the
    dense backend's step on identical weights."""
    import paddle_tpu.jit as jit
    import paddle_tpu.nn.functional as F
    from paddle_tpu.vision.models import resnet50

    # 64x64 keeps layer4's feature maps at 2x2 so its batch-stat BN
    # normalizes over M=16 samples.  At 32x32 the maps collapse to 1x1
    # (M=batch) and BN turns the net chaotic: eager-dense vs
    # compiled-dense alone then disagree by O(1) in loss, so no loss
    # tolerance is meaningful there for ANY backend pairing.
    xnp = np.random.RandomState(3) \
        .uniform(-1, 1, (4, 3, 64, 64)).astype(np.float32)
    lbl = paddle.to_tensor(np.random.RandomState(4)
                           .randint(0, 10, (4,), np.int64))

    def one_step(backend):
        paddle.seed(0)
        model = resnet50(num_classes=10, conv_backend=backend)
        model.train()
        opt = paddle.optimizer.SGD(learning_rate=0.01,
                                   parameters=model.parameters())
        step = jit.TrainStep(model, opt, F.cross_entropy)
        return float(step(paddle.to_tensor(xnp.copy()), lbl))

    loss_d = one_step("dense")
    reset_conv_path_stats()
    loss_p = one_step("pallas")
    # 16 blocks x 3 convs + 4 downsamples, counted during the trace
    assert CONV_PATH_STATS["pallas_train"] == 52
    # ~1e-4 observed: fp32 rounding differences between the fused and
    # composed graphs, amplified once per BN by 1/sigma over 53 layers
    assert abs(loss_p - loss_d) / max(abs(loss_d), 1e-6) <= 1e-3


@pytest.mark.slow
def test_bwd_bench_runners_tiny():
    """Both ISSUE-16 lazy bench runners execute end-to-end at tiny
    shapes with their in-runner tolerance asserts live."""
    rec = bench_ops._conv_fused_bwd_sweep_case(
        shapes=(("conv_c2_1x1_64_256", 8, 16, 32, 1, 1),
                ("conv_c4_3x3_256_s2", 8, 16, 16, 3, 2)), batch=2)()
    assert set(rec["shapes"]) == {"conv_c2_1x1_64_256",
                                  "conv_c4_3x3_256_s2"}
    for curves in rec["shapes"].values():
        assert curves["rel_err"] <= bench_ops.CONV_FUSED_REL_TOL
    rec = bench_ops._resnet50_fused_block_train_case(
        batch=2, hw=8, inplanes=32, planes=8, steps=2)()
    assert rec["loss_rel_err"] <= bench_ops.CONV_FUSED_REL_TOL
    assert rec["dense_ms"] > 0 and rec["ms"] > 0

