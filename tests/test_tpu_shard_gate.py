"""Tier-1 tpu-shard gate: the full 44-program harvest runs self-clean
against the committed SHARD_BASELINE.json through the real CLI, the
two flagship rules (TPU301 undeclared-resharding, TPU302
replicated-large-buffer) are proven against deliberately broken
programs built on a REAL mp=2 engine (so the gate's green is known to
be falsifiable), the per-axis budget table in jit.introspect is pinned
to the live class surfaces it claims to describe, and the four
analysis CLIs' rule namespaces stay mutually disjoint end to end.
"""
import itertools
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu.analysis.trace as T
from paddle_tpu.analysis.shard.core import DEFAULT_SHARD_BASELINE
from paddle_tpu.analysis.shard.model import build_record, eval_payload
from paddle_tpu.analysis.shard.rules import check_tpu301, check_tpu302
from paddle_tpu.jit import introspect

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CLI_TOOLS = ("tpu_lint", "tpu_verify", "tpu_race", "tpu_shard")


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


@pytest.fixture(scope="module")
def tiny_mp2_engine():
    import paddle_tpu as paddle
    from paddle_tpu.inference.engine import GenerationEngine
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    model = GPTForCausalLM(GPTConfig.tiny(vocab=64, hidden=32,
                                          layers=2, heads=4, seq=32))
    model.eval()
    return GenerationEngine(model, num_slots=2, block_size=8,
                            attention_backend="dense", mp_degree=2,
                            donate=True)


def _decode_args(eng):
    S, MB = eng.num_slots, eng.max_blocks
    return (eng._state_arrays(), eng.cache.kpool, eng.cache.vpool,
            jnp.asarray(np.zeros((S, 1), np.int32)),
            jnp.asarray(np.zeros(S, np.int32)),
            jnp.asarray(np.zeros((S, MB), np.int32)))


def _decode_prog(eng, fn, geometry=None):
    from paddle_tpu.analysis.trace.harvest import _geometry

    args = _decode_args(eng)
    return T.TracedProgram(
        contract=T.get_contract("engine_decode_step"),
        config="dense,K=0,mp=2", mp=2, num_layers=2,
        jaxpr=jax.make_jaxpr(fn)(*args), lowered_text="",
        donated_leaves=0,
        geometry=geometry or _geometry(eng, 2, eng.num_slots))


def test_cli_acceptance_command_exits_zero():
    """THE gate, and the ISSUE acceptance command verbatim: the CLI
    harvests the full contract matrix and runs every TPU3xx rule plus
    the byte-drift comparison self-clean against the committed
    SHARD_BASELINE.json."""
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tpu_shard.py"),
         os.path.join(REPO, "paddle_tpu")],
        env=_env(), capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "tpu-shard clean: 44 programs" in res.stdout


def test_shard_baseline_is_committed_and_covers_the_matrix():
    """The committed snapshot has one entry per harvested program:
    every sharded (mp=2) engine step moves bytes over 'mp' only, in
    the three declared kinds; every mp=1 / conv / COW program pins an
    EMPTY axes map (growing a collective where none existed is drift
    too). The CLI acceptance test above proves the live harvest
    matches these totals exactly."""
    with open(DEFAULT_SHARD_BASELINE) as f:
        snap = json.load(f)["programs"]
    assert len(snap) == 44
    moving = {k for k, v in snap.items() if v["axes"]}
    assert len(moving) == 14
    for key in moving:
        assert "mp=2" in key, key
        assert set(snap[key]["axes"]) == {"mp"}
        assert set(snap[key]["axes"]["mp"]) <= \
            {"all_gather", "psum", "pmax"}
        for v in snap[key]["axes"]["mp"].values():
            assert v["count"] > 0 and v["moved_bytes"] > 0
    # the COW copy is sharded but collective-free; conv and mp=1
    # programs have no mesh at all
    for key in set(snap) - moving:
        assert "mp=2" not in key or key.startswith("engine_cow_copy")


def test_tpu301_fires_on_an_extra_all_gather(tiny_mp2_engine):
    """Deliberate break #1: one accidental extra all-gather appended
    to the mp=2 decode step busts the per-axis count (9 = 4/layer x 2
    layers + 1 fixed) and TPU301 names the axis; the real step — with
    its live geometry, so the BYTE caps are exercised too — passes."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    eng = tiny_mp2_engine
    extra = shard_map(
        lambda t: jax.lax.all_gather(t, "mp", axis=0, tiled=True),
        mesh=eng.mesh, in_specs=(P(),), out_specs=P(),
        check_rep=False)

    def broken_step(*a):
        nxt, kp, vp = eng._decode_pure(*a)
        return extra(nxt)[: nxt.shape[0]], kp, vp

    found = check_tpu301(build_record(_decode_prog(eng, broken_step)))
    assert [f.rule for f in found] == ["TPU301"]
    assert "all_gather crosses axis 'mp' 10x" in found[0].message
    assert "allowed 9" in found[0].message
    clean = build_record(_decode_prog(eng, eng._decode_pure))
    assert check_tpu301(clean) == []
    # the clean step's byte totals sit under the budget caps with the
    # REAL payload bounds evaluated (not just vacuously skipped)
    assert clean.axis_totals["mp"]["all_gather"]["moved_bytes"] > 0


def test_tpu302_fires_when_a_pool_lowers_replicated(tiny_mp2_engine):
    """Deliberate break #2: pinning a paged KV pool's in_sharding to
    replicated while the declared layout truth (pool_pspec) says
    head-sharded — every chip would silently pay mp x its HBM share.
    The engine's own sharding passes the same check."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    eng = tiny_mp2_engine
    # a host-side stand-in with the pool's exact geometry (the real
    # committed pool already carries its sharding, which jit would
    # rightly refuse to override)
    pool = np.zeros(eng.cache.kpool.shape, eng.cache.kpool.dtype)
    declared = (tuple(eng.cache.pool_pspec()),)

    def prog(sharding):
        lowered = jax.jit(lambda k: k + 1.0,
                          in_shardings=(sharding,)).lower(pool)
        return T.TracedProgram(
            contract=T.get_contract("engine_decode_step"),
            config="dense,K=0,mp=2", mp=2, num_layers=2,
            jaxpr=jax.make_jaxpr(lambda k: k + 1.0)(pool),
            lowered_text=lowered.as_text(), donated_leaves=0,
            declared_in_specs=declared)

    broken = prog(NamedSharding(eng.mesh, P()))
    found = check_tpu302(build_record(broken))
    assert [f.rule for f in found] == ["TPU302"]
    assert "lowered replicated" in found[0].message
    fixed = prog(NamedSharding(eng.mesh, eng.cache.pool_pspec()))
    rec = build_record(fixed)
    assert check_tpu302(rec) == []
    from paddle_tpu.analysis.shard.rules import check_tpu303
    assert check_tpu303(rec) == []


def test_axis_budget_table_pins_real_surfaces(tiny_mp2_engine):
    """The ONE per-axis budget table (introspect) is what the model
    module exports, what the engine contracts resolve to, and its
    rows describe the live mesh: axis 'mp' on ICI, kinds that are
    real collective primitives, payload bounds that evaluate to
    positive byte counts over the real harvest geometry — and the
    merged count view reproduces the legacy TPU104 budget exactly."""
    from paddle_tpu.analysis.trace.contracts import resolve_budget
    from paddle_tpu.analysis.trace.harvest import _geometry
    from paddle_tpu.analysis.trace.rules import COLLECTIVE_PRIMS
    from paddle_tpu.models import gpt

    budget = introspect.GPT_SERVING_AXIS_BUDGET
    assert gpt.GPT_SERVING_COLLECTIVES is budget
    for step in ("engine_decode_step", "engine_verify_step",
                 "engine_prefill", "engine_prefill_chunk"):
        assert resolve_budget(T.get_contract(step)) is budget
    assert budget.axis_names() == ("mp",)
    assert budget.link_of("mp") == "ici"
    assert budget.slow_axes() == ()
    assert set(budget.kinds()) <= COLLECTIVE_PRIMS
    geom = _geometry(tiny_mp2_engine, 2, tiny_mp2_engine.num_slots)
    for kind in budget.kinds():
        bounds = budget.payload_bounds("mp", kind)
        assert bounds, kind
        assert all(eval_payload(b, geom) > 0 for b in bounds), kind
    # the TPU104 count surface, unchanged through the refactor: 9
    # gathers (4/layer x 2 + 1 lm-head), 1 psum, 3 pmax at L=2
    assert budget.allowed("all_gather", 2) == 9
    assert budget.allowed("psum", 2) == 1
    assert budget.allowed("pmax", 2) == 3
    assert dict(budget.per_layer) == {"all_gather": 4, "pmax": 1}
    assert dict(budget.fixed) == {"all_gather": 1, "psum": 1,
                                  "pmax": 1}


def test_per_token_contracts_mark_the_decode_loop():
    """TPU305's latency classification rides the contract: the
    decode/verify steps (the per-generated-token host loop body) are
    per_token; prefills and the COW copy run per admission."""
    for step, hot in (("engine_decode_step", True),
                      ("engine_verify_step", True),
                      ("engine_prefill", False),
                      ("engine_prefill_chunk", False),
                      ("engine_cow_copy", False)):
        assert T.get_contract(step).per_token is hot, step


@pytest.fixture(scope="module")
def cli_rule_ids():
    """rule-id set per analysis CLI, straight from `--list-rules`."""
    out = {}
    for tool in _CLI_TOOLS:
        res = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", tool + ".py"),
             "--list-rules"],
            env=_env(), capture_output=True, text=True, timeout=300)
        assert res.returncode == 0, (tool, res.stdout + res.stderr)
        ids = {line.split()[0] for line in res.stdout.splitlines()
               if line.strip().startswith("TPU")}
        assert ids, tool
        out[tool] = ids
    return out


@pytest.mark.parametrize("a,b",
                         list(itertools.combinations(_CLI_TOOLS, 2)))
def test_cli_rule_namespaces_mutually_disjoint(cli_rule_ids, a, b):
    """End-to-end namespace disjointness: what the four CLIs actually
    ADVERTISE (not just the registries) never collides — a suppression
    or baseline entry can always be attributed to exactly one tier."""
    assert not (cli_rule_ids[a] & cli_rule_ids[b]), (a, b)


def test_tpu_shard_advertises_the_tpu3xx_block(cli_rule_ids):
    ids = cli_rule_ids["tpu_shard"]
    assert ids == {"TPU300", "TPU301", "TPU302", "TPU303", "TPU304",
                   "TPU305"}
