"""Packaging + CI bench regression gate (VERDICT r3 missing #4).

Reference analogs: tools/check_op_benchmark_result.py,
tools/ci_model_benchmark.sh, setup.py (packaging).
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GATE = os.path.join(REPO, "tools", "check_bench_result.py")


def _run(args):
    return subprocess.run([sys.executable, GATE] + args,
                          capture_output=True, text=True, timeout=120)


def _bench_lines(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


def test_gate_passes_within_threshold(tmp_path):
    base = {"m1": {"metric": "m1", "value": 100.0, "unit": "x/s"}}
    (tmp_path / "base.json").write_text(json.dumps(base))
    _bench_lines(tmp_path / "cur.jsonl",
                 [{"metric": "m1", "value": 95.0, "unit": "x/s"}])
    res = _run(["--bench", str(tmp_path / "cur.jsonl"),
                "--baseline", str(tmp_path / "base.json")])
    assert res.returncode == 0, res.stdout + res.stderr
    assert "bench gate ok" in res.stdout


def test_gate_fails_on_regression(tmp_path):
    base = {"m1": {"metric": "m1", "value": 100.0, "unit": "x/s"}}
    (tmp_path / "base.json").write_text(json.dumps(base))
    _bench_lines(tmp_path / "cur.jsonl",
                 [{"metric": "m1", "value": 80.0, "unit": "x/s"}])
    res = _run(["--bench", str(tmp_path / "cur.jsonl"),
                "--baseline", str(tmp_path / "base.json")])
    assert res.returncode == 1
    assert "REGRESSION GATE FAILED" in res.stdout
    assert "+20.0% regression" in res.stdout


def test_gate_fails_on_missing_or_failed_row(tmp_path):
    base = {"m1": {"metric": "m1", "value": 100.0},
            "m2": {"metric": "m2", "value": 10.0}}
    (tmp_path / "base.json").write_text(json.dumps(base))
    _bench_lines(tmp_path / "cur.jsonl",
                 [{"metric": "m1_FAILED", "value": 0, "unit": "error"},
                  {"metric": "m1", "value": 0, "unit": "error"}])
    res = _run(["--bench", str(tmp_path / "cur.jsonl"),
                "--baseline", str(tmp_path / "base.json")])
    assert res.returncode == 1
    assert "m2: missing" in res.stdout
    assert "m1: current run FAILED" in res.stdout


def test_gate_update_writes_baseline(tmp_path):
    _bench_lines(tmp_path / "cur.jsonl",
                 [{"metric": "m1", "value": 50.0, "unit": "x/s"}])
    res = _run(["--bench", str(tmp_path / "cur.jsonl"),
                "--baseline", str(tmp_path / "new.json"), "--update"])
    assert res.returncode == 0
    data = json.loads((tmp_path / "new.json").read_text())
    assert data["m1"]["value"] == 50.0


def test_gate_opbench_mode(tmp_path):
    base = {"op_a": {"op": "op_a", "ms": 1.0}}
    (tmp_path / "base.json").write_text(json.dumps(base))
    (tmp_path / "cur.json").write_text(json.dumps(
        {"op_a": {"op": "op_a", "ms": 2.0}}))
    res = _run(["--opbench", str(tmp_path / "cur.json"),
                "--baseline", str(tmp_path / "base.json")])
    assert res.returncode == 1
    assert "+100%" in res.stdout


def test_repo_baseline_is_current_format():
    """The committed BENCH_BASELINE.json gates the committed metric
    names — a renamed bench row must update the baseline too."""
    with open(os.path.join(REPO, "BENCH_BASELINE.json")) as f:
        base = json.load(f)
    for m in ("gpt_1p3b_train_tokens_per_sec_per_chip",
              "bert_base_finetune_tokens_per_sec_per_chip",
              "resnet50_train_images_per_sec_per_chip"):
        assert m in base
        assert base[m]["value"] > 0


def test_pyproject_packaging_metadata():
    """pip install -e . consumes this file; validate it statically
    (no network in the test env)."""
    try:
        import tomllib                   # 3.11+
    except ModuleNotFoundError:
        import tomli as tomllib          # the 3.10 backport

    with open(os.path.join(REPO, "pyproject.toml"), "rb") as f:
        meta = tomllib.load(f)
    assert meta["project"]["name"] == "paddle-tpu"
    assert "jax" in meta["project"]["dependencies"]
    inc = meta["tool"]["setuptools"]["packages"]["find"]["include"]
    assert "paddle_tpu*" in inc
    from setuptools import find_packages

    pkgs = find_packages(where=REPO, include=["paddle_tpu*"])
    assert "paddle_tpu" in pkgs and "paddle_tpu.distributed" in pkgs


def test_gate_floor_row_absolute_pass_condition(tmp_path):
    """VERDICT r5 next #8a: a row with a decided 'floor' is gated on
    clearing that absolute throughput, not on the relative drop vs its
    own best-ever value (the ResNet go/no-go shape)."""
    base = {"r": {"metric": "r", "value": 2435.0, "unit": "images/s",
                  "floor": 2350.0}}
    (tmp_path / "base.json").write_text(json.dumps(base))
    # 2360 is a >3% drop vs 2435 BUT clears the floor: pass
    _bench_lines(tmp_path / "cur.jsonl",
                 [{"metric": "r", "value": 2360.0, "unit": "images/s"}])
    res = _run(["--bench", str(tmp_path / "cur.jsonl"),
                "--baseline", str(tmp_path / "base.json"),
                "--threshold", "0.02"])
    assert res.returncode == 0, res.stdout + res.stderr
    # below the floor fails regardless of threshold
    _bench_lines(tmp_path / "cur.jsonl",
                 [{"metric": "r", "value": 2300.0, "unit": "images/s"}])
    res = _run(["--bench", str(tmp_path / "cur.jsonl"),
                "--baseline", str(tmp_path / "base.json"),
                "--threshold", "0.50"])
    assert res.returncode == 1
    assert "below the decided floor" in res.stdout


def test_gate_update_preserves_floor(tmp_path):
    base = {"r": {"metric": "r", "value": 2435.0, "floor": 2350.0}}
    (tmp_path / "base.json").write_text(json.dumps(base))
    _bench_lines(tmp_path / "cur.jsonl",
                 [{"metric": "r", "value": 2500.0, "unit": "images/s"}])
    res = _run(["--bench", str(tmp_path / "cur.jsonl"),
                "--baseline", str(tmp_path / "base.json"), "--update"])
    assert res.returncode == 0
    data = json.loads((tmp_path / "base.json").read_text())
    assert data["r"]["value"] == 2500.0 and data["r"]["floor"] == 2350.0
    # a partial run MISSING the floored row must not erase the decision
    _bench_lines(tmp_path / "cur.jsonl",
                 [{"metric": "other", "value": 1.0, "unit": "x/s"}])
    res = _run(["--bench", str(tmp_path / "cur.jsonl"),
                "--baseline", str(tmp_path / "base.json"), "--update"])
    assert res.returncode == 0
    data = json.loads((tmp_path / "base.json").read_text())
    assert data["r"]["floor"] == 2350.0 and data["r"]["value"] == 2500.0
    assert data["other"]["value"] == 1.0


def test_repo_resnet_row_carries_decided_floor():
    """The committed baseline encodes the ResNet go/no-go decision."""
    with open(os.path.join(REPO, "BENCH_BASELINE.json")) as f:
        base = json.load(f)
    assert base["resnet50_train_images_per_sec_per_chip"]["floor"] == 2350.0


def test_pending_smoke_flags_unadopted_opbench_rows():
    """--pending smoke (ISSUE 4 satellite): the suite rows added by
    PRs 1-18 stay VISIBLY pending until a TPU `bench_ops.py --save`
    refresh adopts them — the gate must keep saying so, loudly."""
    res = _run(["--pending", os.path.join(REPO, "OPBENCH.json")])
    assert res.returncode == 0, res.stdout + res.stderr  # report-only
    for row in ("gpt_decode_kv_350m", "gpt_engine_offered_load",
                "paged_attention_decode_sweep",
                "gpt_engine_offered_load_pallas",
                "gpt_engine_prefix_cache", "gpt_engine_chunked_prefill",
                "gpt_engine_speculative",
                "gpt_engine_offered_load_mp2",
                "gpt_engine_offered_load_int8",
                "gpt_fleet_offered_load",
                "gpt_engine_multitenant_lora", "gpt_engine_sampling",
                "conv_fused_sweep", "resnet50_fused_block",
                "conv_fused_bwd_sweep", "resnet50_fused_block_train",
                "gpt_engine_host_gap", "gpt_engine_async_overlap"):
        assert f"PENDING: {row}" in res.stdout, res.stdout
    assert "pending row(s) not gated" in res.stdout
    # --strict turns the report into a failure
    res = _run(["--pending", os.path.join(REPO, "OPBENCH.json"),
                "--strict"])
    assert res.returncode == 1
