"""ZeRO-3 end-to-end tests (VERDICT r2 #6): stage-3 params actually
sharded over 'sharding' with XLA inserting the just-in-time all-gathers,
training parity vs a plain eager loop, and host offload of optimizer
state (group_sharded offload analog) via pinned_host memory kind.

Reference analogs: distributed/sharding/group_sharded.py:37,
meta_parallel/sharding/group_sharded_stage3.py:1117.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import (HybridCommunicateGroup,
                                    set_hybrid_communicate_group)


def _make(seed):
    paddle.seed(seed)
    net = nn.Sequential(nn.Linear(16, 64), nn.Tanh(), nn.Linear(64, 16))
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=net.parameters())
    return net, opt


def _data(n=5):
    rs = np.random.RandomState(0)
    return [(rs.randn(8, 16).astype(np.float32),
             rs.randn(8, 16).astype(np.float32)) for _ in range(n)]


def _eager_losses(data, seed):
    net, opt = _make(seed)
    losses = []
    for x, y in data:
        loss = F.mse_loss(net(paddle.to_tensor(x)), paddle.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses, net


def test_stage3_param_sharded_and_parity():
    data = _data()
    ref_losses, ref_net = _eager_losses(data, seed=11)

    hcg = HybridCommunicateGroup(sharding=8)
    set_hybrid_communicate_group(hcg)
    net, opt = _make(seed=11)
    net, opt, _ = dist.group_sharded_parallel(net, opt, level="p_g_os")
    # no explicit sharding_stage: must come from group_sharded_parallel
    step = dist.DistributedTrainStep(net, opt,
                                     lambda o, t: F.mse_loss(o, t), hcg=hcg)
    losses = [float(step(paddle.to_tensor(x), paddle.to_tensor(y)))
              for x, y in data]
    set_hybrid_communicate_group(HybridCommunicateGroup())

    assert step.sharding_stage == 3
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4, atol=1e-6)
    # ZeRO-3: the weights themselves are sharded over 'sharding'
    w = net[0].weight
    assert "sharding" in str(w._array.sharding.spec)
    m = opt._accumulators["moment1"][0]
    assert "sharding" in str(m.sharding.spec)
    # final weights match the eager baseline
    for (k, a), (_, b) in zip(net.state_dict().items(),
                              ref_net.state_dict().items()):
        np.testing.assert_allclose(np.asarray(a._array),
                                   np.asarray(b._array),
                                   rtol=1e-4, atol=1e-6, err_msg=k)


def test_stage3_offload_host_resident_opt_state():
    data = _data()
    ref_losses, _ = _eager_losses(data, seed=12)

    hcg = HybridCommunicateGroup(sharding=8)
    set_hybrid_communicate_group(hcg)
    net, opt = _make(seed=12)
    with pytest.warns(UserWarning, match="offload takes effect"):
        net, opt, _ = dist.group_sharded_parallel(net, opt, level="p_g_os",
                                                  offload=True)
    # no level/offload here: must come from the model attrs
    step = dist.make_sharded_step(net, opt, lambda o, t: F.mse_loss(o, t))
    losses = [float(step(paddle.to_tensor(x), paddle.to_tensor(y)))
              for x, y in data]
    set_hybrid_communicate_group(HybridCommunicateGroup())

    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4, atol=1e-6)
    # optimizer state parked in host memory between steps
    m = opt._accumulators["moment1"][0]
    assert m.sharding.memory_kind == "pinned_host"
