"""Regression tests for ADVICE round-3 findings.

1 (medium): buffer updates (BN running stats, SpectralNorm u/v power
   iteration) must persist across compiled TrainStep /
   DistributedTrainStep calls — previously bound_state restored them
   every step, so sigma never converged and BN eval stats stayed at
   init under compiled training.
2 (low): unfold/fold run the patch conv at HIGHEST precision (pure data
   movement must be exact).
3 (low): Engine.predict feeds the WHOLE batch as inputs (no label
   split) so multi-input unlabeled datasets keep their last input.
4 (low): ASP n:m masks are re-applied inside the compiled update, not
   just eager optimizer.step.
5 (low): complex() on complex-less backends keeps gradients to both
   inputs and derives the complex dtype from the inputs.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.jit as jit
import paddle_tpu.nn as nn


class _BNNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.conv = nn.Conv2D(3, 4, 3, padding=1)
        self.bn = nn.BatchNorm2D(4)
        self.fc = nn.Linear(4, 2)

    def forward(self, x):
        h = self.bn(self.conv(x)).mean(axis=[2, 3])
        return self.fc(h)


def _loss(out, label):
    return ((out - label) ** 2).mean()


def test_bn_running_stats_advance_under_trainstep():
    paddle.seed(0)
    model = _BNNet()
    opt = paddle.optimizer.SGD(learning_rate=0.01,
                               parameters=model.parameters())
    step = jit.TrainStep(model, opt, _loss)
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(4, 3, 8, 8).astype(np.float32))
    y = paddle.to_tensor(np.zeros((4, 2), np.float32))
    m0 = np.asarray(model.bn._mean._array).copy()
    step(x, y)
    m1 = np.asarray(model.bn._mean._array)
    assert not np.allclose(m0, m1), \
        "BN running mean did not advance under compiled TrainStep"
    # a second step advances again (state threads, not just one write)
    step(x, y)
    m2 = np.asarray(model.bn._mean._array)
    assert not np.allclose(m1, m2)


def test_bn_stats_match_eager_under_trainstep():
    """The compiled step's stat update must equal the eager one."""
    rs = np.random.RandomState(1)
    xnp = rs.randn(4, 3, 8, 8).astype(np.float32)
    ynp = np.zeros((4, 2), np.float32)

    paddle.seed(0)
    m_eager = _BNNet()
    out = m_eager(paddle.to_tensor(xnp))
    loss = _loss(out, paddle.to_tensor(ynp))
    loss.backward()  # grads unused; forward already updated stats

    paddle.seed(0)
    m_comp = _BNNet()
    opt = paddle.optimizer.SGD(learning_rate=0.0,
                               parameters=m_comp.parameters())
    jit.TrainStep(m_comp, opt, _loss)(paddle.to_tensor(xnp),
                                      paddle.to_tensor(ynp))
    np.testing.assert_allclose(np.asarray(m_eager.bn._mean._array),
                               np.asarray(m_comp.bn._mean._array),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(m_eager.bn._variance._array),
                               np.asarray(m_comp.bn._variance._array),
                               rtol=1e-5, atol=1e-6)


def test_bn_stats_advance_under_run_repeat_and_scan():
    paddle.seed(0)
    model = _BNNet()
    opt = paddle.optimizer.SGD(learning_rate=0.01,
                               parameters=model.parameters())
    step = jit.TrainStep(model, opt, _loss)
    x = paddle.to_tensor(np.random.RandomState(2)
                         .randn(4, 3, 8, 8).astype(np.float32))
    y = paddle.to_tensor(np.zeros((4, 2), np.float32))
    m0 = np.asarray(model.bn._mean._array).copy()
    step.run_repeat(x, y, steps=3)
    m1 = np.asarray(model.bn._mean._array)
    assert not np.allclose(m0, m1)
    xs = paddle.to_tensor(np.random.RandomState(3)
                          .randn(2, 4, 3, 8, 8).astype(np.float32))
    ys = paddle.to_tensor(np.zeros((2, 4, 2), np.float32))
    step.run_scan(xs, ys)
    m2 = np.asarray(model.bn._mean._array)
    assert not np.allclose(m1, m2)


class _SNNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(6, 6)
        self.sn = nn.SpectralNorm([6, 6], power_iters=1)
        self.out = nn.Linear(6, 1)

    def forward(self, x):
        w = self.sn(self.fc.weight)
        return self.out(x @ w + self.fc.bias)


def test_spectral_norm_power_iteration_converges_compiled():
    """u/v must advance across compiled steps: with power_iters=1 the
    sigma estimate converges to the true max singular value only if
    state persists (the round-3 advisor finding)."""
    paddle.seed(0)
    model = _SNNet()
    opt = paddle.optimizer.SGD(learning_rate=0.0,  # freeze params
                               parameters=model.parameters())
    step = jit.TrainStep(model, opt, _loss)
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(4, 6).astype(np.float32))
    y = paddle.to_tensor(np.zeros((4, 1), np.float32))
    u0 = np.asarray(model.sn.weight_u._array).copy()
    for _ in range(30):
        step(x, y)
    u_final = np.asarray(model.sn.weight_u._array)
    assert not np.allclose(u0, u_final), \
        "SpectralNorm u did not advance under compiled training"
    # after many persisted iterations sigma(u,v) ~= true sigma_max
    w = np.asarray(model.fc.weight._array)
    v = np.asarray(model.sn.weight_v._array)
    sigma_est = float(u_final @ (w @ v))
    sigma_true = float(np.linalg.svd(w, compute_uv=False)[0])
    assert abs(sigma_est - sigma_true) / sigma_true < 1e-3


def test_unfold_fold_exact_roundtrip():
    import paddle_tpu.nn.functional as F

    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(2, 3, 8, 8).astype(np.float32))
    cols = F.unfold(x, 3, strides=1, paddings=1)
    back = F.fold(cols, (8, 8), 3, strides=1, paddings=1)
    # every pixel is covered by a known number of patches; dividing by
    # the coverage count must reproduce x EXACTLY (data movement only)
    ones = paddle.ones_like(x)
    cnt = F.fold(F.unfold(ones, 3, strides=1, paddings=1), (8, 8), 3,
                 strides=1, paddings=1)
    rec = np.asarray(back._array) / np.asarray(cnt._array)
    # float32 summation order costs ~1e-7 relative; the bf16 default-
    # precision bug this guards against costs ~2e-3
    np.testing.assert_allclose(rec, np.asarray(x._array),
                               rtol=1e-5, atol=1e-6)


def test_engine_predict_multi_input_no_label_split():
    from paddle_tpu.distributed.auto_parallel import Engine
    from paddle_tpu.distributed.topology import (
        HybridCommunicateGroup, set_hybrid_communicate_group)

    set_hybrid_communicate_group(HybridCommunicateGroup())

    class TwoIn(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 2)

        def forward(self, a, b):
            return self.fc(a + b)

    paddle.seed(0)
    model = TwoIn()
    eng = Engine(model)
    a = np.random.RandomState(0).randn(6, 4).astype(np.float32)
    b = np.random.RandomState(1).randn(6, 4).astype(np.float32)

    class DS(paddle.io.Dataset):
        def __len__(self):
            return 6

        def __getitem__(self, i):
            return a[i], b[i]

    pred = eng.predict(DS(), batch_size=3)
    model.eval()
    want = np.asarray(model(paddle.to_tensor(a),
                            paddle.to_tensor(b))._array)
    np.testing.assert_allclose(pred, want, rtol=1e-5, atol=1e-6)


def test_asp_masks_hold_under_trainstep():
    from paddle_tpu.incubate import asp

    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 2))
    masks = asp.prune_model(model, n=2, m=4)
    assert masks, "prune_model found nothing to prune"
    opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                 parameters=model.parameters())
    step = jit.TrainStep(model, opt, _loss)
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(4, 8).astype(np.float32))
    y = paddle.to_tensor(np.zeros((4, 2), np.float32))
    for _ in range(3):
        step(x, y)
    w = np.asarray(model[0].weight._array)
    assert asp.check_mask_1d(w, n=2, m=4), \
        "n:m sparsity decayed under compiled training"


def test_complex_fallback_grads_and_dtype(monkeypatch):
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core import device as device_mod

    # force the complex-less fallback path even on CPU
    monkeypatch.setattr(device_mod, "_supports_complex", False)
    r = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                         stop_gradient=False)
    i = paddle.to_tensor(np.array([3.0, 4.0], np.float32),
                         stop_gradient=False)
    c = paddle.complex(r, i)
    assert np.asarray(c._array).dtype == np.complex64
    loss = (c.real() * 2 + c.imag() * 3).sum()
    loss.backward()
    np.testing.assert_allclose(np.asarray(r.grad._array), [2.0, 2.0])
    np.testing.assert_allclose(np.asarray(i.grad._array), [3.0, 3.0])


def test_recompute_threads_bn_buffers():
    """recompute (jax.checkpoint) composed with BatchNorm inside a
    compiled TrainStep: no tracer leak, and running stats advance
    (the buffer updates ride the vjp aux, r4 fix)."""
    from paddle_tpu.distributed.recompute import recompute

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.block = nn.Sequential(
                nn.Conv2D(3, 4, 3, padding=1), nn.BatchNorm2D(4),
                nn.ReLU())
            self.fc = nn.Linear(4, 2)

        def forward(self, x):
            h = recompute(self.block, x)
            return self.fc(h.mean(axis=[2, 3]))

    paddle.seed(0)
    model = Net()
    opt = paddle.optimizer.SGD(learning_rate=0.01,
                               parameters=model.parameters())
    step = jit.TrainStep(model, opt, _loss)
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(4, 3, 8, 8).astype(np.float32))
    y = paddle.to_tensor(np.zeros((4, 2), np.float32))
    bn = model.block[1]
    m0 = np.asarray(bn._mean._array).copy()
    w0 = np.asarray(model.block[0].weight._array).copy()
    step(x, y)
    assert not np.allclose(m0, np.asarray(bn._mean._array)), \
        "BN stats did not advance through recompute"
    assert not np.allclose(w0, np.asarray(model.block[0].weight._array)), \
        "grads did not reach the rematted block's params"


def test_shared_sublayer_no_double_donation():
    """A layer registered under two parents yields duplicate
    parameters()/buffers() entries; the compiled step must dedup them
    (duplicates crash XLA donation with INVALID_ARGUMENT, r4 fix)."""

    class Shared(nn.Layer):
        def __init__(self):
            super().__init__()
            self.body = nn.Sequential(nn.Linear(4, 4), nn.ReLU())
            self.alias = self.body  # second registration, same object
            self.bn = nn.BatchNorm1D(4)
            self.bn_alias = self.bn
            self.out = nn.Linear(4, 2)

        def forward(self, x):
            h = self.alias(self.body(x))
            h = self.bn(h.unsqueeze(-1)).squeeze(-1)
            return self.out(h)

    paddle.seed(0)
    model = Shared()
    opt = paddle.optimizer.SGD(learning_rate=0.01,
                               parameters=model.parameters())
    step = jit.TrainStep(model, opt, _loss)
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(4, 4).astype(np.float32))
    y = paddle.to_tensor(np.zeros((4, 2), np.float32))
    l0 = float(step(x, y))
    l1 = float(step(x, y))
    assert np.isfinite(l0) and np.isfinite(l1)


def test_avg_pool3d_divisor_override():
    import paddle_tpu.nn.functional as F

    x = paddle.to_tensor(np.ones((1, 1, 4, 4, 4), np.float32))
    out = F.avg_pool3d(x, kernel_size=2, stride=2, divisor_override=4)
    # window sum is 8 ones; / 4 override = 2
    np.testing.assert_allclose(np.asarray(out._array),
                               np.full((1, 1, 2, 2, 2), 2.0))


def test_hybrid_coo_partial_sparse_dim():
    a = np.zeros((3, 2), np.float32)
    a[1] = [5.0, 0.0]
    t = paddle.to_tensor(a)
    sp = t.to_sparse_coo(1)  # hybrid: 1 sparse dim, 1 dense dim
    assert sp.nnz() == 1
    np.testing.assert_array_equal(np.asarray(sp.indices()._array), [[1]])
    np.testing.assert_array_equal(np.asarray(sp.values()._array),
                                  [[5.0, 0.0]])
    np.testing.assert_allclose(np.asarray(sp.to_dense()._array), a)


def test_asp_mask_2d_greedy():
    from paddle_tpu.incubate import asp

    rs = np.random.RandomState(0)
    w = rs.randn(8, 8).astype(np.float32)
    mask = asp.create_mask_2d_greedy(w, n=2, m=4)
    assert asp.check_mask_2d(w * mask, n=2, m=4)
    # exactly n*m survivors per complete block
    for r in range(0, 8, 4):
        for c in range(0, 8, 4):
            assert mask[r:r + 4, c:c + 4].sum() == 8
    # greedy keeps the largest entry of every block
    for r in range(0, 8, 4):
        for c in range(0, 8, 4):
            blk = np.abs(w[r:r + 4, c:c + 4])
            i, j = np.unravel_index(blk.argmax(), blk.shape)
            assert mask[r + i, c + j] == 1.0
    # a 1d-only mask generally violates the 2d column constraint check
    assert not asp.check_mask_2d(np.eye(8) * 0 + [1, 1, 0, 0] * 2)

    # prune_model accepts the algo and sparsity holds under training
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 2))
    masks = asp.prune_model(model, n=2, m=4, mask_algo="mask_2d_greedy")
    assert masks
    assert asp.check_mask_2d(np.asarray(model[0].weight._array))
