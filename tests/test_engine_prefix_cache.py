"""Prefix-cached paged KV + chunked prefill + admission QoS (ISSUE 6).

The serving-scheduler contracts, proven the way PR 1/3 proved theirs:
token-exact parity (prefix cache on vs off, cold vs warm, chunked vs
legacy whole-bucket prefill, all against the single-request compiled
decode oracle), copy-on-write leaving cached KV byte-identical,
refcount/eviction bookkeeping, trace-count bounds via jit.count_traces
(decode == 1, chunked prefill == 1 regardless of prompt-length mix),
allocator hardening (double-free / null-block free raise), QoS
priority admission + shed-on-saturation, and the instant-finish TPOT
accounting fix.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.jit as jit
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.inference import GenerationEngine, PagedKVCache
from paddle_tpu.observability.metrics import series_total

VOCAB = 61


def _model(seed=0):
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(seed)
    cfg = GPTConfig.tiny(vocab=VOCAB, hidden=32, layers=2, heads=2,
                         seq=64)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return _model()


def _reference(model, prompt, max_new, eos=None):
    out = model.generate(
        Tensor._wrap(np.asarray(prompt, np.int32)[None]),
        max_length=len(prompt) + max_new, eos_token_id=eos,
        use_cache=True)
    return np.asarray(out._array)[0]


# ---------------------------------------------------------------------------
# satellite: allocator hardening
# ---------------------------------------------------------------------------

def test_paged_kv_cache_free_hardening():
    """free() must raise on double-free and on the null block — a
    scheduler bug silently double-allocating a live block is the worst
    kind of KV corruption (two requests writing one block)."""
    c = PagedKVCache(1, 6, 4, 2, 8)
    blocks = c.allocate(2)
    assert all(c.refcount(b) == 1 for b in blocks)
    c.free(blocks)
    with pytest.raises(RuntimeError, match="double free"):
        c.free([blocks[0]])
    with pytest.raises(ValueError, match="null block"):
        c.free([0])
    # share/free pairs keep the count exact
    (b,) = c.allocate(1)
    c.share([b])
    assert c.refcount(b) == 2
    c.free([b])
    assert c.refcount(b) == 1
    c.free([b])
    with pytest.raises(RuntimeError, match="double free"):
        c.free([b])
    with pytest.raises(RuntimeError, match="dead block"):
        c.share([b])


def test_prefix_cache_match_register_evict_lifecycle():
    """Unit-level prefix map mechanics: register publishes full blocks,
    match takes refs (reviving evictable entries), refcount-zero cached
    blocks are evicted LRU-deepest-first only under allocation
    pressure, and first-writer-wins on hash races."""
    c = PagedKVCache(1, 8, 4, 2, 8)        # 7 usable blocks
    toks = np.arange(12, dtype=np.int32)   # 3 full blocks
    blocks = c.allocate(3)
    assert c.register_prefix(toks, blocks) == 3
    assert c.num_cached_blocks == 3
    # a racing identical prompt keeps the original mapping
    other = c.allocate(3)
    assert c.register_prefix(toks, other) == 0
    c.free(other)

    hit_blocks, hit = c.match_prefix(np.concatenate([toks, [7, 7]]))
    assert hit == 12 and hit_blocks == blocks
    assert all(c.refcount(b) == 2 for b in blocks)
    c.free(hit_blocks)
    # a shorter prefix only matches its aligned part
    part, hit = c.match_prefix(toks[:9])   # 2 full blocks + 1 token
    assert hit == 8 and part == blocks[:2]
    c.free(part)
    # a diverging prompt misses
    div = toks.copy()
    div[0] += 1
    assert c.match_prefix(div) == ([], 0)

    # owner releases: blocks go EVICTABLE (still matchable), not free
    c.free(blocks)
    assert c.num_free == 7 and c.num_cached_blocks == 3
    again, hit = c.match_prefix(toks)
    assert hit == 12 and again == blocks   # revived from evictable
    c.free(blocks)
    # allocation pressure evicts cold cache blocks (deepest link first)
    got = c.allocate(6)                    # 4 free + 2 evicted
    assert got is not None and c.num_cached_blocks == 1
    _, hit = c.match_prefix(toks)
    assert hit == 4                        # only the chain head is left
    assert c.allocate(2) is None           # stall path intact


# ---------------------------------------------------------------------------
# tentpole: token-exact parity across every scheduler mode
# ---------------------------------------------------------------------------

def _trace(rng, n):
    return [(rng.randint(0, VOCAB, rng.randint(1, 14)).astype(np.int32),
             int(rng.randint(2, 9))) for _ in range(n)]


def _run_trace(eng, reqs, midrun=True):
    ids = [eng.add_request(p, n) for p, n in reqs[:len(reqs) // 2]]
    if midrun:
        for _ in range(2):
            eng.step()                 # admissions land mid-decode
    ids += [eng.add_request(p, n) for p, n in reqs[len(reqs) // 2:]]
    out = eng.run()
    return [np.asarray(out[rid]) for rid in ids]


def test_chunked_cache_on_off_and_bucketed_all_token_identical(model):
    """THE acceptance gate: one mixed trace (prompts shorter and longer
    than the chunk, shared prefixes by construction) through (a) legacy
    whole-bucket prefill, (b) chunked with the prefix cache off,
    (c) chunked+cache cold, (d) chunked+cache warm — identical outputs
    everywhere, equal to the single-request oracle; decode compiles
    once and the chunked prefill compiles once TOTAL (bounded by the
    chunk shape, not the prompt-length mix); the warm pass serves hit
    tokens without prefill compute."""
    rng = np.random.RandomState(11)
    base = _trace(rng, 6)
    shared = rng.randint(0, VOCAB, 8).astype(np.int32)   # hot prefix
    reqs = base + [
        (np.concatenate([shared, rng.randint(0, VOCAB, 3)])
         .astype(np.int32), 4),
        (np.concatenate([shared, rng.randint(0, VOCAB, 5)])
         .astype(np.int32), 3),
        (shared.copy(), 4),            # block-aligned full-prefix hit
    ]

    def mk(**kw):
        return GenerationEngine(model, num_slots=3, block_size=4,
                                num_blocks=64, **kw)

    outs_bucketed = _run_trace(mk(prefill_buckets=(16, 64)), reqs)
    eng_off = mk(prefill_chunk=8, enable_prefix_cache=False)
    outs_off = _run_trace(eng_off, reqs)
    eng = mk(prefill_chunk=8)
    outs_cold = _run_trace(eng, reqs)
    chunks_cold = series_total(eng.metrics_snapshot(),
                               "engine_prefill_chunks_total")
    outs_warm = _run_trace(eng, reqs, midrun=False)   # same engine
    snap = eng.metrics_snapshot()
    chunks_warm = series_total(
        snap, "engine_prefill_chunks_total") - chunks_cold

    for (p, n), a, b, c, d in zip(reqs, outs_bucketed, outs_off,
                                  outs_cold, outs_warm):
        want = _reference(model, p, n)
        np.testing.assert_array_equal(a, want)
        np.testing.assert_array_equal(b, want)
        np.testing.assert_array_equal(c, want)
        np.testing.assert_array_equal(d, want)

    # cache off never hits; cold run hits the shared prefix reqs
    assert eng_off.prefix_hit_tokens == 0
    assert series_total(snap,
                        "engine_prefix_cache_hit_tokens_total") > 0
    # warm pass: every prompt re-served from cache -> fewer chunks
    assert 0 < chunks_warm < chunks_cold
    # trace bounds: ONE decode program, ONE chunk program, ONE cow
    # program across all of that churn (cache on/off, cold/warm)
    for e in (eng, eng_off):
        assert e.decode_traces == 1
        assert e.prefill_traces == 1
    assert eng._cow_pure.traces <= 1
    # steady state: a warmed engine retraces NOTHING
    with jit.expect_traces(eng._decode_pure, 0), \
            jit.expect_traces(eng._prefill_pure, 0):
        eng.add_request(rng.randint(0, VOCAB, 13), 3)
        eng.run()
    # drained: every block reference returned (cached blocks count as
    # allocatable capacity)
    assert eng.cache.num_free == eng.cache.num_blocks - 1


def test_full_prefix_hit_cow_keeps_cached_blocks_byte_identical(model):
    """A block-aligned prompt served twice: the second request seats
    ALL its blocks from the cache (zero prefill chunks) and its first
    decode write lands inside a cached block — copy-on-write must give
    it a private copy and leave the cached KV bytes untouched, so a
    third request still hits pristine content."""
    from paddle_tpu.ops.paged_attention import dense_gather_reference

    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           num_blocks=32, prefill_chunk=8)
    rng = np.random.RandomState(5)
    prompt = rng.randint(0, VOCAB, 8).astype(np.int32)  # 2 full blocks
    want = _reference(model, prompt, 5)

    ra = eng.add_request(prompt, 5)
    outa = eng.run()
    np.testing.assert_array_equal(np.asarray(outa[ra]), want)
    cached, hit = eng.cache.match_prefix(prompt)
    assert hit == 8
    row = np.zeros(eng.max_blocks, np.int32)
    row[:len(cached)] = cached
    gk0, gv0 = dense_gather_reference(eng.cache.kpool, eng.cache.vpool,
                                      0, row, 8)
    eng.cache.free(cached)

    chunks0 = series_total(eng.metrics_snapshot(),
                           "engine_prefill_chunks_total")
    rb = eng.add_request(prompt, 5)
    outb = eng.run()
    snap = eng.metrics_snapshot()
    np.testing.assert_array_equal(np.asarray(outb[rb]), want)
    # full hit: no prefill chunk ran, COW promoted the write block
    assert series_total(snap, "engine_prefill_chunks_total") == chunks0
    assert series_total(snap, "engine_cow_copies_total") >= 1
    # the cached blocks' KV is byte-identical after B's decode run
    gk1, gv1 = dense_gather_reference(eng.cache.kpool, eng.cache.vpool,
                                      0, row, 8)
    np.testing.assert_array_equal(np.asarray(gk0), np.asarray(gk1))
    np.testing.assert_array_equal(np.asarray(gv0), np.asarray(gv1))
    # and a third request still decodes exactly
    rc = eng.add_request(prompt, 5)
    np.testing.assert_array_equal(np.asarray(eng.run()[rc]), want)


def test_eviction_under_pressure_stays_exact(model):
    """A pool far smaller than the distinct-prompt working set: cold
    cached blocks must be evicted (LRU) to serve new admissions, with
    every output still exact and the allocator ending balanced."""
    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           num_blocks=9, prefill_chunk=8)
    rng = np.random.RandomState(7)
    reqs = [(rng.randint(0, VOCAB, 8).astype(np.int32), 3)
            for _ in range(6)]          # 6 distinct 2-block prompts
    for p, n in reqs:
        rid = eng.add_request(p, n)
        np.testing.assert_array_equal(np.asarray(eng.run()[rid]),
                                      _reference(model, p, n))
    snap = eng.metrics_snapshot()
    # the cache filled, then pressure forced evictions: fewer resident
    # cached blocks than the 12 full prompt blocks seen
    resident = snap["engine_prefix_cached_blocks"]["series"][0]["value"]
    assert 0 < resident <= 8
    assert eng.cache.num_free == eng.cache.num_blocks - 1
    # a repeat of the LAST prompt still hits (most recently used)
    base = eng.prefix_hit_tokens
    rid = eng.add_request(reqs[-1][0], 2)
    eng.run()
    assert eng.prefix_hit_tokens > base


# ---------------------------------------------------------------------------
# tentpole: admission QoS
# ---------------------------------------------------------------------------

def test_priority_classes_order_admission_and_label_metrics(model):
    """Priority classes admit best-first regardless of arrival order,
    and TTFT/TPOT land in priority-labeled series."""
    eng = GenerationEngine(model, num_slots=1, block_size=4,
                           num_blocks=32, prefill_chunk=8)
    rng = np.random.RandomState(3)
    # prompts span two chunks, so after one step the admitted request
    # is still seated (mid-prefill) and observable
    rb = eng.add_request(rng.randint(0, VOCAB, 12), 2, priority="batch")
    ri = eng.add_request(rng.randint(0, VOCAB, 12), 2,
                         priority="interactive")
    eng.step()                          # one admission: the single lane
    seated = [s for s in eng._slots if s is not None]
    assert seated and seated[0].req.req_id == ri   # jumped the queue
    out = eng.run()
    assert set(out) == {rb, ri}
    with pytest.raises(ValueError, match="priority"):
        eng.add_request([1, 2], 2, priority="vip")
    snap = eng.metrics_snapshot()
    ttft_by = {s["labels"]["priority"]: s["count"]
               for s in snap["engine_ttft_seconds"]["series"]}
    assert ttft_by.get("interactive") == 1
    assert ttft_by.get("batch") == 1


def test_shed_on_saturation_prefers_high_priority(model):
    """max_queue exceeded: the lowest class loses — either the worst
    queued request (when the incoming ranks higher) or the incoming
    one; shed results surface as None and engine_shed_total counts
    them by class."""
    eng = GenerationEngine(model, num_slots=1, block_size=4,
                           num_blocks=32, prefill_chunk=8, max_queue=2)
    rng = np.random.RandomState(4)
    p = rng.randint(0, VOCAB, 4).astype(np.int32)
    keep = [eng.add_request(p, 2, priority="standard"),
            eng.add_request(p, 2, priority="batch")]
    # queue full (lane not yet filled: nothing ran). Interactive
    # arrival sheds the newest batch request...
    vip = eng.add_request(p, 2, priority="interactive")
    # ...and a batch arrival into a still-full queue sheds ITSELF
    loser = eng.add_request(p, 2, priority="batch")
    out = eng.run()
    assert out[keep[1]] is None and out[loser] is None
    assert out[keep[0]] is not None and out[vip] is not None
    np.testing.assert_array_equal(np.asarray(out[vip]),
                                  _reference(model, p, 2))
    snap = eng.metrics_snapshot()
    shed_by = {s["labels"]["priority"]: s["value"]
               for s in snap["engine_shed_total"]["series"]}
    assert shed_by == {"batch": 2.0}


# ---------------------------------------------------------------------------
# satellite: instant-finish TPOT accounting
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", ["chunked", "bucketed"])
def test_instant_finish_lands_in_tpot_histogram(model, mode):
    """A max_new_tokens==1 request produces exactly one token and used
    to vanish from the TPOT histogram while still counting in
    engine_tokens_generated_total; its producing-step latency must now
    be recorded — in both prefill modes, and on the full-prefix-hit
    decode path too."""
    kw = {"prefill_chunk": 8} if mode == "chunked" \
        else {"prefill_buckets": (16, 64)}
    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           num_blocks=32, **kw)
    rng = np.random.RandomState(9)
    p = rng.randint(0, VOCAB, 8).astype(np.int32)
    eng.add_request(p, 1)
    eng.run()
    snap = eng.metrics_snapshot()
    tpot = sum(s["count"]
               for s in snap["engine_tpot_seconds"]["series"])
    assert tpot == 1                   # the single token is visible
    assert series_total(snap, "engine_tokens_generated_total") == 1
    if mode == "chunked":
        # the same prompt again: full-prefix hit, first token comes
        # from the DECODE step — still visible
        eng.add_request(p, 1)
        eng.run()
        snap = eng.metrics_snapshot()
        assert sum(s["count"] for s in
                   snap["engine_tpot_seconds"]["series"]) == 2


# ---------------------------------------------------------------------------
# satellite: bench rows (CI-scale runners + suite registration)
# ---------------------------------------------------------------------------

def test_prefix_cache_and_chunked_bench_rows(monkeypatch):
    """The two new SUITE_ROWS at test scale: the multi-tenant trace
    runner must show warm prefix hits skipping prefill compute (hit
    tokens > 0, fewer chunk dispatches than cold) and the chunked-
    prefill row must report tail-TPOT for both prefill modes."""
    monkeypatch.delenv("PADDLE_PAGED_ATTENTION_BACKEND", raising=False)
    import bench_ops
    from paddle_tpu.models import GPTConfig

    cfg = GPTConfig.tiny(vocab=32, hidden=16, layers=1, heads=2, seq=64)
    paddle.seed(0)
    rec = bench_ops._engine_prefix_cache_case(
        model_cfg=cfg, num_tenants=2, per_tenant=2, uniques=1,
        prefix_len=8, suffix_max=4, max_new=3, num_slots=2,
        block_size=4, prefill_chunk=8)()
    assert rec["hit_tokens"] > 0
    assert rec["prefill_chunks_warm"] < rec["prefill_chunks_cold"]
    assert rec["tokens_per_s"] > 0 and rec["ms"] > 0

    paddle.seed(0)
    rec = bench_ops._engine_chunked_prefill_case(
        model_cfg=cfg, long_prompt=24, decode_lanes=1, max_new=6,
        num_slots=2, block_size=4, prefill_chunk=8)()
    assert rec["ms"] > 0
    assert rec["tpot_ms_p99_chunked"] is not None
    assert rec["tpot_ms_p99_whole"] is not None

    names = bench_ops.suite_names()
    assert "gpt_engine_prefix_cache" in names
    assert "gpt_engine_chunked_prefill" in names
