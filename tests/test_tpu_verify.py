"""tpu-verify unit tests: per-rule golden fixtures (a minimal traced
program that FIRES each TPU1xx rule and a minimal one that must NOT),
contract waiver semantics, drift-snapshot comparison, finding-ID
stability, and the no-backend import smoke.

The fixtures build TracedProgram records directly from tiny local
functions — the rules are pure functions over (jaxpr, lowered text,
arg leaves), so they are provable without constructing engines.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.analysis.trace import (CollectiveBudget, TraceContract,
                                       TracedProgram, check_program,
                                       compare_snapshot, snapshot_of)
from paddle_tpu.analysis.trace.rules import (check_tpu101, check_tpu102,
                                             check_tpu103, check_tpu104,
                                             check_tpu105, check_tpu106)
from paddle_tpu.analysis.findings import assign_ids

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def trace_prog(fn, args, contract, mp=1, num_layers=1):
    """Build a TracedProgram for a fixture fn exactly the way the
    harvester does (make_jaxpr + jit(...).lower with the contract's
    donation)."""
    closed = jax.make_jaxpr(fn)(*args)
    lowered = jax.jit(
        fn, donate_argnums=contract.donate_argnums).lower(*args)
    donated = sum(len(jax.tree_util.tree_leaves(args[i]))
                  for i in contract.donate_argnums)
    leaves = [(jax.tree_util.keystr(p), leaf) for p, leaf in
              jax.tree_util.tree_flatten_with_path(args)[0]]
    return TracedProgram(
        contract=contract, config="fixture", mp=mp,
        num_layers=num_layers, jaxpr=closed,
        lowered_text=lowered.as_text(), donated_leaves=donated,
        arg_leaves=leaves)


def _contract(**kw):
    kw.setdefault("name", "fixture_step")
    kw.setdefault("declared_at", "tests/test_tpu_verify.py")
    return TraceContract(**kw)


def _mesh(n=2):
    return jax.sharding.Mesh(np.array(jax.devices()[:n]), ("mp",))


# -- TPU101 donation-actually-applied -----------------------------------

def test_tpu101_positive_dropped_alias():
    """Donating a buffer whose 'updated' output changed dtype: jax
    silently drops the alias (a warning at most) — the rule turns
    that into a failure."""
    def step(pool, tok):
        return tok.sum(), (pool + 1.0).astype(jnp.bfloat16)

    c = _contract(donate_argnums=(0,))
    with pytest.warns(UserWarning):
        prog = trace_prog(step, (jnp.zeros((4, 8)), jnp.ones((3,))), c)
    found = check_tpu101(prog)
    assert [f.rule for f in found] == ["TPU101"]
    assert "donation was dropped" in found[0].message


def test_tpu101_negative_pinned_alias():
    def step(pool, tok):
        return tok.sum(), pool + 1.0

    c = _contract(donate_argnums=(0,))
    prog = trace_prog(step, (jnp.zeros((4, 8)), jnp.ones((3,))), c)
    assert prog.lowered_text.count("tf.aliasing_output") == 1
    assert check_tpu101(prog) == []


def test_tpu101_skipped_without_declared_donation():
    def step(pool):
        return pool * 2.0

    prog = trace_prog(step, (jnp.zeros((4,)),), _contract())
    assert check_tpu101(prog) == []


# -- TPU102 baked-large-constant ----------------------------------------

def test_tpu102_positive_closure_captured_weight():
    baked = jnp.asarray(np.ones((64, 64), np.float32))   # 16 KiB

    def step(x):
        return x @ baked

    prog = trace_prog(step, (jnp.ones((2, 64)),),
                      _contract(max_const_bytes=4096))
    found = check_tpu102(prog)
    assert [f.rule for f in found] == ["TPU102"]
    assert "16384 bytes" in found[0].message


def test_tpu102_negative_weight_as_argument():
    def step(x, w):
        return x @ w

    prog = trace_prog(
        step, (jnp.ones((2, 64)), jnp.ones((64, 64))),
        _contract(max_const_bytes=4096))
    assert check_tpu102(prog) == []


# -- TPU103 accumulation-dtype ------------------------------------------

def test_tpu103_positive_bf16_accumulation():
    def step(a, b):
        # jnp.sum auto-upcasts bf16 computation, so the genuine
        # narrow-accumulation hazard is raw lax usage: this reduce
        # specializes to a bf16 reduce_sum
        return a @ b, jax.lax.reduce(b, np.array(0, "bfloat16"),
                                     jax.lax.add, (0, 1))

    prog = trace_prog(
        step, (jnp.ones((4, 8), jnp.bfloat16),
               jnp.ones((8, 4), jnp.bfloat16)), _contract())
    rules = sorted(f.message.split(" ")[0] for f in check_tpu103(prog))
    assert rules == ["dot_general", "reduce_sum"]


def test_tpu103_negative_fp32_accumulation():
    def step(a, b):
        d = jnp.einsum("ij,jk->ik", a, b,
                       preferred_element_type=jnp.float32)
        return d, jnp.sum(b, dtype=jnp.float32)

    prog = trace_prog(
        step, (jnp.ones((4, 8), jnp.bfloat16),
               jnp.ones((8, 4), jnp.bfloat16)), _contract())
    assert check_tpu103(prog) == []


def test_tpu103_fp32_operands_never_flagged():
    def step(a, b):
        return a @ b

    prog = trace_prog(step, (jnp.ones((4, 8)), jnp.ones((8, 4))),
                      _contract())
    assert check_tpu103(prog) == []


def _jaxpr_prog(fn, args, contract=None):
    """TracedProgram from make_jaxpr alone (no lowering) — for
    fixtures whose exotic dtype combinations the CPU backend need not
    compile; TPU103 reads only the jaxpr."""
    return TracedProgram(
        contract=contract or _contract(), config="fixture", mp=1,
        num_layers=1, jaxpr=jax.make_jaxpr(fn)(*args),
        lowered_text="", donated_leaves=0)


def _int8_dot(a, b, accum):
    return jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                               preferred_element_type=accum)


def test_tpu103_int8_positive_narrow_accumulation():
    """The quantized-serving contract (ISSUE 11): an int8 dot_general
    accumulating in bf16 — or staying int8 — fires; quantization
    already spent the narrow bits once, the accumulator must not
    spend them again."""
    a = jnp.ones((4, 8), jnp.int8)
    b = jnp.ones((8, 4), jnp.int8)
    found = check_tpu103(_jaxpr_prog(
        lambda x, y: _int8_dot(x, y, jnp.bfloat16), (a, b)))
    assert [f.rule for f in found] == ["TPU103"]
    assert "int8/int8" in found[0].message \
        and "bfloat16" in found[0].message
    found = check_tpu103(_jaxpr_prog(
        lambda x, y: _int8_dot(x, y, None), (a, b)))  # stays int8
    assert [f.rule for f in found] == ["TPU103"]


def test_tpu103_int8_negative_wide_accumulation():
    """int8 operands accumulating in fp32 (the engine's dequantized
    matmuls' pinned policy) or exact int32 pass."""
    a = jnp.ones((4, 8), jnp.int8)
    b = jnp.ones((8, 4), jnp.int8)
    for accum in (jnp.float32, jnp.int32):
        prog = _jaxpr_prog(lambda x, y: _int8_dot(x, y, accum), (a, b))
        assert check_tpu103(prog) == [], accum
    # int32 token ids are NOT narrow — reductions over them are fine
    ids = jnp.ones((16,), jnp.int32)
    assert check_tpu103(_jaxpr_prog(lambda x: jnp.sum(x), (ids,))) \
        == []


# -- TPU104 collective-budget -------------------------------------------

def _gather_fn(n_gathers):
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    def body(x):
        for _ in range(n_gathers):
            x = jax.lax.all_gather(x, "mp", axis=0,
                                   tiled=True).reshape(2, -1)[0]
        return x

    return shard_map(body, mesh=_mesh(), in_specs=(P("mp"),),
                     out_specs=P("mp"), check_rep=False)


def test_tpu104_positive_budget_exceeded():
    c = _contract(collective_budget=CollectiveBudget(
        fixed=(("all_gather", 1),)))
    prog = trace_prog(_gather_fn(2), (jnp.ones((4,)),), c, mp=2)
    found = check_tpu104(prog)
    assert [f.rule for f in found] == ["TPU104"]
    assert "all_gather appears 2x" in found[0].message \
        and "allowed 1" in found[0].message


def test_tpu104_negative_within_budget():
    c = _contract(collective_budget=CollectiveBudget(
        fixed=(("all_gather", 1),)))
    prog = trace_prog(_gather_fn(1), (jnp.ones((4,)),), c, mp=2)
    assert check_tpu104(prog) == []


def test_tpu104_unsharded_step_allows_no_collectives():
    """At mp=1 the budget is zero regardless of the declaration."""
    c = _contract(collective_budget=CollectiveBudget(
        fixed=(("all_gather", 8),)))
    prog = trace_prog(_gather_fn(1), (jnp.ones((4,)),), c, mp=1)
    found = check_tpu104(prog)
    assert [f.rule for f in found] == ["TPU104"]
    assert "unsharded steps run no collectives" in found[0].message


def test_tpu104_per_layer_budget_scales_with_layers():
    c = _contract(collective_budget=CollectiveBudget(
        per_layer=(("all_gather", 1),)))
    prog = trace_prog(_gather_fn(3), (jnp.ones((4,)),), c, mp=2,
                      num_layers=3)
    assert check_tpu104(prog) == []
    prog.num_layers = 2
    assert len(check_tpu104(prog)) == 1


# -- TPU105 trace-key instability ---------------------------------------

def test_tpu105_positive_python_scalar_and_weak_leaf():
    def step(x, s):
        return x * s

    prog = trace_prog(step, (jnp.ones((4,)), 2.5), _contract())
    found = check_tpu105(prog)
    assert [f.rule for f in found] == ["TPU105"]
    assert "python float" in found[0].message
    # the weak-typed-array branch: a scalar laundered through
    # jnp.asarray keeps weak_type=True and must still fire
    weak = jnp.asarray(2.5)
    assert weak.aval.weak_type
    prog = trace_prog(step, (jnp.ones((4,)), weak), _contract())
    found = check_tpu105(prog)
    assert [f.rule for f in found] == ["TPU105"]
    assert "weak-typed leaf" in found[0].message


def test_tpu105_negative_strong_typed_args():
    def step(x, s):
        return x * s

    prog = trace_prog(
        step, (jnp.ones((4,)), jnp.float32(2.5)), _contract())
    assert check_tpu105(prog) == []


# -- TPU106 host-callback-in-compiled-step ------------------------------

def test_tpu106_positive_pure_callback():
    def step(x):
        return jax.pure_callback(
            lambda a: np.asarray(a) * 2,
            jax.ShapeDtypeStruct((4,), jnp.float32), x)

    prog = trace_prog(step, (jnp.ones((4,)),), _contract())
    found = check_tpu106(prog)
    assert [f.rule for f in found] == ["TPU106"]
    assert "pure_callback" in found[0].message


def test_tpu106_negative_pure_program():
    def step(x):
        return x * 2.0

    prog = trace_prog(step, (jnp.ones((4,)),), _contract())
    assert check_tpu106(prog) == []


def test_tpu106_contract_opt_in_allows_callbacks():
    def step(x):
        return jax.pure_callback(
            lambda a: np.asarray(a) * 2,
            jax.ShapeDtypeStruct((4,), jnp.float32), x)

    prog = trace_prog(step, (jnp.ones((4,)),),
                      _contract(allow_host_callbacks=True))
    assert check_tpu106(prog) == []


# -- waivers, IDs, drift snapshot ---------------------------------------

def test_contract_waiver_suppresses_with_justification():
    def step(a, b):
        return a @ b

    c = _contract(waive=(("TPU103", "fixture: proving waiver "
                          "plumbing, not a real accumulation"),))
    prog = trace_prog(
        step, (jnp.ones((4, 8), jnp.bfloat16),
               jnp.ones((8, 4), jnp.bfloat16)), c)
    found = check_program(prog)
    tpu103 = [f for f in found if f.rule == "TPU103"]
    assert tpu103 and all(f.suppressed for f in tpu103)


def test_contract_waiver_requires_justification():
    c = _contract(waive=(("TPU103", "   "),))
    with pytest.raises(ValueError, match="justification"):
        c.waived("TPU103")


def test_finding_ids_stable_across_reruns():
    def step(x, s):
        return x * s

    def one():
        prog = trace_prog(step, (jnp.ones((4,)), 2.5), _contract())
        return assign_ids(check_tpu105(prog))[0].id

    assert one() == one()


def test_snapshot_drift_and_stale_detection():
    def step(pool, x):
        return x.sum(), pool + 1.0

    c = _contract(donate_argnums=(0,))
    prog = trace_prog(step, (jnp.zeros((4, 8)), jnp.ones((3,))), c)
    base = snapshot_of([prog])
    drift, stale = compare_snapshot([prog], base)
    assert drift == [] and stale == []
    # any op-count change fails loudly
    mutated = {k: dict(v, ops=dict(v["ops"], add=99))
               for k, v in base.items()}
    drift, _ = compare_snapshot([prog], mutated)
    assert [f.rule for f in drift] == ["TPU100"]
    assert "drifted" in drift[0].message
    # a program missing from the baseline fails; a baseline entry no
    # current program matches is reported stale
    drift, stale = compare_snapshot([prog], {"ghost[cfg]": {}})
    assert [f.rule for f in drift] == ["TPU100"]
    assert "no TRACE_BASELINE.json entry" in drift[0].message
    assert stale == ["ghost[cfg]"]


def test_tpu100_drift_is_never_grandfatherable():
    """A TPU100 finding's stable ID hashes the program key, not the
    drift content — so a findings-baseline entry for it would mask
    every FUTURE drift of that program too. The baseline application
    must refuse to honor such an entry (it surfaces as stale), and
    the drift finding stays live."""
    from paddle_tpu.analysis.trace import (TraceResult,
                                           apply_findings_baseline)

    def step(pool, x):
        return x.sum(), pool + 1.0

    c = _contract(donate_argnums=(0,))
    prog = trace_prog(step, (jnp.zeros((4, 8)), jnp.ones((3,))), c)
    base_snap = snapshot_of([prog])
    mutated = {k: dict(v, const_bytes=v["const_bytes"] + 1)
               for k, v in base_snap.items()}
    drift, _ = compare_snapshot([prog], mutated)
    res = TraceResult()
    res.findings = assign_ids(drift + check_tpu105(
        trace_prog(step, (jnp.zeros((4, 8)), 1.0), c)))
    fake_baseline = {f.id: {"id": f.id, "justification": "x" * 20}
                     for f in res.findings}
    stale = apply_findings_baseline(res, fake_baseline)
    tpu100 = [f for f in res.findings if f.rule == "TPU100"]
    tpu105 = [f for f in res.findings if f.rule == "TPU105"]
    assert tpu100 and not any(f.baselined for f in tpu100)
    assert tpu105 and all(f.baselined for f in tpu105)
    assert [i for i in stale] == [f.id for f in tpu100]
    assert tpu100[0] in res.new_findings()


def test_trace_import_has_no_backend_init():
    """ISSUE satellite: importing analysis.trace (and the contract-
    declaring builder modules) must not initialize a JAX backend —
    only invoking harvest may."""
    code = (
        "import paddle_tpu.analysis.trace as T\n"
        "import paddle_tpu.inference.engine\n"
        "import paddle_tpu.ops.paged_attention\n"
        "from jax._src import xla_bridge\n"
        "assert not xla_bridge._backends, 'import initialized a backend'\n"
        "assert len(T.registered_contracts()) == 5\n"
        "assert len(T.all_trace_rule_ids()) == 7\n"
        "print('TRACE_SMOKE_OK')\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    res = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "TRACE_SMOKE_OK" in res.stdout
