"""PS tail (VERDICT r4 next #9/#10): host-side GraphTable analog of
common_graph_table.h, and DeepFM over the same DistributedEmbedding
tables as WideDeep.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed.ps import GraphTable
from paddle_tpu.models import DeepFM


def _toy_graph(nshards=2):
    g = GraphTable(nshards=nshards)
    # 0 -> {1 (w3), 2 (w1)}; 1 -> {2}; 3 isolated
    g.add_edges([0, 0, 1], [1, 2, 2], weights=[3.0, 1.0, 1.0])
    g.add_graph_node([3])
    return g


def test_graph_table_build_and_stats():
    g = _toy_graph()
    st = g.stats()
    assert st["nodes"] == 4 and st["edges"] == 3 and st["nshards"] == 2
    np.testing.assert_array_equal(g.node_ids(), [0, 1, 2, 3])
    np.testing.assert_array_equal(g.pull_graph_list(1, 2), [1, 2])


def test_graph_table_neighbor_sampling_weighted():
    g = _toy_graph()
    nbrs, w = g.random_sample_neighbors([0, 1, 3], sample_size=200,
                                        seed=0, need_weight=True)
    assert nbrs.shape == (3, 200)
    # node 0: neighbor 1 carries weight 3 vs 1 -> sampled ~3x as often
    counts = {v: int((nbrs[0] == v).sum()) for v in (1, 2)}
    assert counts[1] + counts[2] == 200
    assert 0.55 < counts[1] / 200 < 0.92
    assert set(np.unique(nbrs[1])) == {2}       # only neighbor
    assert set(np.unique(nbrs[2])) == {-1}      # isolated pads with -1
    assert float(w[2].sum()) == 0.0
    # determinism under the same seed
    again = g.random_sample_neighbors([0, 1, 3], 200, seed=0)
    np.testing.assert_array_equal(nbrs, again)


def test_graph_table_node_feats_roundtrip():
    g = _toy_graph()
    g.set_node_feat([0, 2], "h", np.array([[1.0, 2.0], [3.0, 4.0]]))
    got = g.get_node_feat([0, 1, 2], "h")
    np.testing.assert_allclose(got, [[1, 2], [0, 0], [3, 4]])
    sampled = g.random_sample_nodes(50, seed=1)
    assert sampled.shape == (50,)
    assert set(np.unique(sampled)) <= {0, 1, 2, 3}


def test_deepfm_trains_locally():
    """Same CTR task as test_ps.py::test_wide_deep_trains, on DeepFM:
    the FM term + deep MLP learn the parity-of-field-0 rule."""
    paddle.seed(0)
    model = DeepFM(4, embedding_dim=8, hidden=(32,))
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=model.parameters())
    rs = np.random.RandomState(0)
    ids_np = rs.randint(0, 1000, size=(256, 4)).astype(np.int64)
    y_np = (ids_np[:, :1] % 2 == 0).astype(np.float32)
    ids, y = paddle.to_tensor(ids_np), paddle.to_tensor(y_np)
    losses = []
    for _ in range(40):
        p = model(ids)
        loss = F.binary_cross_entropy(p, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        model.push_sparse()
        losses.append(float(loss))
    assert losses[-1] < 0.45 < losses[0]
    acc = ((model(ids).numpy() > 0.5) == (y_np > 0.5)).mean()
    assert acc > 0.9


def test_graph_table_feat_width_contract_and_validation():
    g = GraphTable(nshards=2)
    g.set_node_feat([0], "h", np.array([[1.0, 2.0]]))
    # shape is call-order independent (fixed at first set)
    assert g.get_node_feat([5], "h").shape == (1, 2)
    with pytest.raises(ValueError, match="fixed at shape"):
        g.set_node_feat([1], "h", np.array([[1.0, 2.0, 3.0]]))
    with pytest.raises(ValueError, match="weights length"):
        g.add_edges([0, 0], [1, 2], weights=[3.0])
    # node_ids cache invalidates on mutation
    g.add_edges([7], [8])
    ids1 = g.node_ids()
    g.add_graph_node([9])
    assert 9 in g.node_ids() and 9 not in ids1
