"""Multi-tenant adapter serving: paged batched-LoRA (ISSUE 13).

The contract, proven the way PR 8/11/12 proved theirs:

- MIXED-TENANT EXACTNESS: a multi-adapter trace served on ONE engine
  is token-identical, per request, to serving each request on a
  dedicated engine that only ever sees that adapter — across both
  attention backends and with speculation on (the verify window
  scores under the adapted model). No cross-slot adapter leakage, by
  assertion rather than by construction.
- NULL PATH: adapter id 0 is bit-identical to a pre-adapter engine
  across {dense,pallas} x {chunked,bucketed} x K in {0,4} x
  mp in {1,2} (tier-1 runs a 4-cell cut; the full 16-cell product is
  slow-marked), and `decode_traces == 1` per config regardless of how
  many adapters are live.
- PAGING: the adapter pool's refcount/LRU/stall-and-retry story
  mirrors the paged KV cache — eviction under pressure never changes
  tokens, `drain()` audits adapter-page refcounts as loudly as KV
  blocks, and the prefix-cache chain hash is adapter-salted so one
  tenant's KV can never alias another's.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.adapters import (AdapterRegistry, PagedAdapterPool,
                                 adapter_pool_spec)
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.inference import GenerationEngine, prefix_key

VOCAB = 64          # divisible by mp in {2, 4}


def _model(seed=0):
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(seed)
    cfg = GPTConfig.tiny(vocab=VOCAB, hidden=32, layers=2, heads=4,
                         seq=64)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return _model()


def _registry(cfg, max_rank=4, ranks=(2, 3), seed=7, scale=0.3,
              group=None):
    """A registry with len(ranks) strong adapters (ids 1..) — factors
    big enough that every adapter visibly changes greedy streams.
    `group` registers them all as ONE rank group (a tenant shipping
    quality/latency variants that share a single page budget)."""
    rng = np.random.RandomState(seed)
    reg = AdapterRegistry(cfg, max_rank=max_rank)
    H, I, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    for aid, r in enumerate(ranks, start=1):
        w = {}
        for site, (i_d, o_d) in (("qkv", (H, 3 * H)), ("out", (H, H)),
                                 ("fc1", (H, I)), ("fc2", (I, H))):
            w[site] = [(rng.randn(r, i_d).astype(np.float32) * scale,
                        rng.randn(o_d, r).astype(np.float32) * scale)
                       for _ in range(L)]
        reg.register(aid, w, scaling=0.5, group=group)
    return reg


@pytest.fixture(scope="module")
def registry(model):
    return _registry(model.config)


def _mixed_trace(rng, adapters=(0, 1, 2), n_per=2):
    """Mixed lengths + a hot base prompt shared ACROSS adapters (the
    aliasing hazard the salt exists for): [(prompt, max_new, aid)]."""
    shared = rng.randint(0, VOCAB, 8).astype(np.int32)
    reqs = []
    for aid in adapters:
        for _ in range(n_per):
            reqs.append((rng.randint(0, VOCAB, rng.randint(2, 13))
                         .astype(np.int32), int(rng.randint(2, 6)),
                         aid))
        reqs.append((np.concatenate(
            [shared, rng.randint(0, VOCAB, 3)]).astype(np.int32), 4,
            aid))
        reqs.append((shared.copy(), 4, aid))
    return reqs


def _serve(eng, reqs, midrun=True):
    ids = [eng.add_request(p, n, adapter_id=a)
           for p, n, a in reqs[:len(reqs) // 2]]
    if midrun:
        for _ in range(2):
            eng.step()                 # admissions land mid-decode
    ids += [eng.add_request(p, n, adapter_id=a)
            for p, n, a in reqs[len(reqs) // 2:]]
    out = eng.run()
    return [list(map(int, out[rid])) for rid in ids]


# ---------------------------------------------------------------------------
# tentpole: mixed-tenant exactness vs dedicated engines
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("backend,K", [("dense", 0), ("pallas", 4)])
def test_mixed_tenants_token_identical_to_dedicated(model, registry,
                                                    monkeypatch,
                                                    backend, K):
    """THE acceptance gate: one engine serving three tenants (base +
    two adapters) interleaved, with mid-run admissions, emits per
    request exactly the tokens a dedicated single-tenant engine
    would — both backends, speculation on for one of them, ONE decode
    trace regardless of tenant mix."""
    monkeypatch.delenv("PADDLE_SPEC_DECODE_K", raising=False)
    monkeypatch.delenv("PADDLE_PAGED_ATTENTION_BACKEND", raising=False)
    rng = np.random.RandomState(11)
    reqs = _mixed_trace(rng)

    def mk():
        return GenerationEngine(model, num_slots=3, block_size=4,
                                num_blocks=64, prefill_chunk=8,
                                spec_decode_k=K,
                                attention_backend=backend,
                                adapters=registry)

    eng = mk()
    mixed = _serve(eng, reqs)
    assert eng.decode_traces == 1, \
        f"{backend} K={K}: decode retraced on a tenant mix"
    for aid in (0, 1, 2):
        mine = [(i, r) for i, r in enumerate(reqs) if r[2] == aid]
        ded = mk()
        got = _serve(ded, [r for _, r in mine], midrun=False)
        assert ded.decode_traces == 1
        for (i, _), toks in zip(mine, got):
            assert toks == mixed[i], \
                (f"{backend} K={K}: adapter {aid} request {i} "
                 "diverged between mixed and dedicated serving")


def test_adapters_actually_change_tokens(model, registry):
    """Effectiveness sanity: a strong adapter's greedy stream differs
    from the base model's AND from another adapter's for the same
    prompt (otherwise every parity assert above is vacuous)."""
    p = np.arange(1, 9, dtype=np.int32)
    eng = GenerationEngine(model, num_slots=3, block_size=4,
                           prefill_chunk=8, adapters=registry)
    ids = [eng.add_request(p, 6, adapter_id=a) for a in (0, 1, 2)]
    out = eng.run()
    assert out[ids[0]] != out[ids[1]]
    assert out[ids[0]] != out[ids[2]]
    assert out[ids[1]] != out[ids[2]]
    # and the base lane matches the no-adapter oracle exactly
    ref = model.generate(
        Tensor._wrap(p[None]), max_length=len(p) + 6, use_cache=True)
    assert out[ids[0]] == list(map(int, np.asarray(ref._array)[0]))


# ---------------------------------------------------------------------------
# null path: adapter id 0 bit-identical to the pre-adapter engine
# ---------------------------------------------------------------------------

_CELLS = [(b, pm, K, mp) for b in ("dense", "pallas")
          for pm in ("chunked", "bucketed") for K in (0, 4)
          for mp in (1, 2)]
_T1_CELLS = [("dense", "chunked", 0, 1), ("pallas", "bucketed", 4, 2),
             ("dense", "bucketed", 4, 1), ("pallas", "chunked", 0, 2)]


def _assert_null_cell(model, registry, backend, pmode, K, mp):
    rng = np.random.RandomState(5)
    reqs = [(p, n, 0) for p, n, _ in _mixed_trace(rng, adapters=(0,),
                                                  n_per=3)]

    def mk(adapters):
        kw = dict(prefill_chunk=8) if pmode == "chunked" \
            else dict(prefill_buckets=(16, 64))
        return GenerationEngine(model, num_slots=2, block_size=4,
                                num_blocks=64, spec_decode_k=K,
                                attention_backend=backend,
                                mp_degree=mp, adapters=adapters, **kw)

    plain = mk(None)
    ref = _serve(plain, reqs)
    lora = mk(registry)
    assert _serve(lora, reqs) == ref, \
        (f"{backend}/{pmode}/K={K}/mp={mp}: adapter id 0 diverged "
         "from the pre-adapter engine")
    assert plain.decode_traces == lora.decode_traces == 1


@pytest.mark.parametrize("backend,pmode,K,mp", _T1_CELLS)
def test_null_adapter_bit_identical(model, registry, monkeypatch,
                                    backend, pmode, K, mp):
    """Adapter id 0 through a LoRA-enabled engine emits exactly the
    pre-adapter engine's tokens (tier-1 cut of the 16-cell matrix)."""
    monkeypatch.delenv("PADDLE_SERVE_MP", raising=False)
    monkeypatch.delenv("PADDLE_SPEC_DECODE_K", raising=False)
    monkeypatch.delenv("PADDLE_PAGED_ATTENTION_BACKEND", raising=False)
    _assert_null_cell(model, registry, backend, pmode, K, mp)


@pytest.mark.slow
@pytest.mark.parametrize("backend,pmode,K,mp",
                         [c for c in _CELLS if c not in _T1_CELLS])
def test_null_adapter_bit_identical_full_matrix(model, registry,
                                                monkeypatch, backend,
                                                pmode, K, mp):
    """The remaining cells of the null-path matrix (identical
    machinery, outside the timed tier-1 window)."""
    monkeypatch.delenv("PADDLE_SERVE_MP", raising=False)
    monkeypatch.delenv("PADDLE_SPEC_DECODE_K", raising=False)
    monkeypatch.delenv("PADDLE_PAGED_ATTENTION_BACKEND", raising=False)
    _assert_null_cell(model, registry, backend, pmode, K, mp)


def test_mp2_and_int8_weights_compose(model, registry, monkeypatch):
    """Adapters under the sharded engine (column-parallel B pages) are
    token-identical to mp=1, and int8 BASE weights compose with fp
    adapters (mixed == dedicated under the same quantized config)."""
    monkeypatch.delenv("PADDLE_SERVE_MP", raising=False)
    monkeypatch.delenv("PADDLE_SERVE_WEIGHT_DTYPE", raising=False)
    rng = np.random.RandomState(3)
    reqs = _mixed_trace(rng, n_per=1)

    def serve(**kw):
        eng = GenerationEngine(model, num_slots=2, block_size=4,
                               num_blocks=64, prefill_chunk=8,
                               adapters=registry, **kw)
        out = _serve(eng, reqs, midrun=False)
        assert eng.decode_traces == 1
        return out

    assert serve(mp_degree=2) == serve()
    q_mixed = serve(weight_dtype="int8")
    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           num_blocks=64, prefill_chunk=8,
                           adapters=registry, weight_dtype="int8")
    only1 = [(i, r) for i, r in enumerate(reqs) if r[2] == 1]
    got = _serve(eng, [r for _, r in only1], midrun=False)
    for (i, _), toks in zip(only1, got):
        assert toks == q_mixed[i]


# ---------------------------------------------------------------------------
# prefix-cache adapter salting
# ---------------------------------------------------------------------------

def test_prefix_chain_is_adapter_salted(model, registry):
    """The same base prompt under two adapters must never share KV:
    the salted chains are disjoint per tenant, id-0 keys are exactly
    the unsalted ones, and a warm hit only ever lands same-tenant."""
    p = np.arange(12, dtype=np.int32)
    assert prefix_key(p, 4, 0) == prefix_key(p, 4)
    assert prefix_key(p, 4, 1) != prefix_key(p, 4)
    assert prefix_key(p, 4, 1) != prefix_key(p, 4, 2)
    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           num_blocks=64, prefill_chunk=8,
                           adapters=registry)
    rid = eng.add_request(p, 3, adapter_id=1)
    warm1 = eng.run()[rid]
    # the published chain is adapter-1's: visible under its salt only
    c = eng.cache
    assert c.warm_prefix_tokens(p, adapter_id=1) == len(p)
    assert c.warm_prefix_tokens(p, adapter_id=2) == 0
    assert c.warm_prefix_tokens(p, adapter_id=0) == 0
    # router keys ARE cache keys: the prefix_key digests peek the
    # same depth the cache would serve
    assert c.warm_prefix_tokens(p, keys=prefix_key(p, 4, 1)) == len(p)
    # a warm re-serve under adapter 1 HITS (tokens unchanged); the
    # same prompt under adapter 2 misses and computes its own KV
    hit0 = eng.prefix_hit_tokens
    rid = eng.add_request(p, 3, adapter_id=1)
    assert eng.run()[rid] == warm1
    assert eng.prefix_hit_tokens > hit0
    hit1 = eng.prefix_hit_tokens
    rid = eng.add_request(p, 3, adapter_id=2)
    out2 = eng.run()[rid]
    assert eng.prefix_hit_tokens == hit1      # no cross-tenant hit
    assert out2 != warm1
    # dedicated-engine oracle for the adapter-2 stream
    ded = GenerationEngine(model, num_slots=2, block_size=4,
                           num_blocks=64, prefill_chunk=8,
                           adapters=registry)
    rid = ded.add_request(p, 3, adapter_id=2)
    assert ded.run()[rid] == out2


# ---------------------------------------------------------------------------
# paging: eviction under pressure, stall/retry, drain audit
# ---------------------------------------------------------------------------

def test_adapter_pool_eviction_never_changes_tokens(model, registry,
                                                    monkeypatch):
    """A 2-page pool (null + ONE tenant page) serving two adapters
    must swap/evict continuously — admissions stall-and-retry on
    page pressure — and still emit exactly the big-pool tokens."""
    rng = np.random.RandomState(9)
    reqs = _mixed_trace(rng, adapters=(1, 2), n_per=2)

    def serve(pages):
        eng = GenerationEngine(model, num_slots=2, block_size=4,
                               num_blocks=64, prefill_chunk=8,
                               adapters=registry,
                               adapter_pool_pages=pages)
        out = _serve(eng, reqs, midrun=False)
        eng.drain()                      # page accounting must close
        return out, eng

    big, _ = serve(pages=3)              # both tenants resident
    small, eng = serve(pages=2)          # one page: thrash
    assert small == big
    pool = eng.adapter_pool
    assert pool.evictions > 0 and pool.swapins > pool.evictions
    snap = eng.metrics_snapshot()
    stalls = [s for s in snap["engine_block_stalls_total"]["series"]
              if s["labels"]["path"] == "adapter"]
    assert stalls and stalls[0]["value"] > 0
    assert pool.leak_check() == []


def test_drain_audits_adapter_pages(model, registry):
    """A leaked adapter-page reference fails drain() as loudly as a
    leaked KV block."""
    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           prefill_chunk=8, adapters=registry)
    rid = eng.add_request(np.arange(5, dtype=np.int32), 3,
                          adapter_id=1)
    eng.run()
    eng2 = GenerationEngine(model, num_slots=2, block_size=4,
                            prefill_chunk=8, adapters=registry)
    eng2.drain()                         # clean engine drains clean
    eng.adapter_pool.acquire(2)          # simulate a dropped release
    with pytest.raises(RuntimeError, match="adapter page"):
        eng.drain()


def test_prebuilt_pool_is_single_engine(model, registry):
    """Paging state is per-engine: a prebuilt pool adopted by one
    engine refuses a second (interleaved refcounts would make one
    replica's drain audit see another's live references); the
    REGISTRY is the safely-shared object."""
    pool = PagedAdapterPool(registry, num_pages=3)
    e1 = GenerationEngine(model, num_slots=1, block_size=4,
                          prefill_chunk=8, adapters=pool)
    assert e1.adapter_pool is pool
    with pytest.raises(ValueError, match="another"):
        GenerationEngine(model, num_slots=1, block_size=4,
                         prefill_chunk=8, adapters=pool)
    # one registry, two engines, two pools: fine
    e2 = GenerationEngine(model, num_slots=1, block_size=4,
                          prefill_chunk=8, adapters=registry)
    assert e2.adapter_pool is not pool


def test_pool_release_and_over_release_harden(model, registry):
    pool = PagedAdapterPool(registry, num_pages=3)
    page = pool.acquire(1)
    assert page != 0 and pool.page_of(1) == page
    assert pool.acquire(1) == page       # refcount 2, same page
    pool.release(1)
    pool.release(1)
    assert pool.leak_check() == []
    with pytest.raises(RuntimeError, match="release"):
        pool.release(1)
    # the null adapter is never paged
    assert pool.acquire(0) == 0 and pool.page_of(0) == 0
    pool.release(0)                      # no-op, never raises


# ---------------------------------------------------------------------------
# rank groups: one tenant at several ranks, ONE page budget (ISSUE 18
# — the grouped multi-rank tail of the PR 13 paged-pool design)
# ---------------------------------------------------------------------------

def test_rank_group_shares_one_page_budget(model):
    """Three rank variants of one tenant in a pool with room for all
    of them: switching variants must REUSE the group's single page in
    place (eviction + swap-in), a referenced sibling must stall the
    acquire, and the free pages must never be touched by the group."""
    reg = _registry(model.config, ranks=(2, 3, 4), group="tenantA")
    pool = PagedAdapterPool(reg, num_pages=4)    # null + 3 usable
    assert reg.group_of(1) == "tenantA"
    assert reg.group_ids("tenantA") == [1, 2, 3]
    page = pool.acquire(1)
    assert page != 0
    # sibling referenced by a live lane: variant switch stalls — and
    # the placement probe agrees BEFORE the acquire is attempted
    assert not pool.can_acquire(2)
    assert pool.acquire(2) is None
    assert pool.can_acquire(1)                   # resident variant: hit
    pool.release(1)
    # idle sibling: the variant lands on THE group page, in place
    assert pool.can_acquire(2)
    evictions = pool.evictions
    assert pool.acquire(2) == page
    assert pool.evictions == evictions + 1
    assert pool.page_of(1) is None and pool.page_of(2) == page
    pool.release(2)
    # prefetch honors the shared budget too: warms in place, takes no
    # reference, never grabs a second page
    assert pool.prefetch(3) == page
    assert pool.page_of(3) == page and pool.page_of(2) is None
    # ONE materialized page ever; the other two stayed truly free
    assert pool.num_resident == 1 and len(pool._free) == 2
    assert pool.leak_check() == []


def test_rank_group_leak_audit_flags_second_page(model, monkeypatch):
    """The audit half of the budget: if an acquire path ever lets a
    rank group spread over two pages (simulated here by disabling the
    sibling lookup), `leak_check` must flag it even though every page
    is properly released — the PR 13 refcount audit cannot see this
    class."""
    reg = _registry(model.config, ranks=(2, 3), group="tenantA")
    pool = PagedAdapterPool(reg, num_pages=3)
    monkeypatch.setattr(pool, "_group_sibling_page", lambda aid: None)
    pool.acquire(1)
    pool.acquire(2)
    pool.release(1)
    pool.release(2)
    leaked = pool.leak_check()
    assert leaked, "a rank group holding two pages passed the audit"


@pytest.mark.slow
def test_rank_group_serving_token_identical_under_shared_budget(model):
    """End to end through the admission path: two rank variants of
    one tenant interleaved across lanes. The shared budget turns
    concurrent variants into stall/retry admissions (the KV
    allocator's contract), pages swap in place — and the tokens are
    exactly the ungrouped registry's: grouping is paging policy, not
    numerics."""
    rng = np.random.RandomState(9)
    reqs = _mixed_trace(rng, adapters=(1, 2), n_per=2)

    def serve(group):
        reg = _registry(model.config, group=group)
        eng = GenerationEngine(model, num_slots=2, block_size=4,
                               num_blocks=64, prefill_chunk=8,
                               adapters=reg, adapter_pool_pages=4)
        out = _serve(eng, reqs, midrun=False)
        eng.drain()                      # group audit runs here too
        return out, eng

    plain, _ = serve(None)
    grouped, eng = serve("tenantA")
    assert grouped == plain
    pool = eng.adapter_pool
    assert pool.evictions > 0, "variants never swapped in place"
    snap = eng.metrics_snapshot()
    stalls = [s for s in snap["engine_block_stalls_total"]["series"]
              if s["labels"]["path"] == "adapter"]
    assert stalls and stalls[0]["value"] > 0, \
        "concurrent variants never contended for the shared page"
    assert pool.leak_check() == []


# ---------------------------------------------------------------------------
# registry validation + layout truth
# ---------------------------------------------------------------------------

def test_registry_validation(model):
    cfg = model.config
    reg = AdapterRegistry(cfg, max_rank=2)
    H = cfg.hidden_size
    ok = {"out": [(np.zeros((2, H), np.float32),
                   np.zeros((H, 2), np.float32))] * cfg.num_layers}
    with pytest.raises(ValueError, match="reserved"):
        reg.register(0, ok)
    with pytest.raises(ValueError, match="max_rank"):
        reg.register(1, {"out": [(np.zeros((3, H), np.float32),
                                  np.zeros((H, 3), np.float32))]
                         * cfg.num_layers})
    with pytest.raises(ValueError, match="want A"):
        reg.register(1, {"out": [(np.zeros((2, H + 1), np.float32),
                                  np.zeros((H, 2), np.float32))]
                         * cfg.num_layers})
    with pytest.raises(ValueError, match="unknown LoRA site"):
        reg.register(1, {"nope": ok["out"]})
    with pytest.raises(ValueError, match="per-layer"):
        reg.register(1, {"out": ok["out"][:1]})
    reg.register(1, ok)
    with pytest.raises(ValueError, match="already registered"):
        reg.register(1, ok)
    assert reg.has(1) and reg.has(0) and not reg.has(2)
    # engine-side intake validation
    eng = GenerationEngine(model, num_slots=1, block_size=4,
                           prefill_chunk=8)
    with pytest.raises(ValueError, match="adapters="):
        eng.add_request([1, 2, 3], 2, adapter_id=1)
    eng = GenerationEngine(model, num_slots=1, block_size=4,
                           prefill_chunk=8, adapters=reg)
    with pytest.raises(ValueError, match="not registered"):
        eng.add_request([1, 2, 3], 2, adapter_id=9)
    # a registry for a different geometry is rejected up front
    other = AdapterRegistry(
        type("C", (), {"num_layers": 1, "hidden_size": 32,
                       "intermediate_size": 128, "num_heads": 4})())
    with pytest.raises(ValueError, match="num_layers"):
        GenerationEngine(model, num_slots=1, block_size=4,
                         prefill_chunk=8, adapters=other)


def test_rank_padding_is_exact(model, registry):
    """A rank-2 adapter served from a max_rank=4 pool emits exactly
    the tokens the same adapter serves from a max_rank=2 pool: the
    padded rank rows are EXACT zeros, not noise."""
    cfg = model.config
    narrow = _registry(cfg, max_rank=2, ranks=(2,))
    wide = _registry(cfg, max_rank=4, ranks=(2,))
    p = np.arange(2, 9, dtype=np.int32)

    def serve(reg):
        eng = GenerationEngine(model, num_slots=1, block_size=4,
                               prefill_chunk=8, adapters=reg)
        rid = eng.add_request(p, 5, adapter_id=1)
        return eng.run()[rid]

    assert serve(narrow) == serve(wide)


def test_adapter_pool_spec_is_the_layout_truth(model, registry):
    """pool arrays, swap-in, and shard specs all derive from
    adapter_pool_spec — shapes match entry for entry, and the B pages
    (and only they) carry an mp shard axis."""
    pool = PagedAdapterPool(registry, num_pages=4)
    spec = pool.adapter_pool_spec()
    assert list(spec) == ["a_qkv", "b_qkv", "a_out", "b_out", "a_fc1",
                          "b_fc1", "a_fc2", "b_fc2", "scaling"]
    for arr, (shape, dt, _) in zip(pool.arrays(), spec.values()):
        assert tuple(arr.shape) == shape
    free = adapter_pool_spec(4, 2, 4, 32, 128, 4, np.float32)
    assert {k: v[0] for k, v in free.items()} \
        == {k: v[0] for k, v in spec.items()}
    assert [name for name, (_, _, ax) in spec.items()
            if ax is not None] == ["b_qkv", "b_out", "b_fc1", "b_fc2"]
    from paddle_tpu.distributed import serving_mesh

    sharded = PagedAdapterPool(registry, num_pages=4,
                               mesh=serving_mesh(2))
    specs = dict(zip(spec, sharded.pool_pspecs()))
    assert "mp" in specs["b_qkv"] and "mp" in specs["b_fc1"]
    assert specs["a_qkv"] == () and specs["scaling"] == ()


def test_lora_delta_matches_the_numpy_oracle(model):
    """The op-tier contract the engine parity tests CANNOT catch (a
    consistently-wrong layout would cancel between mixed and
    dedicated engines): the gathered delta equals the textbook
    `x . A^T . B^T * scaling` in the flat [3H]/[out] layout the user
    registered, null rows are exact zeros, and the head-major and
    3-major qkv orientations are transposes of one another."""
    from paddle_tpu.ops.lora import lora_linear_delta, lora_qkv_delta

    cfg = model.config
    H, L = cfg.hidden_size, cfg.num_layers
    rng = np.random.RandomState(0)
    A = rng.randn(2, H).astype(np.float32)
    Bq = rng.randn(3 * H, 2).astype(np.float32)
    Bo = rng.randn(H, 2).astype(np.float32)
    reg = AdapterRegistry(cfg, max_rank=4)
    reg.register(1, {"qkv": [(A, Bq)] * L, "out": [(A, Bo)] * L},
                 scaling=0.7)
    pool = PagedAdapterPool(reg, num_pages=3)
    page = pool.acquire(1)
    arrs = pool.arrays()
    x = rng.randn(3, 1, H).astype(np.float32)
    rows = np.asarray([page, 0, page], np.int32)
    want_q = (x[0, 0] @ A.T @ Bq.T) * 0.7          # flat [3H] oracle
    d = np.asarray(lora_qkv_delta(
        x, arrs[0], arrs[1], rows, arrs[8], 0,
        head_major=False)._array)                  # [B,S,3,heads,D]
    assert np.allclose(d[0, 0].reshape(3 * H), want_q, atol=1e-5)
    assert (d[1] == 0).all()                       # null page: exact 0
    dm = np.asarray(lora_qkv_delta(
        x, arrs[0], arrs[1], rows, arrs[8], 0,
        head_major=True)._array)                   # [B,S,heads,3,D]
    assert np.array_equal(dm[0, 0], d[0, 0].transpose(1, 0, 2))
    dl = np.asarray(lora_linear_delta(
        x, arrs[2], arrs[3], rows, arrs[8], 0)._array)
    assert np.allclose(dl[0, 0], (x[0, 0] @ A.T @ Bo.T) * 0.7,
                       atol=1e-5)
    assert (dl[1] == 0).all()


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------

def test_multitenant_lora_bench_runner_tiny(model, monkeypatch):
    """The gpt_engine_multitenant_lora SUITE_ROWS runner at test
    scale: mixed-pool engine vs the engine-per-tenant strawman,
    outputs asserted identical inside the runner, per-tenant latency
    series populated, swap-ins visible with a page-tight pool."""
    monkeypatch.delenv("PADDLE_SERVE_KV_DTYPE", raising=False)
    monkeypatch.delenv("PADDLE_SERVE_WEIGHT_DTYPE", raising=False)
    import bench_ops

    assert "gpt_engine_multitenant_lora" in bench_ops.suite_names()
    rec = bench_ops._engine_multitenant_lora_case(
        model_cfg=model.config, num_tenants=3, per_tenant=4, rank=2,
        max_rank=4, prefix_len=8, suffix_max=6, max_new=6,
        num_slots=2, block_size=4, prefill_chunk=8,
        adapter_pool_pages=3)()
    assert rec["tokens_per_s"] > 0
    assert rec["tokens_per_s_dedicated"] > 0
    assert rec["tenants"] == 3 and rec["requests"] == 7
    assert rec["adapter_swapins"] > 0
    assert rec["decode_recompiles"] == 0
    assert set(rec["ttft_ms_p99_by_tenant"]) == {"1", "2", "3"}


def test_adapter_labeled_metrics(model, registry):
    """Per-tenant TTFT/TPOT series + pool paging health; a plain
    engine's exposition carries NONE of the adapter families."""
    rng = np.random.RandomState(2)
    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           prefill_chunk=8, adapters=registry)
    for aid in (0, 1, 2):
        eng.add_request(rng.randint(0, VOCAB, 6).astype(np.int32), 3,
                        adapter_id=aid)
    eng.run()
    snap = eng.metrics_snapshot()
    ttft = {s["labels"]["adapter"]: s
            for s in snap["engine_adapter_ttft_seconds"]["series"]}
    assert set(ttft) == {"0", "1", "2"}
    assert all(s["count"] == 1 for s in ttft.values())
    tpot = {s["labels"]["adapter"]: s
            for s in snap["engine_adapter_tpot_seconds"]["series"]}
    assert set(tpot) == {"0", "1", "2"}
    assert snap["engine_adapter_pool_pages"]["series"][0]["value"] \
        == 1 + eng.num_slots
    assert snap["engine_adapter_pool_resident"]["series"][0][
        "value"] == 2
    assert snap["engine_adapter_swapins_total"]["series"][0][
        "value"] == 2
    assert snap["engine_adapter_pool_used_pages"]["series"][0][
        "value"] == 0                    # all lanes finished
    # the priority-labeled SLO series are untouched
    assert snap["engine_ttft_seconds"]["series"][0]["count"] == 3
    plain = GenerationEngine(model, num_slots=2, block_size=4,
                             prefill_chunk=8)
    assert "engine_adapter_ttft_seconds" not in plain.metrics_snapshot()


def test_alpha_with_mixed_ranks_is_rejected(model):
    """alpha=/rank is ambiguous when sites carry different ranks (one
    adapter-wide scaling cannot express per-module alpha/r) — require
    an explicit scaling instead of silently picking a rank."""
    cfg = model.config
    reg = AdapterRegistry(cfg, max_rank=8)
    H = cfg.hidden_size
    w = {"out": [(np.zeros((2, H), np.float32) + 1,
                  np.zeros((H, 2), np.float32) + 1)] * cfg.num_layers,
         "fc1": [(np.zeros((4, H), np.float32) + 1,
                  np.zeros((cfg.intermediate_size, 4),
                           np.float32) + 1)] * cfg.num_layers}
    with pytest.raises(ValueError, match="mixed ranks"):
        reg.register(1, w, alpha=16)
    reg.register(1, w, scaling=2.0)      # explicit scaling is fine
    assert reg.scaling_of(1) == 2.0


# ---------------------------------------------------------------------------
# live registration (ISSUE 17 satellite: the PR 13 operational tail)
# ---------------------------------------------------------------------------

def _adapter_weights(cfg, rank, seed, scale=0.3):
    """One adapter's weight dict, deterministic in `seed` — so two
    registries built on different schedules can hold bit-identical
    factors for the same adapter id."""
    rng = np.random.RandomState(seed)
    H, I, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    w = {}
    for site, (i_d, o_d) in (("qkv", (H, 3 * H)), ("out", (H, H)),
                             ("fc1", (H, I)), ("fc2", (I, H))):
        w[site] = [(rng.randn(rank, i_d).astype(np.float32) * scale,
                    rng.randn(o_d, rank).astype(np.float32) * scale)
                   for _ in range(L)]
    return w


def test_live_adapter_registration_token_identical(model):
    """Registering a NEW adapter on a registry already wired into a
    serving engine is legal (no construction-time freeze) and the
    late tenant's streams are token-identical to an engine whose
    registry carried it from the start — with tracing ON, the cold
    swap-in shows up as a labeled `adapter.swap_in` span and
    `decode_traces == 1` survives the tenant-set growth."""
    cfg = model.config
    w1 = _adapter_weights(cfg, 2, seed=21)
    w2 = _adapter_weights(cfg, 3, seed=22)

    def mk(reg, tracing=False):
        return GenerationEngine(model, num_slots=2, block_size=4,
                                num_blocks=64, prefill_chunk=8,
                                adapters=reg, tracing=tracing)

    rng = np.random.RandomState(3)
    reqs = [(rng.randint(0, VOCAB, rng.randint(3, 10))
             .astype(np.int32), int(rng.randint(2, 6)), aid)
            for aid in (0, 2, 1, 2) for _ in range(1)]

    # reference: adapter 2 present before the engine ever existed
    reg_ref = AdapterRegistry(cfg, max_rank=4)
    reg_ref.register(1, w1, scaling=0.5)
    reg_ref.register(2, w2, scaling=0.5)
    ref = _serve(mk(reg_ref), reqs, midrun=False)

    # live path: engine built with ONLY adapter 1; tenant 2 arrives
    # after construction — and after the engine has already served
    reg_live = AdapterRegistry(cfg, max_rank=4)
    reg_live.register(1, w1, scaling=0.5)
    eng = mk(reg_live, tracing=True)
    warm = [r for r in reqs if r[2] != 2]
    pre = _serve(eng, warm, midrun=False)
    assert pre == [t for t, r in zip(ref, reqs) if r[2] != 2]
    with pytest.raises(ValueError, match="is not registered"):
        eng.add_request(reqs[0][0], 2, adapter_id=2)
    reg_live.register(2, w2, scaling=0.5)          # live registration
    late = _serve(eng, reqs, midrun=False)
    assert late == ref
    assert eng.decode_traces == 1
    swaps = [e for e in eng.tracer.snapshot()
             if e["name"] == "adapter.swap_in"]
    assert any(e["args"]["adapter"] == 2 for e in swaps)
    # the live id is still guarded: re-registering it raises
    with pytest.raises(ValueError, match="already registered"):
        reg_live.register(2, w2, scaling=0.5)
