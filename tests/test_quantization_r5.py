"""Quantization depth (VERDICT r4 next #7): QATConv2D, per-channel
observers/quanters, and quantization.convert producing a jit.save-able
int8-simulated model. Reference: python/paddle/nn/quant/,
static/quantization pipeline.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import quantization as Q


def _res_block():
    """A ResNet basic-block shape: conv-bn-relu-conv-bn + skip."""
    paddle.seed(0)

    class Block(nn.Layer):
        def __init__(self):
            super().__init__()
            self.conv1 = nn.Conv2D(4, 4, 3, padding=1)
            self.bn1 = nn.BatchNorm2D(4)
            self.conv2 = nn.Conv2D(4, 4, 3, padding=1)
            self.bn2 = nn.BatchNorm2D(4)
            self.head = nn.Linear(4, 3)

        def forward(self, x):
            h = F.relu(self.bn1(self.conv1(x)))
            h = self.bn2(self.conv2(h)) + x
            return self.head(F.relu(h).mean(axis=[2, 3]))

    return Block()


def _x(seed=0, n=4):
    return paddle.to_tensor(
        np.random.RandomState(seed).randn(n, 4, 8, 8).astype(np.float32))


def test_per_channel_quantize_absmax():
    w = np.random.RandomState(1).randn(6, 3, 3, 3).astype(np.float32)
    q, s = Q.quantize_absmax(w, axis=0)
    assert q.dtype == np.int8 and s.shape == (6, 1, 1, 1)
    # each output channel uses ITS absmax
    for c in range(6):
        expect = np.abs(w[c]).max() / 127
        np.testing.assert_allclose(float(s[c, 0, 0, 0]), expect,
                                   rtol=1e-6)
    np.testing.assert_allclose(np.asarray(q, np.float32) * np.asarray(s),
                               w, atol=np.abs(w).max() / 127 + 1e-6)


def test_per_channel_observer_and_quanter():
    obs = Q.PerChannelAbsmaxObserver(channel_axis=0)
    w1 = paddle.to_tensor(np.array([[1.0, -2.0], [3.0, 0.5]], np.float32))
    w2 = paddle.to_tensor(np.array([[4.0, 0.1], [0.2, 0.3]], np.float32))
    obs(w1)
    obs(w2)
    np.testing.assert_allclose(obs.scale(), np.array([4.0, 3.0]) / 127,
                               rtol=1e-6)

    quanter = Q.FakeQuanterChannelWiseAbsMax(channel_axis=0)
    out = quanter(w1)
    # fake-quant keeps shape; values snap to the per-channel grid
    assert out.shape == [2, 2]
    s = quanter.scale()
    assert s.shape == (2,)
    grid = np.round(np.asarray(w1) / s[:, None]) * s[:, None]
    np.testing.assert_allclose(np.asarray(out), grid, rtol=1e-5)


def test_qat_resnet_block_accuracy_parity_and_training():
    block = _res_block()
    x = _x()
    ref = block(x).numpy()

    q = Q.QAT(Q.QuantConfig(
        activation=Q.FakeQuanterWithAbsMaxObserver(moving_rate=0.9),
        weight=Q.FakeQuanterChannelWiseAbsMax()))
    qblock = q.quantize(block)
    # conv AND linear layers got wrapped
    kinds = [type(l).__name__ for l in qblock.sublayers()]
    assert "QATConv2D" in kinds and "QATLinear" in kinds

    out = qblock(x).numpy()
    # int8 simulation error stays small (accuracy parity tolerance)
    assert np.abs(out - ref).max() < 0.12 * np.abs(ref).max() + 0.05

    # STE: training through the fake-quant graph moves the loss
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=qblock.parameters())
    losses = []
    for _ in range(5):
        loss = (qblock(x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_qat_convert_to_int8_and_save(tmp_path):
    import paddle_tpu.inference as infer
    import paddle_tpu.jit as jit
    from paddle_tpu.jit.api import InputSpec

    block = _res_block()
    block.eval()
    x = _x(seed=3)

    q = Q.QAT(Q.QuantConfig(
        activation=Q.FakeQuanterWithAbsMaxObserver(),
        weight=Q.FakeQuanterChannelWiseAbsMax()))
    qblock = q.quantize(block)
    for _ in range(3):  # calibrate the moving-average scales
        qblock(_x(seed=7))
    qat_out = qblock(x).numpy()

    converted = Q.convert(qblock)
    kinds = [type(l).__name__ for l in converted.sublayers()]
    assert "QuantedConv2D" in kinds and "QuantedLinear" in kinds
    for l in converted.sublayers():
        if isinstance(l, (Q.QuantedConv2D, Q.QuantedLinear)):
            assert str(l.qweight._array.dtype) == "int8"
    conv_out = converted(x).numpy()
    # converted int8 model tracks the QAT-simulated model
    assert np.abs(conv_out - qat_out).max() < \
        0.1 * np.abs(qat_out).max() + 0.05

    # the converted model jit.saves (int8 weights + scales as buffers)
    # and the loaded artifact reproduces it exactly
    path = str(tmp_path / "int8_block")
    jit.save(converted, path,
             input_spec=[InputSpec([4, 4, 8, 8], "float32")])
    pred = infer.create_predictor(infer.Config(path))
    (loaded_out,) = pred.run([np.asarray(x)])
    np.testing.assert_allclose(loaded_out, conv_out, rtol=1e-5,
                               atol=1e-5)


def test_ptq_conv_pipeline():
    block = _res_block()
    block.eval()
    ptq = Q.PTQ(Q.QuantConfig(activation=Q.AbsmaxObserver, weight=None))
    observed = ptq.quantize(block)
    for s in range(3):
        observed(_x(seed=s))
    ref = observed(_x(seed=9)).numpy()
    converted = ptq.convert(observed)
    kinds = [type(l).__name__ for l in converted.sublayers()]
    assert "QuantedConv2D" in kinds and "QuantedLinear" in kinds
    out = converted(_x(seed=9)).numpy()
    assert np.abs(out - ref).max() < 0.15 * np.abs(ref).max() + 0.08


def test_perchannel_activation_scale_survives_convert():
    """ADVICE r5 #6: a PerChannelAbsmaxObserver calibration converts to
    a VECTOR activation scale broadcast along the observer's
    channel_axis — not silently collapsed to one scalar."""
    paddle.seed(3)
    net = nn.Sequential(nn.Linear(4, 6))
    ptq = Q.PTQ(Q.QuantConfig(
        activation=Q.PerChannelAbsmaxObserver(channel_axis=1)))
    observed = ptq.quantize(net)
    # channels with very different ranges: per-channel grids differ
    x = np.ones((8, 4), np.float32)
    x[:, 0] *= 100.0
    x[:, 1] *= 0.01
    observed(paddle.to_tensor(x))
    converted = ptq.convert(observed)
    ql = converted[0]
    assert isinstance(ql, Q.QuantedLinear)
    assert np.ndim(ql.act_scale) == 1 and ql.act_scale.shape == (4,)
    assert ql.act_channel_axis == 1
    # the big channel keeps fidelity a shared scalar grid would lose:
    # channel 1 values (0.01) round to 0 on a 100-max absmax grid
    y = ql._quant_act(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(y[:, 1], 0.01, rtol=0.05)
    np.testing.assert_allclose(y[:, 0], 100.0, rtol=0.05)


def test_vector_scale_without_axis_warns_and_collapses():
    """A vector scale with no channel axis can't be placed — loud
    conservative collapse, not silent."""
    paddle.seed(4)
    lin = nn.Linear(4, 3)
    with pytest.warns(UserWarning, match="channel_axis"):
        ql = Q.QuantedLinear(lin, act_scale=np.array([1.0, 2.0, 4.0,
                                                      8.0]))
    assert ql.act_scale == 8.0                 # per-tensor max
    out = ql(paddle.to_tensor(np.ones((2, 4), np.float32)))
    assert tuple(out.shape) == (2, 3)
