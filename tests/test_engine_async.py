"""Async engine core (ISSUE 18): the dispatch-ahead decode pipeline.

The contract under test is brutal on purpose: the async core is a
SCHEDULING refactor, not a numerics change —

- token IDENTITY serial vs async across the whole serving matrix
  ({dense, pallas} x K in {0, 4} x mp in {1, 2} x kv in {fp, int8}),
  chunked cold + warm and legacy bucketed prefill, with mid-run
  admissions, saturation shedding, and adapter-pool evictions in the
  mix.  Sampled lanes hold too: the acceptance coin at each verify
  position is compared against p(draft token), so identical tokens
  REQUIRE identical drafts — the helper-thread proposals must equal
  the serial ones bit-for-bit (`_m_spec_ok/_m_spec_rej` equality is
  asserted as the direct witness).
- the pipeline DRAINS: an in-flight dispatched step outstanding when
  EOS lands / drain() is called completes on the step thread, and the
  block/adapter-page leak audits stay green.
- `decode_traces == 1` per config and steady-state `expect_traces(0)`
  — dispatch-ahead reuses the exact compiled programs.
- `PADDLE_SERVE_ASYNC` wins over the ctor arg; async off (the
  default) leaves the engine on the serial path with no in-flight
  machinery engaged.
- the flight recorder shows the pipeline actually pipelining:
  `async_dispatch(seq)` strictly precedes `async_complete(seq)` and
  completes interleave one-ahead, never deeper.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.jit as jit
from paddle_tpu.adapters import AdapterRegistry
from paddle_tpu.inference import GenerationEngine, ServingFleet
from paddle_tpu.inference import speculative
from paddle_tpu.inference.sampling import SamplingParams

VOCAB = 64


def _model(seed=0):
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(seed)
    cfg = GPTConfig.tiny(vocab=VOCAB, hidden=32, layers=2, heads=4,
                         seq=64)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return _model()


@pytest.fixture(autouse=True)
def _no_env_overrides(monkeypatch):
    for var in ("PADDLE_SERVE_ASYNC", "PADDLE_SPEC_DECODE_K",
                "PADDLE_PAGED_ATTENTION_BACKEND",
                "PADDLE_SERVE_KV_DTYPE", "PADDLE_SERVE_MP"):
        monkeypatch.delenv(var, raising=False)


def _trace(rng, n=4):
    """Mixed lengths + motif-tiled prompts (so the NgramDrafter
    actually matches and the accept walk sees non-empty windows) + a
    hot shared prefix."""
    motif = rng.randint(0, VOCAB, 3).astype(np.int32)
    reqs = [(rng.randint(0, VOCAB, rng.randint(2, 13)).astype(np.int32),
             int(rng.randint(2, 7))) for _ in range(n)]
    reqs += [(np.tile(motif, 5).astype(np.int32), 6),
             (np.tile(motif, 3).astype(np.int32), 8)]
    shared = rng.randint(0, VOCAB, 8).astype(np.int32)
    reqs += [(np.concatenate([shared, rng.randint(0, VOCAB, 3)])
              .astype(np.int32), 4),
             (shared.copy(), 4)]
    return reqs


def _run_trace(eng, reqs, midrun=True):
    ids = [eng.add_request(p, n) for p, n in reqs[:len(reqs) // 2]]
    if midrun:
        for _ in range(2):
            eng.step()                 # admissions land mid-pipeline
    ids += [eng.add_request(p, n) for p, n in reqs[len(reqs) // 2:]]
    out = eng.run()
    return [list(map(int, out[rid])) for rid in ids]


def _spec_counters(eng):
    return (int(eng._m_spec_ok.value), int(eng._m_spec_rej.value))


def _assert_async_matrix_cell(model, backend, K, mp=None, kv=None,
                              bucketed=True):
    """One (backend, K, mp, kv_dtype) cell: the same mixed trace
    served serial then async over (a) chunked cold, (b) same engine
    warm, (c) legacy bucketed — token lists identical per mode, ONE
    decode trace each, and at K>0 identical draft-acceptance counters
    (the direct witness that helper-thread drafts equal serial
    drafts)."""
    rng = np.random.RandomState(11)
    reqs = _trace(rng)

    def serve(async_core):
        quant = dict(kv_dtype=kv, weight_dtype=kv) if kv else {}
        def mk(**kw):
            return GenerationEngine(model, num_slots=3, block_size=4,
                                    num_blocks=64, spec_decode_k=K,
                                    attention_backend=backend,
                                    mp_degree=mp, async_core=async_core,
                                    **quant, **kw)

        eng = mk(prefill_chunk=8)
        out = [_run_trace(eng, reqs),
               _run_trace(eng, reqs, midrun=False)]   # warm cache
        engines = [eng]
        if bucketed:
            eng_b = mk(prefill_buckets=(16, 64))
            out.append(_run_trace(eng_b, reqs))
            engines.append(eng_b)
        for e in engines:
            assert e.async_core == async_core
            assert e.decode_traces == 1, \
                (f"{backend} K={K} mp={mp} kv={kv} "
                 f"async={async_core}: decode retraced")
        return out, eng

    serial, eng_s = serve(False)
    amode, eng_a = serve(True)
    assert amode == serial, \
        f"{backend} K={K} mp={mp} kv={kv}: async diverged from serial"
    if K:
        assert _spec_counters(eng_a) == _spec_counters(eng_s), \
            "helper-thread drafts diverged from serial proposals"
        assert sum(_spec_counters(eng_s)) > 0, \
            "trace never exercised the drafter — weak test"
    # the async engine retired every dispatched step before returning
    assert eng_a._inflight is None and eng_a._ahead is None


# ---------------------------------------------------------------------------
# tentpole: serial-vs-async token identity
# ---------------------------------------------------------------------------

# The 1-core CI box can't fit the whole suite in the tier-1 window,
# so tier-1 carries ONE identity cell — dense K=4, the cell that
# exercises the helper-thread drafter AND the pipeline at once — and
# the slow tier carries the rest (the test_engine_sharded precedent).
@pytest.mark.parametrize(
    "K", [pytest.param(0, marks=pytest.mark.slow), 4])
def test_async_token_identity_dense(model, K):
    """Tier-1 cut of THE acceptance gate: (dense, K, mp=1, fp) over
    chunked cold + warm + bucketed with mid-run admissions."""
    _assert_async_matrix_cell(model, "dense", K)


@pytest.mark.slow
def test_async_token_identity_pallas_spec(model):
    """Tier-1 lean probe of the (pallas, K=4) cell — the fused verify
    kernel under the dispatch-ahead pipeline (chunked legs only; the
    slow full matrix adds bucketed + mp + int8)."""
    _assert_async_matrix_cell(model, "pallas", 4, bucketed=False)


@pytest.mark.slow
@pytest.mark.parametrize("kv", [None, "int8"])
@pytest.mark.parametrize("mp", [None, 2])
@pytest.mark.parametrize("backend,K", [("dense", 0), ("dense", 4),
                                       ("pallas", 0), ("pallas", 4)])
def test_async_token_identity_full_matrix(model, backend, K, mp, kv):
    """The full {backend} x K x mp x kv_dtype identity matrix the
    ISSUE gates on (slow-marked; tier-1 carries the three lean cells
    above — the test_engine_sharded precedent)."""
    _assert_async_matrix_cell(model, backend, K, mp=mp, kv=kv)


@pytest.mark.slow
def test_async_sampled_lanes_identical(model):
    """Sampled lanes are where draft identity has teeth: the
    acceptance coin compares against p(draft token), so ANY
    helper-thread draft divergence shows up as a different token
    stream. Mixed greedy + sampled lanes, serial vs async."""
    rng = np.random.RandomState(7)
    reqs = _trace(rng)

    def serve(async_core):
        eng = GenerationEngine(model, num_slots=3, block_size=4,
                               num_blocks=64, prefill_chunk=8,
                               spec_decode_k=4, sampling=True,
                               async_core=async_core)
        ids = []
        for i, (p, n) in enumerate(reqs):
            sp = SamplingParams(temperature=0.9, top_k=8,
                                seed=100 + i) if i % 2 else None
            ids.append(eng.add_request(p, n, sampling_params=sp))
        out = eng.run()
        return [list(map(int, out[rid])) for rid in ids], eng

    serial, eng_s = serve(False)
    amode, eng_a = serve(True)
    assert amode == serial
    assert _spec_counters(eng_a) == _spec_counters(eng_s)


# ---------------------------------------------------------------------------
# draft_window: the ONE filter both the serial scheduler and the
# async drafter thread run — pure-function contract (no engine, no
# jit; a divergence here breaks sampled-lane token identity, so the
# edge cases get direct coverage)
# ---------------------------------------------------------------------------

class _ListDrafter:
    """Stub drafter replaying a fixed proposal regardless of input."""

    def __init__(self, tokens):
        self.tokens = list(tokens)

    def propose(self, prompt, generated, budget):
        return list(self.tokens)


@pytest.mark.parametrize("proposal,budget,vocab,want", [
    ([3, 5, 7], 3, 64, [3, 5, 7]),        # in-vocab, exact budget
    ([3, 5, 7, 9], 2, 64, [3, 5]),        # over-proposal capped
    ([3, 64, 7], 3, 64, [3]),             # vocab edge truncates...
    ([3, -1, 7], 3, 64, [3]),             # ...as does a negative id
    ([64, 3, 5], 3, 64, []),              # junk head: verify nothing
    ([3, 5], 0, 64, []),                  # exhausted budget: no call
    ([3, 5], -2, 64, []),                 # clamped budget stays empty
    ([], 4, 64, []),                      # drafter declined
])
def test_draft_window_junk_filter_and_budget(proposal, budget, vocab,
                                             want):
    got = speculative.draft_window(_ListDrafter(proposal), [1, 2],
                                   [0], budget, vocab)
    assert got == want


def test_draft_window_numpy_scalars_coerced():
    """Drafters may return numpy ints; the window must hand the
    engine plain Python ints (they're compared + device_put later)."""
    got = speculative.draft_window(
        _ListDrafter(np.array([3, 5], dtype=np.int32)), [1], [], 2, 64)
    assert got == [3, 5] and all(type(t) is int for t in got)


def test_draft_window_snapshot_equals_live_context():
    """The async core hands the helper thread a SNAPSHOT of
    slot.generated; the ngram drafter must propose identically from
    the copy (purity — the thread-safety contract in the docstring)."""
    rng = np.random.RandomState(3)
    motif = rng.randint(0, 64, 4).tolist()
    prompt = np.array(motif * 3, dtype=np.int32)
    live = list(motif) + [7]
    drafter = speculative.NgramDrafter()
    a = speculative.draft_window(drafter, prompt, list(live), 4, 64)
    b = speculative.draft_window(drafter, prompt, live, 4, 64)
    assert a == b
    assert live == list(motif) + [7]      # context never mutated


# ---------------------------------------------------------------------------
# satellite: knob resolution + serial path untouched
# ---------------------------------------------------------------------------

def test_async_knob_default_off_and_ctor(model):
    assert GenerationEngine(model, num_slots=2, block_size=4,
                            num_blocks=32).async_core is False
    assert GenerationEngine(model, num_slots=2, block_size=4,
                            num_blocks=32,
                            async_core=True).async_core is True


def test_async_env_knob_wins_over_ctor(model, monkeypatch):
    mk = lambda **kw: GenerationEngine(model, num_slots=2,
                                       block_size=4, num_blocks=32,
                                       **kw)
    monkeypatch.setenv("PADDLE_SERVE_ASYNC", "1")
    assert mk(async_core=False).async_core is True
    monkeypatch.setenv("PADDLE_SERVE_ASYNC", "off")
    assert mk(async_core=True).async_core is False
    monkeypatch.setenv("PADDLE_SERVE_ASYNC", "")   # '' means unset
    assert mk(async_core=True).async_core is True
    monkeypatch.setenv("PADDLE_SERVE_ASYNC", "maybe")
    with pytest.raises(ValueError, match="PADDLE_SERVE_ASYNC"):
        mk()


def test_async_off_engages_no_pipeline_state(model):
    """The serial default never touches the in-flight machinery: no
    dispatched-ahead slot, no helper thread, no async flight events —
    the op-for-op guarantee has an observable witness."""
    rng = np.random.RandomState(3)
    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           num_blocks=32, prefill_chunk=8,
                           spec_decode_k=4)
    eng.add_request(rng.randint(0, VOCAB, 6).astype(np.int32), 5)
    eng.run()
    assert eng._inflight is None and eng._ahead is None
    events = {e["event"] for e in eng.flight.dump()}
    assert not (events & {"async_dispatch", "async_complete",
                          "adapter_prefetch"})


# ---------------------------------------------------------------------------
# satellite: pipeline drain — EOS / shed / drain() with a step in flight
# ---------------------------------------------------------------------------

def test_async_drain_completes_inflight_step(model):
    """drain() called while a dispatched-ahead step is outstanding:
    the in-flight step must complete (not leak device work or
    blocks), results must match the serial engine, and both leak
    audits must pass."""
    rng = np.random.RandomState(9)
    reqs = _trace(rng)

    def serve(async_core):
        eng = GenerationEngine(model, num_slots=3, block_size=4,
                               num_blocks=64, prefill_chunk=8,
                               spec_decode_k=4, async_core=async_core)
        ids = [eng.add_request(p, n) for p, n in reqs]
        # step until a dispatched step is actually in flight, then
        # drain with it outstanding
        for _ in range(16):
            eng.step()
            if async_core and eng._inflight is not None:
                break
        if async_core:
            assert eng._inflight is not None, \
                "trace never left a step in flight — weak test"
        out = eng.drain()               # audits blocks + raises on leak
        return [list(map(int, out[rid])) for rid in ids], eng

    serial, _ = serve(False)
    amode, eng = serve(True)
    assert amode == serial
    assert eng._inflight is None and eng._ahead is None


@pytest.mark.slow
def test_async_eos_mid_pipeline(model):
    """An EOS accepted while the pipeline is warm truncates exactly
    like the serial engine — the in-flight step covering the retired
    lane completes and the lane's blocks come back."""
    rng = np.random.RandomState(5)
    motif = rng.randint(0, VOCAB, 3).astype(np.int32)
    reqs = [(np.tile(motif, 4).astype(np.int32), 12),
            (rng.randint(0, VOCAB, 7).astype(np.int32), 12)]

    def serve(async_core, eos):
        eng = GenerationEngine(model, num_slots=2, block_size=4,
                               num_blocks=64, prefill_chunk=8,
                               spec_decode_k=4, async_core=async_core)
        ids = [eng.add_request(p, n, eos_token_id=eos)
               for p, n in reqs]
        out = eng.drain()
        return [list(map(int, out[rid])) for rid in ids]

    base = serve(False, None)
    # pick an eos the streams actually emit -> mid-run truncation
    eos = int(base[0][len(reqs[0][0]) + 1])
    serial = serve(False, eos)
    amode = serve(True, eos)
    assert amode == serial
    assert any(len(a) < len(b) for a, b in zip(serial, base)), \
        "eos never truncated a stream — weak test"


@pytest.mark.slow
def test_async_shed_midrun_identical(model):
    """Saturation shedding under the async core: same losers (None
    results), same survivors' tokens as serial."""
    rng = np.random.RandomState(13)
    reqs = [(rng.randint(0, VOCAB, rng.randint(3, 10))
             .astype(np.int32), 4) for _ in range(8)]

    def serve(async_core):
        eng = GenerationEngine(model, num_slots=2, block_size=4,
                               num_blocks=64, prefill_chunk=8,
                               max_queue=2, async_core=async_core)
        ids = [eng.add_request(p, n, priority="batch")
               for p, n in reqs]
        out = eng.run()
        shed = sum(out[rid] is None for rid in ids)
        return [None if out[rid] is None else
                list(map(int, out[rid])) for rid in ids], shed

    serial, shed_s = serve(False)
    amode, shed_a = serve(True)
    assert amode == serial
    assert shed_a == shed_s > 0, "queue never saturated — weak test"


# ---------------------------------------------------------------------------
# satellite: compiled-program identity + steady state
# ---------------------------------------------------------------------------

def test_async_steady_state_retraces_nothing(model):
    """A warmed async engine serves new work under
    `expect_traces(0)` on both compiled steps — dispatch-ahead feeds
    the EXACT programs the serial core compiled."""
    rng = np.random.RandomState(2)
    eng = GenerationEngine(model, num_slots=3, block_size=4,
                           num_blocks=64, prefill_chunk=8,
                           spec_decode_k=4, async_core=True)
    _run_trace(eng, _trace(rng))
    assert eng.decode_traces == 1 and eng.prefill_traces == 1
    with jit.expect_traces(eng._decode_pure, 0), \
            jit.expect_traces(eng._prefill_pure, 0):
        eng.add_request(rng.randint(0, VOCAB, 9).astype(np.int32), 5)
        eng.run()


# ---------------------------------------------------------------------------
# satellite: the flight recorder shows the pipeline pipelining
# ---------------------------------------------------------------------------

def test_async_flight_recorder_interleave(model):
    """The black box proves the dispatch-ahead shape: per sequence
    number, `async_dispatch(s)` strictly precedes `async_complete(s)`;
    the pipe never runs deeper than ONE in-flight step (dispatch s+1
    only after complete s); every dispatch is eventually completed."""
    rng = np.random.RandomState(4)
    eng = GenerationEngine(model, num_slots=3, block_size=4,
                           num_blocks=64, prefill_chunk=8,
                           spec_decode_k=4, async_core=True,
                           flight_capacity=4096)
    _run_trace(eng, _trace(rng))
    evs = [(e["event"], e["seq"]) for e in eng.flight.dump()
           if e["event"] in ("async_dispatch", "async_complete")]
    assert evs, "no pipeline events recorded"
    outstanding = None
    seen = 0
    for event, seq in evs:
        if event == "async_dispatch":
            assert outstanding is None, \
                f"dispatch {seq} while {outstanding} in flight"
            assert seq == seen + 1, f"dispatch seq skipped: {evs}"
            outstanding, seen = seq, seq
        else:
            assert outstanding == seq, \
                f"complete {seq} without its dispatch"
            outstanding = None
    assert outstanding is None, "a dispatched step was never completed"
    assert seen > 2, "trace too short to exercise the pipeline"


# ---------------------------------------------------------------------------
# satellite: adapter prefetch rides the pipeline
# ---------------------------------------------------------------------------

def _strong_registry(cfg, ranks=(2, 3), seed=7, scale=0.3, group=None):
    rng = np.random.RandomState(seed)
    reg = AdapterRegistry(cfg, max_rank=4)
    H, I, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    for aid, r in enumerate(ranks, start=1):
        w = {}
        for site, (i_d, o_d) in (("qkv", (H, 3 * H)), ("out", (H, H)),
                                 ("fc1", (H, I)), ("fc2", (I, H))):
            w[site] = [(rng.randn(r, i_d).astype(np.float32) * scale,
                        rng.randn(o_d, r).astype(np.float32) * scale)
                       for _ in range(L)]
        reg.register(aid, w, scaling=0.5, group=group)
    return reg


@pytest.mark.slow
def test_async_adapter_prefetch_and_evictions(model):
    """Multi-tenant trace under pool pressure (3 hot adapters + base
    over 1-2 usable pages): async serves token-identically to serial
    while
    `adapter_prefetch` events land in the flight recorder, evictions
    still happen mid-run, and the drain audit stays green."""
    registry = _strong_registry(model.config, ranks=(2, 3, 2))
    rng = np.random.RandomState(11)
    reqs = []
    for aid in (1, 2, 0, 3, 0, 1, 3, 2):
        reqs.append((rng.randint(0, VOCAB, rng.randint(2, 12))
                     .astype(np.int32), int(rng.randint(2, 6)), aid))

    def serve(async_core, pages):
        eng = GenerationEngine(model, num_slots=2, block_size=4,
                               num_blocks=64, prefill_chunk=8,
                               adapters=registry,
                               adapter_pool_pages=pages,
                               async_core=async_core)
        ids = [eng.add_request(p, n, adapter_id=a)
               for p, n, a in reqs]
        out = eng.drain()
        return [list(map(int, out[rid])) for rid in ids], eng

    # pressure leg: ONE usable page -> the tenants thrash it, and the
    # prefetcher must never steal it from a live lane
    serial, eng_s = serve(False, pages=2)
    amode, eng_a = serve(True, pages=2)
    assert amode == serial
    assert eng_a.adapter_pool.evictions > 0, \
        "pool never thrashed — weak test"
    # headroom leg: with a spare page the pipeline warms the queue
    # head's adapter behind the dispatched step
    serial, _ = serve(False, pages=3)
    amode, eng_a = serve(True, pages=3)
    assert amode == serial
    prefetches = [e for e in eng_a.flight.dump()
                  if e["event"] == "adapter_prefetch"]
    assert prefetches, "async core never prefetched an adapter page"
    # prefetch is an optimization, not an accounting channel: pages
    # still audit clean (drain() above already asserted leak_check)
    assert eng_a.adapter_pool.leak_check() == []


# ---------------------------------------------------------------------------
# satellite: the gpt_engine_async_overlap bench row
# ---------------------------------------------------------------------------

def test_suite_rows_carry_async_overlap_row():
    import bench_ops

    assert "gpt_engine_async_overlap" in bench_ops.SUITE_ROWS


@pytest.mark.slow
def test_async_overlap_bench_runner_tiny(monkeypatch):
    """The `gpt_engine_async_overlap` runner end-to-end on a tiny
    config — its in-runner gates ARE the acceptance criteria: per-rep
    token identity, async overlappable host gap
    (schedule+draft_propose+adapter_swap) strictly below serial's,
    async device fraction no lower. Here we only re-check the record
    shape; the runner already threw if any gate failed."""
    from paddle_tpu.models import GPTConfig

    import bench_ops

    monkeypatch.delenv("PADDLE_SERVE_TRACING", raising=False)
    # hidden=256/layers=3 keeps the step device-bound even on the CPU
    # runner: the device-fraction gate (async >= serial) only holds
    # structurally when there IS device time left to hide host work
    # behind — a host-bound toy model lets the async core drive the
    # device_wait residual toward zero, which is the pipeline working,
    # not a regression.
    cfg = GPTConfig.tiny(vocab=VOCAB, hidden=256, layers=3, heads=4,
                         seq=128)
    rec = bench_ops._engine_async_overlap_case(
        model_cfg=cfg, num_requests=12, block_size=8, max_new=6)()
    assert "ms" in rec and rec["ms"] > 0
    for mode in ("serial", "async"):
        phases = rec[mode]["phase_ms_per_step_warm"]
        assert "dispatch" in phases and "adapter_swap" in phases
        assert 0.0 <= rec[mode]["device_fraction_warm"] <= 1.0
    assert rec["async"]["host_overlap_gap_ms"] \
        < rec["serial"]["host_overlap_gap_ms"]


# ---------------------------------------------------------------------------
# satellite: fleet replicas run the async core via the env knob
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_fleet_async_replicas_token_exact(model, monkeypatch):
    """A disaggregated fleet with every replica on the async core
    (via PADDLE_SERVE_ASYNC — the fleet builds its own engines) stays
    token-exact vs the serial bare engine, and the prestaged handoff
    flush still drains every parked prefill."""
    rng = np.random.RandomState(6)
    trace = [(rng.randint(0, VOCAB, int(rng.randint(3, 30))), 5)
             for _ in range(6)]

    def eng_serve():
        eng = GenerationEngine(model, num_slots=4, block_size=8)
        ids = [eng.add_request(p, max_new_tokens=n) for p, n in trace]
        out = eng.run()
        return {i: list(map(int, out[i])) for i in ids}

    ref = eng_serve()
    monkeypatch.setenv("PADDLE_SERVE_ASYNC", "1")
    fleet = ServingFleet(model, num_slots=4, block_size=8,
                         num_replicas=1, num_prefill_replicas=1)
    ids = [fleet.add_request(p, max_new_tokens=n) for p, n in trace]
    out = fleet.run()
    assert {i: list(map(int, out[i])) for i in ids} == ref
    for rep in fleet._replicas.values():
        assert rep.engine.async_core is True
