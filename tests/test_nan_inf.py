"""FLAGS_check_nan_inf tests (VERDICT r2 #10): flags registry, eager op
checks, and the staged check inside compiled train steps.

Reference analogs: paddle/fluid/eager/nan_inf_utils.h:37,
paddle.set_flags/get_flags.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.jit as jit


@pytest.fixture
def nan_check():
    paddle.set_flags({"FLAGS_check_nan_inf": 1})
    yield
    paddle.set_flags({"FLAGS_check_nan_inf": 0,
                      "FLAGS_check_nan_inf_level": 0})


def test_flags_registry_roundtrip():
    assert paddle.get_flags("FLAGS_check_nan_inf") == \
        {"FLAGS_check_nan_inf": False}
    paddle.set_flags({"FLAGS_check_nan_inf": "true"})
    assert paddle.get_flags(["FLAGS_check_nan_inf"])[
        "FLAGS_check_nan_inf"] is True
    paddle.set_flags({"FLAGS_check_nan_inf": 0})
    with pytest.raises(ValueError, match="unknown flag"):
        paddle.set_flags({"FLAGS_no_such": 1})


def test_eager_nan_detected(nan_check):
    x = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
    with pytest.raises(FloatingPointError, match="divide"):
        x / x  # 0/0 -> nan

    # warn-only level
    paddle.set_flags({"FLAGS_check_nan_inf_level": 3})
    with pytest.warns(UserWarning, match="nan/inf"):
        x / x


def test_eager_clean_ops_pass(nan_check):
    x = paddle.to_tensor(np.ones(4, np.float32))
    y = (x * 2.0 + 1.0).sum()
    assert float(y) == 12.0


def test_compiled_step_nan_raises(nan_check):
    paddle.seed(0)
    net = nn.Linear(4, 4)
    opt = paddle.optimizer.SGD(learning_rate=1e30,  # explodes fast
                               parameters=net.parameters())
    step = jit.TrainStep(net, opt, F.mse_loss)
    x = paddle.to_tensor(np.ones((2, 4), np.float32) * 1e20)
    y = paddle.to_tensor(np.zeros((2, 4), np.float32))
    with pytest.raises(Exception, match="nan/inf detected"):
        for _ in range(4):
            loss = step(x, y)
            float(loss)  # force sync so the callback fires


def test_flag_toggle_reaches_compiled_step():
    """Enabling the flag AFTER the step compiled must still take effect
    (caches key on the flags epoch)."""
    paddle.seed(0)
    net = nn.Linear(4, 4)
    opt = paddle.optimizer.SGD(learning_rate=1e30,
                               parameters=net.parameters())
    step = jit.TrainStep(net, opt, F.mse_loss)
    x = paddle.to_tensor(np.ones((2, 4), np.float32) * 1e20)
    y = paddle.to_tensor(np.zeros((2, 4), np.float32))
    float(step(x, y))  # compiles with checks OFF
    paddle.set_flags({"FLAGS_check_nan_inf": 1})
    try:
        with pytest.raises(Exception, match="nan/inf detected"):
            for _ in range(4):
                float(step(x, y))
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": 0})


def test_compiled_step_clean_passes(nan_check):
    paddle.seed(0)
    net = nn.Linear(4, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    step = jit.TrainStep(net, opt, F.mse_loss)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    y = paddle.to_tensor(np.zeros((2, 4), np.float32))
    l0 = float(step(x, y))
    l1 = float(step(x, y))
    assert l1 < l0
