"""Worker script for the 2-process localhost tests (the dist_mnist.py
analog of test_dist_base.py:899): launched by
`python -m paddle_tpu.distributed.launch --nprocs 2 --backend cpu`.

Phases:
  collectives — init_parallel_env, then exercise the five core eager
      collectives + barrier against numpy expectations;
  train — DistributedTrainStep dp=2 parity: rank 0 writes per-step
      losses to OUT_FILE for the parent to compare with its 1-process
      baseline.
"""
import json
import os
import sys

import numpy as np


def check(name, got, want):
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6, err_msg=name)
    print(f"ok {name}", flush=True)


def run_collectives(dist, paddle, rank, world):
    t = paddle.to_tensor(np.full((4,), float(rank + 1), np.float32))
    dist.all_reduce(t)
    check("all_reduce", t._array, np.full((4,), sum(range(1, world + 1)), np.float32))

    outs = []
    t = paddle.to_tensor(np.full((3,), float(rank), np.float32))
    dist.all_gather(outs, t)
    for j in range(world):
        check(f"all_gather[{j}]", outs[j]._array, np.full((3,), float(j)))

    t = paddle.to_tensor(np.full((2,), float(rank * 10 + 5), np.float32))
    dist.broadcast(t, src=1)
    check("broadcast", t._array, np.full((2,), 15.0))

    t = paddle.to_tensor(np.full((2,), float(rank + 1), np.float32))
    dist.reduce(t, dst=0, op=dist.ReduceOp.MAX)
    if rank == 0:
        check("reduce", t._array, np.full((2,), float(world)))

    # scatter: src=0 provides per-rank rows
    t = paddle.to_tensor(np.zeros((2,), np.float32))
    tl = [paddle.to_tensor(np.full((2,), 100.0 + j, np.float32))
          for j in range(world)] if rank == 0 else None
    dist.scatter(t, tensor_list=tl, src=0)
    check("scatter", t._array, np.full((2,), 100.0 + rank))

    # alltoall: rank r sends value r*10+j to rank j
    ins = [paddle.to_tensor(np.full((2,), float(rank * 10 + j), np.float32))
           for j in range(world)]
    outs = []
    dist.alltoall(ins, outs)
    for j in range(world):
        check(f"alltoall[{j}]", outs[j]._array,
              np.full((2,), float(j * 10 + rank)))

    # reduce_scatter: everyone contributes [world] rows, gets its summed row
    t = paddle.to_tensor(np.zeros((2,), np.float32))
    tl = [paddle.to_tensor(np.full((2,), float(rank + j), np.float32))
          for j in range(world)]
    dist.reduce_scatter(t, tl)
    want = sum(r + rank for r in range(world))
    check("reduce_scatter", t._array, np.full((2,), float(want)))

    dist.barrier()
    print("ok barrier", flush=True)


def run_train(dist, paddle, rank, world, out_file):
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import (DistributedTrainStep,
                                        HybridCommunicateGroup,
                                        set_hybrid_communicate_group)

    hcg = HybridCommunicateGroup(dp=world)
    set_hybrid_communicate_group(hcg)

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 32), nn.Tanh(), nn.Linear(32, 4))
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=net.parameters())
    step = DistributedTrainStep(net, opt, F.cross_entropy, hcg=hcg)

    rng = np.random.RandomState(42)
    losses = []
    for _ in range(5):
        # every rank feeds the identical GLOBAL batch; the step's input
        # sharding slices out the local dp shard
        x = rng.uniform(-1, 1, (8, 8)).astype(np.float32)
        y = rng.randint(0, 4, (8,)).astype(np.int64)
        loss = step(paddle.to_tensor(x), paddle.to_tensor(y))
        losses.append(float(loss))
    if rank == 0 and out_file:
        with open(out_file, "w") as f:
            json.dump(losses, f)
    print("ok train", losses, flush=True)


def run_localsgd(dist, paddle, rank, world, out_file):
    """LocalSGD 2-process: ranks train on DIFFERENT local batches for
    k=2 local steps, then params average; after each sync both ranks
    must hold identical parameters."""
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed.localsgd import LocalSGD

    paddle.seed(0)  # same init everywhere (broadcast analog)
    net = nn.Linear(6, 3)
    opt = LocalSGD(paddle.optimizer.SGD(learning_rate=0.1,
                                        parameters=net.parameters()),
                   k_steps=2)
    rng = np.random.RandomState(100 + rank)  # per-rank local data
    for i in range(4):
        x = paddle.to_tensor(rng.randn(8, 6).astype(np.float32))
        y = paddle.to_tensor(rng.randint(0, 3, (8,)))
        F.cross_entropy(net(x), y).backward()
        opt.step()
        opt.clear_grad()
    # 4 steps / k=2 -> 2 syncs; the last step ended ON a sync boundary
    w = np.asarray(net.weight._array)
    gathered = []
    dist.all_gather(gathered, paddle.to_tensor(w))
    check("localsgd_params_equal", gathered[0]._array, gathered[1]._array)
    if rank == 0 and out_file:
        with open(out_file, "w") as f:
            json.dump({"ok": True}, f)
    print("ok localsgd", flush=True)


def run_ps(dist, paddle, rank, world):
    """2-process PS: each host owns id%2 rows; pulls/pushes for remote
    ids ride the alltoall (the distributed_lookup/push_sparse path)."""
    from paddle_tpu.distributed.ps import MemorySparseTable, SparseSGDRule

    t = MemorySparseTable(dim=4, rule=SparseSGDRule(0.1))
    assert t.nshards == world
    # mixed-ownership ids incl. >2^24 (float32 would corrupt them)
    ids = np.array([0, 1, 2, 3, 2**33 + 1])
    rows = t.pull(ids)
    assert rows.shape == (5, 4)
    # remote and local rows agree across processes (same shard serves all)
    again = t.pull(ids)
    check("ps_pull_stable", again, rows)
    # push from every process: owner applies BOTH pushes (sum over
    # trainers, like the PS server accumulating pushed grads)
    t.push(ids, np.ones((5, 4), np.float32))
    dist.barrier()
    after = t.pull(ids)
    check("ps_push", after, rows - 0.1 * world)
    print("ok ps", flush=True)


def run_zero(dist, paddle, rank, world, out_file):
    """ZeRO-2 with the 'sharding' axis spanning PROCESS boundaries: each
    rank holds one device, so the reduce-scatter/all-gather the SPMD
    partitioner inserts ride the cross-process fabric — the
    group_sharded multi-host regime."""
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import (HybridCommunicateGroup,
                                        set_hybrid_communicate_group)

    hcg = HybridCommunicateGroup(sharding=world)
    set_hybrid_communicate_group(hcg)
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(16, 64), nn.Tanh(), nn.Linear(64, 16))
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=net.parameters())
    from paddle_tpu.distributed import make_sharded_step

    step = make_sharded_step(net, opt, lambda o, t: F.mse_loss(o, t),
                             level="os_g")
    rng = np.random.RandomState(7)
    losses = []
    for _ in range(4):
        x = rng.randn(8, 16).astype(np.float32)
        y = rng.randn(8, 16).astype(np.float32)
        losses.append(float(step(paddle.to_tensor(x),
                                 paddle.to_tensor(y))))
    # opt state is genuinely sharded across the two processes
    m = opt._accumulators["moment1"][0]
    assert "sharding" in str(m.sharding.spec), m.sharding
    if rank == 0 and out_file:
        with open(out_file, "w") as f:
            json.dump(losses, f)
    print("ok zero", losses, flush=True)


def run_mp(dist, paddle, rank, world, out_file):
    """Tensor parallel with the 'mp' axis spanning processes: the row
    layer's partial-sum all-reduce crosses the process fabric (the
    multi-host Megatron regime)."""
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import (DistributedTrainStep,
                                        HybridCommunicateGroup,
                                        set_hybrid_communicate_group)

    hcg = HybridCommunicateGroup(mp=world)
    set_hybrid_communicate_group(hcg)
    paddle.seed(0)
    import paddle_tpu.nn as nn

    class MPNet(nn.Layer):
        def __init__(self):
            super().__init__()
            self.col = dist.ColumnParallelLinear(16, 32,
                                                 gather_output=False)
            self.row = dist.RowParallelLinear(32, 16,
                                              input_is_parallel=True)

        def forward(self, x):
            return self.row(self.col(x))

    net = MPNet()
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=net.parameters())
    step = DistributedTrainStep(net, opt, lambda o, t: F.mse_loss(o, t),
                                hcg=hcg, batch_axes=())
    rng = np.random.RandomState(11)
    losses = []
    for _ in range(4):
        x = rng.randn(8, 16).astype(np.float32)
        y = rng.randn(8, 16).astype(np.float32)
        losses.append(float(step(paddle.to_tensor(x),
                                 paddle.to_tensor(y))))
    w = net.col.weight._array
    assert "mp" in str(w.sharding.spec), w.sharding
    if rank == 0 and out_file:
        with open(out_file, "w") as f:
            json.dump(losses, f)
    print("ok mp", losses, flush=True)


def run_pp(dist, paddle, rank, world, out_file):
    """Pipeline parallel with the 'pp' axis spanning processes: the
    shift-register's collective-permute crosses the process fabric (the
    multi-host p2p send/recv regime)."""
    from paddle_tpu.distributed import (HybridCommunicateGroup,
                                        set_hybrid_communicate_group)
    from pp_model import build_pp_model, run_pp_losses

    set_hybrid_communicate_group(HybridCommunicateGroup(pp=world))
    model, step = build_pp_model(num_stages=world)
    losses = run_pp_losses(step, paddle)
    # the stacked body must REALLY be pp-sharded — a silent fallback to
    # replicated sequential execution would still match the baseline
    stacked = model.stack._stacked[0]._array
    assert "pp" in str(stacked.sharding.spec), stacked.sharding
    if rank == 0 and out_file:
        with open(out_file, "w") as f:
            json.dump(losses, f)
    print("ok pp", losses, flush=True)


def run_epcp(dist, paddle, rank, world, out_file):
    """Expert parallel (MoE token all-to-all) and context parallel (ring
    attention ppermute) with their axes spanning processes."""
    import jax
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed import (HybridCommunicateGroup,
                                        set_hybrid_communicate_group)
    from paddle_tpu.distributed.ring_attention import ring_attention

    # ep: tokens ship to their expert's owner process and back
    set_hybrid_communicate_group(HybridCommunicateGroup(ep=world))
    paddle.seed(0)
    moe = dist.MoELayer(d_model=8, d_hidden=16, num_experts=4,
                        capacity_factor=4.0)
    x_np = np.random.RandomState(0).randn(2, 8, 8).astype(np.float32)
    y = moe(paddle.to_tensor(x_np))
    from jax.experimental import multihost_utils

    # the output shards span both processes; gather to host-local numpy
    # (jax REQUIRES tiled=True for global non-fully-addressable arrays —
    # it reassembles the global value rather than stacking copies)
    y_np = np.asarray(multihost_utils.process_allgather(y._array,
                                                        tiled=True))

    # cp: ring attention over a cross-process sequence shard
    hcg = HybridCommunicateGroup(cp=world)
    set_hybrid_communicate_group(hcg)
    mesh = hcg.mesh
    B, S, H, D = 1, 8, 2, 4
    rs = np.random.RandomState(1)
    q = rs.randn(B, S, H, D).astype(np.float32)
    k = rs.randn(B, S, H, D).astype(np.float32)
    v = rs.randn(B, S, H, D).astype(np.float32)
    fn = shard_map(
        lambda a, b, c: ring_attention(a, b, c, axis_name="cp",
                                       causal=True),
        mesh=mesh, in_specs=(P(None, "cp"),) * 3, out_specs=P(None, "cp"))
    out = jax.jit(fn)(q, k, v)
    # each process holds its own sequence shard
    sh = out.addressable_shards[0]
    local = np.asarray(sh.data)
    seq_slice = sh.index[1]

    if rank == 0 and out_file:
        with open(out_file, "w") as f:
            json.dump({"moe_out": y_np.tolist(),
                       "cp_local": local.tolist(),
                       "cp_start": int(seq_slice.start or 0)}, f)
    print("ok epcp", flush=True)


def _remote_square(x):
    return x * x


def _remote_matsum(n):
    import paddle_tpu as paddle

    return float(paddle.ones([n, n]).sum()._array)


def run_rpc(dist, paddle, rank, world):
    """RPC rendezvous + sync/async calls between the two ranks."""
    from paddle_tpu.distributed import rpc

    me = rpc.init_rpc(f"worker{rank}")
    assert me.rank == rank
    infos = rpc.get_all_worker_infos()
    assert [w.name for w in infos] == [f"worker{i}" for i in range(world)]
    peer = f"worker{(rank + 1) % world}"
    assert rpc.rpc_sync(peer, _remote_square, args=(7,)) == 49
    fut = rpc.rpc_async(peer, _remote_matsum, args=(8,))
    assert fut.wait() == 64.0
    # exceptions propagate across the wire
    try:
        rpc.rpc_sync(peer, _remote_square, args=("x",))
        raise AssertionError("expected remote TypeError")
    except TypeError:
        pass
    dist.barrier()  # both sides done calling before servers go away
    rpc.shutdown()
    print("ok rpc", flush=True)


def run_p2p(dist, paddle, rank, world):
    """Host p2p send/recv + batch_isend_irecv over the rpc transport
    (communication/send.py, batch_isend_irecv.py analogs)."""
    from paddle_tpu.distributed import rpc

    rpc.init_rpc(f"worker{rank}")
    # blocking pair: 0 -> 1
    if rank == 0:
        dist.send(paddle.to_tensor(np.arange(4, dtype=np.float32) + 10),
                  dst=1)
    elif rank == 1:
        buf = paddle.to_tensor(np.zeros(4, np.float32))
        dist.recv(buf, src=0)
        np.testing.assert_allclose(np.asarray(buf._array),
                                   [10, 11, 12, 13])
    dist.barrier()
    # batched bidirectional exchange (the ring-exchange shape)
    peer = (rank + 1) % world
    out = paddle.to_tensor(np.full((3,), float(rank), np.float32))
    buf = paddle.to_tensor(np.zeros(3, np.float32))
    tasks = dist.batch_isend_irecv([
        dist.P2POp(dist.isend, out, peer),
        dist.P2POp(dist.irecv, buf, (rank - 1) % world),
    ])
    for t in tasks:
        t.wait()
    np.testing.assert_allclose(np.asarray(buf._array),
                               np.full((3,), float((rank - 1) % world)))
    dist.barrier()
    rpc.shutdown()
    print("ok p2p", flush=True)


def main():
    phase = sys.argv[1] if len(sys.argv) > 1 else "all"
    out_file = sys.argv[2] if len(sys.argv) > 2 else None

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    dist.init_parallel_env()
    rank = dist.get_rank()
    world = dist.get_world_size()
    assert world == int(os.environ["PADDLE_TRAINERS_NUM"]), \
        f"world={world} env={os.environ['PADDLE_TRAINERS_NUM']}"

    # out_file goes only to the explicitly requested phase: under
    # phase 'all' the writers would silently overwrite each other
    if phase in ("all", "collectives"):
        run_collectives(dist, paddle, rank, world)
    if phase in ("all", "train"):
        run_train(dist, paddle, rank, world,
                  out_file if phase == "train" else None)
    if phase in ("all", "ps"):
        run_ps(dist, paddle, rank, world)
    if phase in ("all", "rpc"):
        run_rpc(dist, paddle, rank, world)
    if phase in ("all", "zero"):
        run_zero(dist, paddle, rank, world,
                 out_file if phase == "zero" else None)
    if phase in ("all", "mp"):
        run_mp(dist, paddle, rank, world,
               out_file if phase == "mp" else None)
    if phase in ("all", "pp"):
        run_pp(dist, paddle, rank, world,
               out_file if phase == "pp" else None)
    if phase in ("all", "epcp"):
        run_epcp(dist, paddle, rank, world,
                 out_file if phase == "epcp" else None)
    if phase in ("all", "localsgd"):
        run_localsgd(dist, paddle, rank, world,
                     out_file if phase == "localsgd" else None)
    if phase == "p2p":
        run_p2p(dist, paddle, rank, world)
    if phase == "twonode":
        # two-node localhost simulation: check the node/local env split
        # is consistent with the global rank, then run a collective
        # across the full nnodes x per-node world
        node = int(os.environ["PADDLE_NODE_RANK"])
        local = int(os.environ["PADDLE_LOCAL_RANK"])
        lsize = int(os.environ["PADDLE_LOCAL_SIZE"])
        nnodes = int(os.environ["PADDLE_NNODES"])
        assert rank == node * lsize + local, (rank, node, local, lsize)
        assert world == nnodes * lsize, (world, nnodes, lsize)
        t = paddle.to_tensor(np.full((2,), float(rank), np.float32))
        dist.all_reduce(t)
        want = sum(range(world))
        np.testing.assert_allclose(np.asarray(t._array),
                                   np.full((2,), float(want)))
        print(f"ok twonode node={node} local={local} rank={rank} "
              f"world={world}", flush=True)
    print("WORKER_DONE", flush=True)


if __name__ == "__main__":
    main()
