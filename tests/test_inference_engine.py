"""Continuous-batching generation engine tests (the serving tier the
north star's "heavy traffic" clause asks for): token parity of the
paged-cache engine against the single-request compiled decode path,
mid-run admissions/evictions, recompile-count bounds via the
jit.count_traces probe, paged-vs-dense op parity, and pool-pressure
behavior.

Reference analogs: vLLM PagedAttention layout + Orca iteration-level
scheduling over the repo's forward_prefill/forward_decode split.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.jit as jit
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.inference import GenerationEngine
from paddle_tpu.models import GPTConfig, GPTForCausalLM

VOCAB = 61


def _model(seed=0, dropout=0.0):
    paddle.seed(seed)
    cfg = GPTConfig.tiny(vocab=VOCAB, hidden=32, layers=2, heads=2,
                         seq=64)
    cfg.dropout = dropout
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return _model()


def _reference(model, prompt, max_new, eos=None):
    """Single-request greedy decode through the compiled fixed-buffer
    KV-cache path — the parity oracle."""
    out = model.generate(Tensor._wrap(np.asarray(prompt, np.int32)[None]),
                         max_length=len(prompt) + max_new,
                         eos_token_id=eos, use_cache=True)
    return np.asarray(out._array)[0]


def test_engine_parity_midrun_arrivals_and_zero_recompiles(model):
    """The two headline acceptance criteria in one serving run:
    (a) >= 8 requests with heterogeneous prompt/output lengths,
    admissions AFTER decode started, slots < requests (finished
    requests vacate lanes for later arrivals), per-request output
    exactly equal to single-request greedy_decode; (b) steady-state
    decode compiles ONCE across all that churn and prefill compiles
    once per length bucket — proven by the jit.count_traces probe, not
    inferred from timing."""
    rng = np.random.RandomState(0)
    reqs = [(rng.randint(0, VOCAB, rng.randint(1, 8)).astype(np.int32),
             int(rng.randint(3, 10))) for _ in range(8)]

    eng = GenerationEngine(model, num_slots=3, block_size=4,
                           num_blocks=40, prefill_buckets=(8, 16, 64))
    ids = [eng.add_request(p, n) for p, n in reqs[:4]]
    for _ in range(3):
        eng.step()                      # decode is mid-stream...
    ids += [eng.add_request(p, n) for p, n in reqs[4:]]  # ...arrivals
    out = eng.run()

    assert len(out) == 8
    for (p, n), rid in zip(reqs, ids):
        got = np.asarray(out[rid])
        assert got.shape == (len(p) + n,)   # no-EOS: exactly max_new
        np.testing.assert_array_equal(got, _reference(model, p, n))

    # every prompt above was < 8 -> ONE bucket; decode traced once
    assert eng.decode_traces == 1
    assert eng.prefill_traces == 1
    # steady state: further churn in warmed buckets retraces NOTHING
    with jit.expect_traces(eng._decode_pure, 0), \
            jit.expect_traces(eng._prefill_pure, 0):
        eng.add_request(rng.randint(0, VOCAB, 5), 3)
        eng.run()
    # a NEW bucket is the one legitimate extra prefill compile
    eng.add_request(rng.randint(0, VOCAB, 12), 2)     # bucket 16
    eng.run()
    assert eng.prefill_traces == 2
    assert eng.decode_traces == 1                     # still one program


def test_engine_eos_early_stop_and_pool_pressure(model):
    """EOS mid-continuation evicts the lane early with exact parity to
    the frozen-row single-request semantics; and a pool smaller than
    sum-of-max-contexts forces block stalls that recover with outputs
    still exact (HBM shared by live context, not reserved per
    request). One small pool serves both scenarios."""
    rng = np.random.RandomState(1)
    prompt = rng.randint(0, VOCAB, 5).astype(np.int32)
    plain = _reference(model, prompt, 12)
    eos = int(plain[len(prompt) + 2])       # 3rd generated token
    ref_eos = _reference(model, prompt, 12, eos=eos)

    # 8 usable blocks x 4 tokens = 32 cached tokens vs 3 slots x 17
    # max demanded: stalls under full occupancy
    eng = GenerationEngine(model, num_slots=3, block_size=4,
                           num_blocks=9, prefill_buckets=(8, 64))
    reqs = [(rng.randint(0, VOCAB, rng.randint(2, 7)).astype(np.int32),
             int(rng.randint(4, 9))) for _ in range(4)]
    ids = [eng.add_request(p, n) for p, n in reqs]
    rid_eos = eng.add_request(prompt, 12, eos_token_id=eos)
    out = eng.run()

    got = out[rid_eos]
    assert len(got) < len(prompt) + 12      # stopped early
    assert got[-1] == eos
    np.testing.assert_array_equal(got, ref_eos[:len(got)])
    for (p, n), rid in zip(reqs, ids):
        np.testing.assert_array_equal(np.asarray(out[rid]),
                                      _reference(model, p, n))
    # all lanes vacated, every block returned to the free list
    assert eng.num_active == 0
    assert eng.cache.num_free == eng.cache.num_blocks - 1


def test_engine_deadlock_is_loud(model):
    """A request whose prompt can never fit the pool must fail with
    sizing guidance, not spin forever."""
    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           num_blocks=3, prefill_buckets=(16, 64))
    eng.add_request(np.arange(12) % VOCAB, 4)     # needs 3 blocks, has 2
    with pytest.raises(RuntimeError, match="grow num_blocks"):
        eng.run()


def test_engine_request_validation_and_eval_gate(model):
    eng = GenerationEngine(model, num_slots=2)
    with pytest.raises(ValueError, match="empty prompt"):
        eng.add_request([], 4)
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.add_request([1, 2], 0)
    with pytest.raises(ValueError, match="exceeds max_model_len"):
        eng.add_request(np.zeros(60, np.int32), 10)   # 70 > 64

    dropout_model = _model(seed=5, dropout=0.1)
    dropout_model.train()
    with pytest.raises(ValueError, match="eval"):
        GenerationEngine(dropout_model)


def test_paged_attention_step_matches_dense_attention():
    """Op-level parity: the block-table gather attention equals dense
    masked attention over the same context (the dense fallback the
    engine's correctness rests on)."""
    import jax.numpy as jnp

    from paddle_tpu.ops.paged_attention import (
        dense_gather_reference, paged_attention_step,
        paged_prefill_write)

    L, nb, bs, H, D = 2, 9, 4, 2, 8
    B, maxb = 3, 4
    rng = np.random.RandomState(7)
    kpool = jnp.zeros((L, nb, bs, H, D), jnp.float32)
    vpool = jnp.zeros((L, nb, bs, H, D), jnp.float32)
    # three slots with distinct context depths and disjoint blocks
    plens = [5, 2, 9]
    tables = np.zeros((B, maxb), np.int32)
    tables[0, :2] = [1, 2]
    tables[1, :1] = [3]
    tables[2, :3] = [4, 5, 6]
    ctx_k = rng.randn(B, maxb * bs, H, D).astype(np.float32)
    ctx_v = rng.randn(B, maxb * bs, H, D).astype(np.float32)
    for b in range(B):                 # seed each slot's prior context
        ks = np.zeros((L, 1, 16, H, D), np.float32)
        vs = np.zeros((L, 1, 16, H, D), np.float32)
        ks[:, 0, :plens[b]] = ctx_k[b, :plens[b]]
        vs[:, 0, :plens[b]] = ctx_v[b, :plens[b]]
        kpool, vpool = paged_prefill_write(
            kpool, vpool, ks, vs, np.asarray(tables[b]),
            np.int32(plens[b]))
        kpool, vpool = kpool._array, vpool._array

    q = rng.randn(B, 1, H, D).astype(np.float32)
    k_new = rng.randn(B, 1, H, D).astype(np.float32)
    v_new = rng.randn(B, 1, H, D).astype(np.float32)
    positions = np.asarray(plens, np.int32)       # write AT the depth
    for layer in range(L):
        out, kpool, vpool = paged_attention_step(
            q, k_new, v_new, kpool, vpool, layer, tables, positions)
        out, kpool, vpool = (np.asarray(out._array), kpool._array,
                             vpool._array)
        for b in range(B):
            T = plens[b] + 1
            kd = np.concatenate([ctx_k[b, :plens[b]], k_new[b]], 0)
            vd = np.concatenate([ctx_v[b, :plens[b]], v_new[b]], 0)
            # the written pool rows reassemble to exactly this context
            gk, gv = dense_gather_reference(kpool, vpool, layer,
                                            tables[b], T)
            np.testing.assert_allclose(gk, kd, rtol=1e-6)
            np.testing.assert_allclose(gv, vd, rtol=1e-6)
            logits = np.einsum("qhd,khd->hqk", q[b], kd) / np.sqrt(D)
            p = np.exp(logits - logits.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            ref = np.einsum("hqk,khd->qhd", p, vd)
            np.testing.assert_allclose(out[b], ref, rtol=1e-4,
                                       atol=1e-5)


def test_forward_decode_per_row_positions_matches_scalar(model):
    """The dense fixed-buffer decode now takes a [B] vector of per-row
    positions (the continuous-batching shape); each row must equal the
    scalar-pos single-row result."""
    rng = np.random.RandomState(6)
    Lbuf = 16
    prompts = [rng.randint(0, VOCAB, 3), rng.randint(0, VOCAB, 6)]

    caches = []
    for p in prompts:
        _, ks, vs = model.gpt.forward_prefill(
            Tensor._wrap(np.asarray(p, np.int32)[None]))
        ks, vs = np.asarray(ks._array), np.asarray(vs._array)
        pad = Lbuf - ks.shape[2]
        widths = [(0, 0), (0, 0), (0, pad), (0, 0), (0, 0)]
        caches.append((np.pad(ks, widths), np.pad(vs, widths)))

    toks = np.asarray([[5], [9]], np.int32)
    pos = np.asarray([len(prompts[0]), len(prompts[1])], np.int32)
    kb = np.concatenate([c[0] for c in caches], axis=1)
    vb = np.concatenate([c[1] for c in caches], axis=1)
    h_b, kb2, vb2 = model.gpt.forward_decode(
        Tensor._wrap(toks), Tensor._wrap(pos),
        Tensor._wrap(kb), Tensor._wrap(vb))
    h_b = np.asarray(h_b._array)

    for r in range(2):
        h1, k1, v1 = model.gpt.forward_decode(
            Tensor._wrap(toks[r:r + 1]), Tensor._wrap(pos[r]),
            Tensor._wrap(caches[r][0]), Tensor._wrap(caches[r][1]))
        np.testing.assert_allclose(h_b[r], np.asarray(h1._array)[0],
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(kb2._array)[:, r],
                                   np.asarray(k1._array)[:, 0],
                                   rtol=1e-6)


def test_count_traces_probe_and_expect_traces():
    """The CI recompile probe itself: counts jit cache misses, and the
    assertion helper trips on an unexpected retrace."""
    import jax
    import jax.numpy as jnp

    fn = jit.count_traces(lambda x: jnp.sin(x) * 2)
    jfn = jax.jit(fn)
    with jit.expect_traces(fn, 1):
        jfn(jnp.ones(3))
        jfn(jnp.ones(3) * 2)          # same shape: cached
    with pytest.raises(AssertionError, match="retracing"):
        with jit.expect_traces(fn, 0):
            jfn(jnp.ones(5))          # new shape: retrace
    with pytest.raises(TypeError):
        with jit.expect_traces(lambda: None, 0):
            pass


def test_engine_offered_load_bench_runner_tiny(monkeypatch):
    """The OPBENCH engine row's runner, at test scale: mixed
    prompt/output lengths through the engine, aggregate tokens/s out
    (the TPU run uses the representative 350M defaults)."""
    # isolate from the deploy knob: the default row must resolve auto
    monkeypatch.delenv("PADDLE_PAGED_ATTENTION_BACKEND", raising=False)
    import bench_ops

    model_cfg = GPTConfig.tiny(vocab=32, hidden=16, layers=1, heads=2,
                               seq=32)
    paddle.seed(0)
    rec = bench_ops._engine_offered_load_case(
        model_cfg=model_cfg,
        requests=[(3, 4), (6, 4), (10, 5)],
        num_slots=2, block_size=4, prefill_buckets=(4, 8, 16, 32))()
    assert rec["requests"] == 3
    assert rec["tokens_per_s"] > 0 and rec["ms"] > 0
    assert rec["attention_backend"] == "dense"     # auto off-TPU
    # the pallas variant row runs the same trace on the fused kernel
    # (interpreted off-TPU) and must serve every request too; ONE
    # request/bucket — interpret-mode compiles dominate, and the
    # backend itself is parity-tested in test_paged_attention_backends
    paddle.seed(0)
    rec_p = bench_ops._engine_offered_load_case(
        model_cfg=model_cfg, requests=[(3, 3)],
        num_slots=1, block_size=4, prefill_buckets=(4, 32),
        attention_backend="pallas")()
    assert rec_p["attention_backend"] == "pallas"
    assert rec_p["requests"] == 1 and rec_p["tokens_per_s"] > 0
    # names the gate will track are emitted by the suite
    s = bench_ops.suite()
    assert "gpt_decode_kv_350m" in s and callable(s["gpt_decode_kv_350m"])
    assert "gpt_engine_offered_load" in s
    # the cheap names-only view (check_bench_result --pending) must
    # never drift from the real suite
    assert list(s) == bench_ops.suite_names()


def test_engine_metrics_spans_and_steady_state_recompiles(model):
    """ISSUE 2 acceptance: a loaded engine run yields nonzero TTFT and
    per-token latency histograms, admission/completion counters exact
    vs the request trace, recompile counter == 0 in steady state — and
    the scheduler's iterations land as spans in the host tracer next to
    the metrics story."""
    from paddle_tpu.observability.metrics import series_total
    from paddle_tpu.profiler import Profiler

    rng = np.random.RandomState(3)
    reqs = [(rng.randint(0, VOCAB, rng.randint(2, 8)).astype(np.int32),
             int(rng.randint(3, 9))) for _ in range(6)]
    eng = GenerationEngine(model, num_slots=3, block_size=4,
                           num_blocks=40, prefill_buckets=(8, 64))
    prof = Profiler()
    with prof:
        for p, n in reqs:
            eng.add_request(p, n)
        eng.run()
        # steady state: more churn through warmed programs
        for p, n in reqs[:2]:
            eng.add_request(p, n)
        eng.run()
    snap = eng.metrics_snapshot()

    total_reqs = len(reqs) + 2
    new_tokens = sum(n for _, n in reqs) + sum(n for _, n in reqs[:2])
    ttft = snap["engine_ttft_seconds"]["series"][0]
    tpot = snap["engine_tpot_seconds"]["series"][0]
    assert ttft["count"] == total_reqs and ttft["sum"] > 0
    # each admitted request's first token comes from prefill; the rest
    # are decode-iteration observations
    assert tpot["count"] == new_tokens - total_reqs and tpot["sum"] > 0
    assert series_total(snap, "engine_admissions_total") == total_reqs
    assert series_total(snap, "engine_finished_total") == total_reqs
    by_reason = {s["labels"]["reason"]: s["value"]
                 for s in snap["engine_finished_total"]["series"]}
    assert by_reason.get("length", 0) == total_reqs  # no EOS configured
    assert series_total(snap, "engine_tokens_generated_total") \
        == new_tokens == eng.tokens_generated
    # steady-state SLO: zero decode recompiles, one compiled program
    assert series_total(snap, "engine_decode_recompiles_total") == 0
    assert snap["engine_decode_traces"]["series"][0]["value"] == 1
    # drained: gauges back to idle, pool fully returned
    assert snap["engine_queue_depth"]["series"][0]["value"] == 0
    assert snap["engine_active_slots"]["series"][0]["value"] == 0
    assert snap["engine_pool_used_blocks"]["series"][0]["value"] == 0
    assert snap["engine_pool_used_high_water_blocks"]["series"][0][
        "value"] > 0

    # trace correlation: scheduler + compiled-step spans in the tracer
    names = {e["name"] for e in prof._events}
    assert {"engine.step", "engine.prefill", "engine.decode"} <= names


def test_engine_pool_pressure_stall_counter(model):
    """A pool smaller than the live-context demand must surface as a
    nonzero block-stall counter while outputs stay exact (the graceful
    degradation PR-1 built, now measurable)."""
    from paddle_tpu.observability.metrics import series_total

    rng = np.random.RandomState(4)
    # 5 usable blocks, 3 slots: two 6-token prompts occupy 4 blocks;
    # the third has a free LANE but cannot get its 2 blocks until a
    # lane finishes — a deterministic admit-path stall with decode
    # still progressing (no deadlock)
    eng = GenerationEngine(model, num_slots=3, block_size=4,
                           num_blocks=6, prefill_buckets=(8, 64))
    reqs = [(rng.randint(0, VOCAB, 6).astype(np.int32), 2)
            for _ in range(3)]
    ids = [eng.add_request(p, n) for p, n in reqs]
    out = eng.run()
    for (p, n), rid in zip(reqs, ids):
        np.testing.assert_array_equal(np.asarray(out[rid]),
                                      _reference(model, p, n))
    snap = eng.metrics_snapshot()
    stalls = {s["labels"]["path"]: s["value"]
              for s in snap["engine_block_stalls_total"]["series"]}
    assert stalls.get("admit", 0) >= 1
    assert series_total(snap, "engine_block_stalls_total") > 0
    assert series_total(snap, "engine_decode_recompiles_total") == 0
    # pressure showed up as pool saturation at the admission peak
    assert snap["engine_pool_used_high_water_blocks"]["series"][0][
        "value"] == 4
    assert snap["engine_pool_used_blocks"]["series"][0]["value"] == 0

    # the engine registry speaks prometheus end-to-end
    text = eng.metrics.render_prometheus()
    assert "engine_block_stalls_total{path=" in text
    # TTFT is priority-labeled since the QoS tier; buckets append `le`
    assert 'engine_ttft_seconds_bucket{priority="standard",le=' in text
