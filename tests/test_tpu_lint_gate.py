"""Tier-1 tpu-lint gate: the analyzer runs self-clean over the whole
codebase against the committed baseline, the baseline stays small and
justified, the TPU002 rule is cross-checked against REAL retrace
behavior, and importing the analysis package touches no JAX backend.
"""
import importlib.util
import os
import subprocess
import sys
from pathlib import Path

import pytest

import paddle_tpu.analysis as A
from paddle_tpu.analysis.cli import DEFAULT_BASELINE

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = Path(__file__).parent / "fixtures" / "tpu_lint"

GATE_PATHS = [os.path.join(REPO, "paddle_tpu")] + sorted(
    str(p) for p in Path(REPO).glob("bench*.py")) + [
    os.path.join(REPO, "tools")]


@pytest.fixture(scope="module")
def repo_analysis():
    """One analysis of the whole repo shared by the gate assertions."""
    baseline = A.load_baseline(DEFAULT_BASELINE)
    return baseline, A.analyze_paths(GATE_PATHS, baseline=baseline)


def test_repo_is_lint_clean_against_baseline(repo_analysis):
    """THE gate: any non-baselined finding in paddle_tpu/, bench*.py
    or tools/ fails tier-1. Fix the hazard, or (exceptionally) add a
    justified baseline entry."""
    _baseline, res = repo_analysis
    new = res.new_findings()
    assert new == [], "non-baselined tpu-lint findings:\n" + "\n".join(
        f.render() for f in new)
    assert res.parse_errors == []
    # the repo gate must actually cover the codebase, not an empty
    # glob (PR 20 added the analysis/shard tier: 196 files and
    # counting)
    assert len(res.files) > 190


def test_baseline_is_small_and_justified(repo_analysis):
    baseline, res = repo_analysis   # load_baseline raises if unjustified
    assert len(baseline) <= 10, (
        "tpu-lint baseline grew past 10 entries — fix findings instead "
        "of grandfathering them")
    for e in baseline.values():
        assert len(str(e["justification"]).strip()) >= 20, \
            f"baseline justification for {e['id']} is too thin"
    # no stale entries: every baselined id still matches a finding
    assert res.stale_baseline == []


def test_tpu002_rule_models_reality_retrace_crosscheck():
    """Runtime cross-check (ISSUE 4 satellite): the TPU002 fixture's
    flagged python branch really does retrace per operand value under
    count_traces — the rule encodes an observed recompile, not style."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.jit import count_traces, expect_traces

    # the static fixture finding: line 6 is the hazardous branch
    findings, _ = A.analyze_file(str(FIXTURES / "tpu002_pos.py"))
    assert [f.line for f in findings if f.rule == "TPU002"][0] == 6

    spec = importlib.util.spec_from_file_location(
        "tpu002_fixture", str(FIXTURES / "tpu002_pos.py"))
    fixture = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fixture)

    counted = count_traces(fixture.branch_on_operand)
    jf = jax.jit(counted, static_argnums=1)
    x = jnp.ones((4,), jnp.float32)
    with expect_traces(counted, 1):
        jf(x, 1)          # first value of the branched operand
    with expect_traces(counted, 1):
        jf(x, 5)          # second value: the python `if` RETRACES
    with expect_traces(counted, 0):
        jf(x, 5)          # same value: cached, no retrace


def test_analysis_import_has_no_backend_init_and_no_jax_use():
    """Importing + running the analyzer must not initialize a JAX
    backend: it is pure AST work over introspect metadata, safe in
    pre-device CI stages."""
    code = (
        "import paddle_tpu.analysis as A\n"
        "from jax._src import xla_bridge\n"
        "assert not xla_bridge._backends, 'import initialized a backend'\n"
        "src = 'import jax\\n@jax.jit\\ndef f(x):\\n    return float(x)\\n'\n"
        "findings, _ = A.analyze_file('snippet.py', src)\n"
        "assert [f.rule for f in findings] == ['TPU001'], findings\n"
        "assert not xla_bridge._backends, 'analysis touched a backend'\n"
        "print('LINT_SMOKE_OK')\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    res = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "LINT_SMOKE_OK" in res.stdout


def test_eager_collective_registry_matches_distributed_api():
    """introspect.EAGER_COLLECTIVES (what TPU007 checks) must track
    paddle_tpu.distributed's real eager surface."""
    import paddle_tpu.distributed as dist

    from paddle_tpu.jit import introspect

    for name in introspect.EAGER_COLLECTIVES:
        assert callable(getattr(dist, name, None)), \
            f"introspect.EAGER_COLLECTIVES lists `{name}` but " \
            "paddle_tpu.distributed does not export it"


def test_cli_acceptance_command_exits_zero():
    """The ISSUE acceptance command, verbatim."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tpu_lint.py"),
         os.path.join(REPO, "paddle_tpu"),
         os.path.join(REPO, "bench_ops.py"),
         os.path.join(REPO, "tools")],
        env=env, capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "tpu-lint clean" in res.stdout
