"""Tests for VERDICT r1 items: to_static stale params (weak #1), PyLayer
custom autograd (missing #7), leaf register_hook (weak #8)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.jit as jit
from paddle_tpu.autograd import PyLayer


# -- to_static live params ---------------------------------------------------

def test_to_static_sees_param_updates():
    """Regression for VERDICT weak #1: to_static over a Layer must read
    LIVE weights, not trace-time constants."""
    paddle.seed(0)
    layer = nn.Linear(4, 3)
    layer = jit.to_static(layer)
    x = paddle.randn([2, 4])
    out1 = layer(x).numpy()
    # mutate the weight and re-run: output must change
    layer.weight.set_value(layer.weight.numpy() * 2.0)
    out2 = layer(x).numpy()
    assert not np.allclose(out1, out2), "to_static baked stale weights"


def test_to_static_forward_optstep_forward_matches_eager():
    """to_static forward -> opt.step() -> forward == eager sequence."""
    def run(static):
        paddle.seed(1)
        m = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
        if static:
            m = jit.to_static(m)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=m.parameters())
        x = paddle.to_tensor(np.full((2, 4), 0.5, np.float32))
        y = paddle.to_tensor(np.zeros((2, 2), np.float32))
        _ = m(x)
        loss = F.mse_loss(m(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return m(x).numpy()

    np.testing.assert_allclose(run(False), run(True), atol=1e-6)


def test_to_static_bound_method():
    """Decorating a bound forward method also threads live params."""
    paddle.seed(2)
    layer = nn.Linear(3, 3)
    fwd = jit.to_static(layer.forward)
    x = paddle.randn([2, 3])
    out1 = fwd(x).numpy()
    layer.weight.set_value(np.zeros_like(layer.weight.numpy()))
    out2 = fwd(x).numpy()
    np.testing.assert_allclose(out2, np.broadcast_to(layer.bias.numpy(), out2.shape),
                               atol=1e-6)
    assert not np.allclose(out1, out2)


# -- PyLayer -----------------------------------------------------------------

def test_pylayer_custom_tanh_grad():
    class cus_tanh(PyLayer):
        @staticmethod
        def forward(ctx, x):
            y = paddle.tanh(x)
            ctx.save_for_backward(y)
            return y

        @staticmethod
        def backward(ctx, dy):
            (y,) = ctx.saved_tensor()
            return dy * (1 - paddle.square(y))

    x = paddle.to_tensor(np.array([0.3, -0.7, 1.2], np.float32))
    x.stop_gradient = False
    out = cus_tanh.apply(x)
    out.sum().backward()
    expect = 1 - np.tanh(x.numpy()) ** 2
    np.testing.assert_allclose(x.grad.numpy(), expect, rtol=1e-6)


def test_pylayer_double_linear_matches_analytic():
    """PyLayer computing w*x with custom backward; composition through
    surrounding tape ops must match analytic grads."""
    class scale_op(PyLayer):
        @staticmethod
        def forward(ctx, x, w):
            ctx.save_for_backward(x, w)
            return x * w

        @staticmethod
        def backward(ctx, dy):
            x, w = ctx.saved_tensor()
            return dy * w, dy * x

    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    w = paddle.to_tensor(np.array([3.0, 4.0], np.float32))
    x.stop_gradient = False
    w.stop_gradient = False
    y = scale_op.apply(x * 2.0, w)  # y = 2x * w
    (y * y).sum().backward()        # d/dx = 2y*2w = 8xw^2 ; d/dw = 2y*2x=8x^2 w
    np.testing.assert_allclose(x.grad.numpy(), 8 * x.numpy() * w.numpy() ** 2,
                               rtol=1e-5)
    np.testing.assert_allclose(w.grad.numpy(), 8 * x.numpy() ** 2 * w.numpy(),
                               rtol=1e-5)


def test_pylayer_multiple_outputs():
    class split2(PyLayer):
        @staticmethod
        def forward(ctx, x):
            return x * 2.0, x * 3.0

        @staticmethod
        def backward(ctx, d1, d2):
            return d1 * 2.0 + d2 * 3.0

    x = paddle.to_tensor(np.array([1.0, 1.0], np.float32))
    x.stop_gradient = False
    a, b = split2.apply(x)
    (a.sum() + b.sum()).backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0])


def test_pylayer_no_grad_passthrough():
    class ident(PyLayer):
        @staticmethod
        def forward(ctx, x):
            return x + 1.0

        @staticmethod
        def backward(ctx, dy):
            return dy

    x = paddle.to_tensor(np.array([1.0], np.float32))  # stop_gradient=True
    out = ident.apply(x)
    assert out.stop_gradient


# -- leaf hooks --------------------------------------------------------------

def test_leaf_register_hook_fires_and_modifies():
    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    x.stop_gradient = False
    seen = []

    def hook(g):
        seen.append(g.numpy().copy())
        return g * 10.0

    h = x.register_hook(hook)
    (x * 3.0).sum().backward()
    assert len(seen) == 1
    np.testing.assert_allclose(seen[0], [3.0, 3.0])
    np.testing.assert_allclose(x.grad.numpy(), [30.0, 30.0])

    # remove: next backward unmodified
    h.remove()
    x.clear_grad()
    (x * 3.0).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [3.0, 3.0])
    assert len(seen) == 1


def test_leaf_hook_fires_once_with_accumulated_grad():
    """A leaf used by several ops gets ONE hook call with the summed
    gradient (GradNodeAccumulation semantics), not one per contribution."""
    w = paddle.to_tensor(np.array([1.0, 1.0], np.float32))
    w.stop_gradient = False
    calls = []
    w.register_hook(lambda g: calls.append(g.numpy().copy()))
    ((w * 2.0).sum() + (w * 3.0).sum()).backward()
    assert len(calls) == 1, f"hook fired {len(calls)} times"
    np.testing.assert_allclose(calls[0], [5.0, 5.0])


def test_to_static_retraces_on_param_replacement():
    """Layer surgery replacing a Parameter object must retrace, not bind
    into the dead object."""
    paddle.seed(4)
    layer = nn.Linear(3, 2)
    slayer = jit.to_static(layer)
    x = paddle.to_tensor(np.ones((1, 3), np.float32))
    _ = slayer(x)
    import paddle_tpu.core.tensor as T
    new_w = T.Parameter(np.zeros((3, 2), np.float32))
    layer.weight = new_w
    out = slayer(x).numpy()
    np.testing.assert_allclose(out, np.broadcast_to(layer.bias.numpy(), out.shape),
                               atol=1e-6)


def test_intermediate_register_hook_still_works():
    x = paddle.to_tensor(np.array([2.0], np.float32))
    x.stop_gradient = False
    y = x * 4.0
    y.register_hook(lambda g: g * 0.5)
    (y * 3.0).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0])  # 3 * 0.5 * 4
