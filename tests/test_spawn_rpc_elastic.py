"""spawn + rpc + elastic tests (SURVEY items 27/30, VERDICT r2 missing
#8): dist.spawn runs a 2-rank collective, rpc_sync/rpc_async work across
2 launched processes, and the launcher's --max-restarts relaunches a
failed pod.

Reference analogs: python/paddle/distributed/spawn.py,
python/paddle/distributed/rpc/rpc.py, fleet/elastic/manager.py:126.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "launch_worker.py")


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PADDLE_TRAINER_ID", None)
    env.pop("PADDLE_TRAINERS_NUM", None)
    return env


def test_spawn_two_ranks(tmp_path):
    from tests.spawn_workers import allreduce_worker

    import paddle_tpu.distributed as dist

    # spawn from inside the test process: fresh interpreters, cpu backend
    dist.spawn(allreduce_worker, args=(str(tmp_path),), nprocs=2,
               backend="cpu")
    for r in range(2):
        with open(tmp_path / f"rank{r}.json") as f:
            got = json.load(f)
        np.testing.assert_allclose(got, [3.0, 3.0])


def test_spawn_surfaces_rank_failure():
    from tests.spawn_workers import failing_worker

    import paddle_tpu.distributed as dist

    with pytest.raises(RuntimeError, match="boom from a rank"):
        dist.spawn(failing_worker, nprocs=1, backend="cpu")


def test_two_process_rpc():
    res = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nprocs", "2", "--backend", "cpu", WORKER, "rpc"],
        env=_env(), capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, \
        f"stdout:\n{res.stdout}\nstderr:\n{res.stderr}"
    assert res.stdout.count("ok rpc\n") == 2


def test_elastic_restart(tmp_path):
    script = tmp_path / "flaky.py"
    sentinel = tmp_path / "attempted"
    script.write_text(
        "import os, sys\n"
        f"s = {str(sentinel)!r}\n"
        "if not os.path.exists(s):\n"
        "    open(s, 'w').close()\n"
        "    print('first attempt: failing', flush=True)\n"
        "    sys.exit(3)\n"
        "print('second attempt: ok', flush=True)\n")

    # without restarts: pod failure propagates
    res = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nprocs", "1", "--backend", "cpu", str(script)],
        env=_env(), capture_output=True, text=True, timeout=300)
    assert res.returncode != 0
    os.unlink(sentinel)

    # with --max-restarts 1: relaunched and succeeds
    res = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nprocs", "1", "--backend", "cpu", "--max-restarts", "1",
         str(script)],
        env=_env(), capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, \
        f"stdout:\n{res.stdout}\nstderr:\n{res.stderr}"
    assert "restart 1/1" in res.stderr
    assert "second attempt: ok" in res.stdout


def test_elastic_scale_in_resumes_from_checkpoint(tmp_path):
    """VERDICT r3 missing #2: a killed rank triggers a relaunch with
    nprocs-1 (membership change), and the survivors resume training
    from the last checkpoint at the new world size."""
    worker = os.path.join(REPO, "tests", "elastic_worker.py")
    ckpt = str(tmp_path / "ckpt.json")
    sentinel = str(tmp_path / "killed")
    res = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nprocs", "3", "--elastic-min", "2", "--max-restarts", "1",
         "--backend", "cpu", worker, ckpt, sentinel],
        env=_env(), capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, \
        f"stdout:\n{res.stdout}\nstderr:\n{res.stderr}"
    assert "scale-in: relaunching with 2 ranks" in res.stderr
    # exactly the 2 surviving ranks finish, at world=2, resumed mid-run
    done = [l for l in res.stdout.splitlines() if "ELASTIC_DONE" in l]
    assert len(done) == 2, res.stdout
    for line in done:
        assert "world=2" in line, line
        assert "resumed_from=6" in line, line
    with open(ckpt) as f:
        final = json.load(f)
    assert final == {"step": 10, "world": 2}


def test_elastic_master_membership_leases():
    """Unit: the KV registry's TTL leases (manager.py:254-267 analog) —
    an unheartbeated external member expires, a heartbeated one stays,
    clear_owned drops only launcher-owned members."""
    import time

    from paddle_tpu.distributed.launch.elastic import (
        ElasticAgent, ElasticClient, ElasticMaster,
    )

    m = ElasticMaster()
    try:
        c = ElasticClient(m.endpoint)
        c.register("ghost", ttl=0.4)          # never heartbeats
        agent = ElasticAgent(m.endpoint, "alive", ttl=0.4)
        m.register("rank0")                    # launcher-owned
        time.sleep(1.0)
        live = m.live()
        assert "ghost" not in live             # lease expired
        assert "alive" in live                 # heartbeats refresh it
        assert live["alive"]["_external"] is True
        assert live["rank0"]["_external"] is False
        m.clear_owned()
        live = m.live()
        assert "rank0" not in live and "alive" in live
        agent.stop()
        assert "alive" not in m.live()         # leave on stop
    finally:
        m.close()


def test_elastic_true_survivor_count_two_rank_loss(tmp_path):
    """VERDICT r4 next #1 (scale-in): SIGKILL 2 of 4 ranks at once ->
    the relaunch uses the ACTUAL survivor count (nprocs=2, not 4-1=3)
    and the survivors resume from the checkpoint."""
    worker = os.path.join(REPO, "tests", "elastic_worker.py")
    ckpt = str(tmp_path / "ckpt.json")
    sentinel = str(tmp_path / "killed")
    res = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nprocs", "4", "--elastic-min", "2", "--max-restarts", "1",
         "--backend", "cpu", worker, ckpt, sentinel, "2"],
        env=_env(), capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, \
        f"stdout:\n{res.stdout}\nstderr:\n{res.stderr}"
    assert "scale-in: relaunching with 2 ranks" in res.stderr, res.stderr
    done = [l for l in res.stdout.splitlines() if "ELASTIC_DONE" in l]
    assert len(done) == 2, res.stdout
    for line in done:
        assert "world=2" in line and "resumed_from=6" in line, line
    with open(ckpt) as f:
        assert json.load(f) == {"step": 10, "world": 2}


def test_elastic_rejoin_scale_out(tmp_path):
    """VERDICT r4 next #1 (scale-out): after the 2-rank loss scales the
    pod in to 2, a recovered host registers with the membership master
    and the next restart boundary runs at nprocs=3."""
    worker = os.path.join(REPO, "tests", "elastic_scaleout_worker.py")
    ckpt = str(tmp_path / "ckpt.json")
    sentinel = str(tmp_path / "killed")
    res = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nprocs", "4", "--elastic-min", "2", "--max-restarts", "2",
         "--backend", "cpu", worker, ckpt, sentinel],
        env=_env(), capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, \
        f"stdout:\n{res.stdout}\nstderr:\n{res.stderr}"
    assert "scale-in: relaunching with 2 ranks" in res.stderr, res.stderr
    assert "membership grew: restarting for scale-out" in res.stderr, \
        res.stderr
    assert "scale-out: relaunching with 3 ranks" in res.stderr, res.stderr
    done = [l for l in res.stdout.splitlines() if "ELASTIC_DONE" in l]
    assert len(done) == 3, res.stdout
    for line in done:
        assert "world=3" in line and "resumed_from=8" in line, line
    with open(ckpt) as f:
        assert json.load(f) == {"step": 10, "world": 3}


def test_elastic_resize_consumes_only_absorbed_joiners():
    """ADVICE r5 #5: when the elastic_max clamp (or an unchanged world
    size) absorbs only some external joiners, the rest keep their TTL
    leases — their agents stay registered and they rejoin at a LATER
    restart boundary instead of silently retiring."""
    import argparse

    from paddle_tpu.distributed.launch.elastic import ElasticMaster
    from paddle_tpu.distributed.launch.main import _elastic_resize

    def _args(nprocs, emin, emax):
        return argparse.Namespace(nprocs=nprocs, nnodes=1,
                                  nprocs_per_node=None,
                                  elastic_min=emin, elastic_max=emax)

    m = ElasticMaster()
    try:
        # 2 launcher-owned survivors + 3 external joiners, ceiling 4:
        # only TWO joiners fit the new world (4 - 2 survivors)
        m.register("rank0")
        m.register("rank1")
        for j in ("joinA", "joinB", "joinC"):
            m.register(j, ttl=60)                     # TTL = external
        args = _args(nprocs=2, emin=2, emax=4)
        _elastic_resize(args, m)
        assert args.nprocs == 4                       # scaled out to max
        joiners_left = sorted(j for j, info in m.live().items()
                              if info.get("_external"))
        assert joiners_left == ["joinC"]              # lease intact

        # a later boundary with headroom absorbs the leftover joiner
        args2 = _args(nprocs=4, emin=2, emax=8)
        _elastic_resize(args2, m)
        assert args2.nprocs == 3                      # 2 owned + joinC
        assert not [j for j, info in m.live().items()
                    if info.get("_external")]

        # new == current with a joiner replacing lost capacity: the
        # joiner IS absorbed (its capacity relaunches as a local rank)
        m.clear_owned()
        m.register("rank0")                            # 1 survivor
        m.register("late", ttl=60)                     # external joiner
        args3 = _args(nprocs=2, emin=1, emax=2)
        _elastic_resize(args3, m)
        assert args3.nprocs == 2                       # unchanged size
        assert "late" not in m.live()                  # but absorbed
    finally:
        m.close()


def test_elastic_registry_token_auth():
    """ADVICE r5: a launcher-generated job token gates wire-level
    register/leave/put; reads stay open for debugging. Tokenless
    masters (direct test use) keep the open behavior."""
    from paddle_tpu.distributed.launch.elastic import (
        ElasticClient, ElasticMaster,
    )

    m = ElasticMaster(token="s3cret")
    try:
        anon = ElasticClient(m.endpoint, token="")
        with pytest.raises(RuntimeError, match="unauthorized"):
            anon.register("rogue", ttl=30)
        with pytest.raises(RuntimeError, match="unauthorized"):
            anon.put("k", "v")

        ok = ElasticClient(m.endpoint, token="s3cret")
        ok.register("good", ttl=30)
        ok.put("k", "v")
        assert "good" in m.live()            # authorized write landed
        # heartbeat is authed too: a rogue replay must not keep a dead
        # member's lease alive (phantom-member resize inflation)
        assert ok.heartbeat("good") is True
        assert anon.heartbeat("good") is False
        assert "rogue" not in m.live()
        # reads are open (the netcat-debuggability contract)
        assert "good" in anon.live()
        assert anon.get("k") == "v"
        # rejected leave must not evict a live member
        with pytest.raises(RuntimeError, match="unauthorized"):
            anon.leave("good")
        assert "good" in m.live()
        ok.leave("good")
        assert "good" not in m.live()

        # env fallback: in-job workers pick the token up implicitly
        os.environ["PADDLE_ELASTIC_TOKEN"] = "s3cret"
        try:
            envc = ElasticClient(m.endpoint)
            envc.register("worker", ttl=30)
            assert "worker" in m.live()
        finally:
            os.environ.pop("PADDLE_ELASTIC_TOKEN", None)
    finally:
        m.close()

    m2 = ElasticMaster()                     # no token: open registry
    try:
        ElasticClient(m2.endpoint).register("anyone", ttl=30)
        assert "anyone" in m2.live()
    finally:
        m2.close()
