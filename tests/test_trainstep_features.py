"""Gradient accumulation + compiled GradScaler tests (VERDICT r2 #22
gradient-merge gap and weak #8 eager-only found_inf).

Reference analogs: fleet/meta_optimizers/gradient_merge_optimizer.py,
python/paddle/amp/grad_scaler.py + amp_optimizer static insertion.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.jit as jit
from paddle_tpu.amp import GradScaler


def _net(seed):
    paddle.seed(seed)
    net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4))
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    return net, opt


def test_grad_accumulation_matches_large_batch():
    rs = np.random.RandomState(0)
    micro = [(rs.randn(8, 8).astype(np.float32),
              rs.randn(8, 4).astype(np.float32)) for _ in range(4)]
    big_x = np.concatenate([m[0] for m in micro])
    big_y = np.concatenate([m[1] for m in micro])

    # reference: one step on the 32-sample batch
    net_a, opt_a = _net(7)
    step_a = jit.TrainStep(net_a, opt_a, F.mse_loss)
    step_a(paddle.to_tensor(big_x), paddle.to_tensor(big_y))

    # gradient merge: 4 micro-steps of 8
    net_b, opt_b = _net(7)
    step_b = jit.TrainStep(net_b, opt_b, F.mse_loss, accumulate_steps=4)
    w0 = np.asarray(net_b[0].weight._array).copy()
    for i, (x, y) in enumerate(micro):
        step_b(paddle.to_tensor(x), paddle.to_tensor(y))
        if i < 3:
            # params untouched until the K-th micro-batch
            np.testing.assert_array_equal(
                np.asarray(net_b[0].weight._array), w0)
    assert opt_b._step_count == 1

    for (ka, va), (kb, vb) in zip(net_a.state_dict().items(),
                                  net_b.state_dict().items()):
        np.testing.assert_allclose(np.asarray(va._array),
                                   np.asarray(vb._array),
                                   rtol=1e-5, atol=1e-6, err_msg=ka)


def test_grad_accumulation_trains():
    rs = np.random.RandomState(1)
    net, opt = _net(3)
    step = jit.TrainStep(net, opt, F.mse_loss, accumulate_steps=2)
    x = paddle.to_tensor(rs.randn(8, 8).astype(np.float32))
    y = paddle.to_tensor(rs.randn(8, 4).astype(np.float32))
    losses = [float(step(x, y)) for _ in range(8)]  # 4 real updates
    assert losses[-1] < losses[0]
    assert opt._step_count == 4


def test_scaler_skips_update_on_overflow():
    net, opt = _net(5)
    # absurd scale: scaled grads overflow fp32 -> found_inf
    scaler = GradScaler(init_loss_scaling=1e38, incr_ratio=2.0,
                        decr_ratio=0.5, decr_every_n_nan_or_inf=1)
    step = jit.TrainStep(net, opt, F.mse_loss, scaler=scaler)
    x = paddle.to_tensor(np.ones((4, 8), np.float32))
    y = paddle.to_tensor(np.full((4, 4), 1e3, np.float32))  # big loss
    w0 = np.asarray(net[0].weight._array).copy()
    step(x, y)
    # update skipped, scale halved
    np.testing.assert_array_equal(np.asarray(net[0].weight._array), w0)
    assert scaler.get_scale() == pytest.approx(0.5e38)


def test_scaler_trains_when_finite():
    net, opt = _net(6)
    scaler = GradScaler(init_loss_scaling=1024.0)
    step = jit.TrainStep(net, opt, F.mse_loss, scaler=scaler)
    rs = np.random.RandomState(2)
    x = paddle.to_tensor(rs.randn(8, 8).astype(np.float32))
    y = paddle.to_tensor(rs.randn(8, 4).astype(np.float32))
    losses = [float(step(x, y)) for _ in range(6)]
    assert losses[-1] < losses[0]
    assert scaler.get_scale() == 1024.0  # no overflow, no decrease

    # parity with an unscaled step: same seed, same data
    net2, opt2 = _net(6)
    step2 = jit.TrainStep(net2, opt2, F.mse_loss)
    losses2 = [float(step2(x, y)) for _ in range(6)]
    np.testing.assert_allclose(losses, losses2, rtol=1e-4, atol=1e-6)


def test_scaler_with_grad_accumulation_parity():
    """VERDICT r3 #7: fp16 loss scaling composed with gradient merge.
    K scaled micro-steps must equal one scaled step on the combined
    batch."""
    rs = np.random.RandomState(3)
    micro = [(rs.randn(8, 8).astype(np.float32),
              rs.randn(8, 4).astype(np.float32)) for _ in range(4)]
    big_x = np.concatenate([m[0] for m in micro])
    big_y = np.concatenate([m[1] for m in micro])

    net_a, opt_a = _net(11)
    step_a = jit.TrainStep(net_a, opt_a, F.mse_loss,
                           scaler=GradScaler(init_loss_scaling=1024.0))
    step_a(paddle.to_tensor(big_x), paddle.to_tensor(big_y))

    net_b, opt_b = _net(11)
    scaler_b = GradScaler(init_loss_scaling=1024.0)
    step_b = jit.TrainStep(net_b, opt_b, F.mse_loss, accumulate_steps=4,
                           scaler=scaler_b)
    w0 = np.asarray(net_b[0].weight._array).copy()
    for i, (x, y) in enumerate(micro):
        step_b(paddle.to_tensor(x), paddle.to_tensor(y))
        if i < 3:
            np.testing.assert_array_equal(
                np.asarray(net_b[0].weight._array), w0)
    assert opt_b._step_count == 1
    assert scaler_b.get_scale() == 1024.0

    for (ka, va), (kb, vb) in zip(net_a.state_dict().items(),
                                  net_b.state_dict().items()):
        np.testing.assert_allclose(np.asarray(va._array),
                                   np.asarray(vb._array),
                                   rtol=1e-5, atol=1e-6, err_msg=ka)


def test_scaler_accumulation_overflow_skips_whole_window():
    """One overflowing micro-step poisons the window: no update, scale
    halved, found_inf reset for the next window."""
    net, opt = _net(12)
    scaler = GradScaler(init_loss_scaling=1e38, decr_every_n_nan_or_inf=1)
    step = jit.TrainStep(net, opt, F.mse_loss, accumulate_steps=2,
                         scaler=scaler)
    w0 = np.asarray(net[0].weight._array).copy()
    x = paddle.to_tensor(np.ones((4, 8), np.float32))
    y = paddle.to_tensor(np.full((4, 4), 1e3, np.float32))
    step(x, y)
    step(x, y)  # window closes here
    np.testing.assert_array_equal(np.asarray(net[0].weight._array), w0)
    assert opt._step_count == 0
    assert scaler.get_scale() == pytest.approx(0.5e38)
    # next window at the halved scale trains normally
    rs = np.random.RandomState(4)
    x2 = paddle.to_tensor(rs.randn(4, 8).astype(np.float32))
    y2 = paddle.to_tensor(rs.randn(4, 4).astype(np.float32))
    scaler._scale = 1024.0  # sane scale for the follow-up window
    step(x2, y2)
    step(x2, y2)
    assert opt._step_count == 1
    assert not np.allclose(np.asarray(net[0].weight._array), w0)
