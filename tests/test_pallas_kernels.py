"""Pallas kernel tests. On CPU the pallas TPU kernels run in interpret
mode or are skipped; the flash router must fall back to XLA and stay
numerically correct either way."""
import numpy as np
import pytest

import paddle_tpu as paddle


def _dense_ref(q, k, v, causal=True):
    import jax
    import jax.numpy as jnp

    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    d = q.shape[-1]
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) / np.sqrt(d)
    if causal:
        S = logits.shape[-1]
        logits = jnp.where(jnp.tril(jnp.ones((S, S), bool)), logits, -1e30)
    p = jax.nn.softmax(logits, -1)
    return np.asarray(jnp.swapaxes(jnp.einsum("bhqk,bhkd->bhqd", p, vh), 1, 2))


def test_flash_router_fallback_matches_dense():
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.flash_attention import flash_attention

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(2, 128, 4, 64).astype(np.float32))
    k = jnp.asarray(rng.randn(2, 128, 4, 64).astype(np.float32))
    v = jnp.asarray(rng.randn(2, 128, 4, 64).astype(np.float32))
    out = np.asarray(flash_attention(q, k, v, causal=True))
    ref = _dense_ref(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-2)


def test_sdpa_routes_and_differentiates():
    """sdpa with causal+TPU-friendly shapes must stay differentiable
    through whichever backend is picked."""
    import paddle_tpu.nn.functional as F

    q = paddle.randn([1, 128, 2, 64])
    q.stop_gradient = False
    out = F.scaled_dot_product_attention(q, q, q, is_causal=True,
                                         training=False)
    out.sum().backward()
    assert q.grad is not None
    assert np.isfinite(q.grad.numpy()).all()


def test_own_pallas_kernel_interpret_mode():
    """Run our kernel in pallas interpret mode on CPU for correctness."""
    import jax
    import jax.numpy as jnp

    import importlib

    fa = importlib.import_module("paddle_tpu.ops.pallas.flash_attention")

    rng = np.random.RandomState(1)
    B, S, H, D = 1, 256, 2, 64
    q = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))

    out = fa.pallas_sdpa_forward(q, k, v, causal=True,
                                 block_q=128, block_k=128, interpret=True)
    ref = _dense_ref(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-3)
