"""Pallas kernel tests. On CPU the pallas TPU kernels run in interpret
mode or are skipped; the flash router must fall back to XLA and stay
numerically correct either way."""
import numpy as np
import pytest

import paddle_tpu as paddle


def _dense_ref(q, k, v, causal=True):
    import jax
    import jax.numpy as jnp

    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    d = q.shape[-1]
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) / np.sqrt(d)
    if causal:
        S = logits.shape[-1]
        logits = jnp.where(jnp.tril(jnp.ones((S, S), bool)), logits, -1e30)
    p = jax.nn.softmax(logits, -1)
    return np.asarray(jnp.swapaxes(jnp.einsum("bhqk,bhkd->bhqd", p, vh), 1, 2))


def test_flash_router_fallback_matches_dense():
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.flash_attention import flash_attention

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(2, 128, 4, 64).astype(np.float32))
    k = jnp.asarray(rng.randn(2, 128, 4, 64).astype(np.float32))
    v = jnp.asarray(rng.randn(2, 128, 4, 64).astype(np.float32))
    out = np.asarray(flash_attention(q, k, v, causal=True))
    ref = _dense_ref(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-2)


def test_sdpa_routes_and_differentiates():
    """sdpa with causal+TPU-friendly shapes must stay differentiable
    through whichever backend is picked."""
    import paddle_tpu.nn.functional as F

    q = paddle.randn([1, 128, 2, 64])
    q.stop_gradient = False
    out = F.scaled_dot_product_attention(q, q, q, is_causal=True,
                                         training=False)
    out.sum().backward()
    assert q.grad is not None
    assert np.isfinite(q.grad.numpy()).all()


def test_causal_cross_length_bottom_right_aligned():
    """causal attention with Sq < Skv (KV-cache continuation) must align
    the mask bottom-right: query i attends keys 0..(Skv-Sq+i). The last
    Sq rows of full self-attention are the reference."""
    import jax.numpy as jnp

    import importlib
    fa = importlib.import_module("paddle_tpu.ops.pallas.flash_attention")

    rng = np.random.RandomState(3)
    B, S, H, D = 1, 128, 2, 64
    q = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    full = np.asarray(fa.flash_attention(q, k, v, causal=True))
    Sq = 32
    part = np.asarray(fa.flash_attention(q[:, -Sq:], k, v, causal=True))
    np.testing.assert_allclose(part, full[:, -Sq:], atol=2e-5)


def test_flash_router_records_path():
    """The router must record which backend each trace used — on CPU that
    is the XLA fallback (and the pallas counter must stay untouched)."""
    import jax.numpy as jnp

    import importlib
    fa = importlib.import_module("paddle_tpu.ops.pallas.flash_attention")

    fa.reset_path_stats()
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(1, 128, 2, 64).astype(np.float32))
    fa.flash_attention(q, q, q, causal=True)
    if fa._on_tpu():
        assert fa.PATH_STATS["pallas"] == 1
    else:
        assert fa.PATH_STATS["xla"] == 1
        assert fa.PATH_STATS["pallas"] == 0


def test_flash_pallas_path_engages_on_tpu():
    """TPU-gated regression for VERDICT r1 weak #2: in a fresh process on
    the real backend, training attention must take the pallas kernel, not
    the dense fallback. Skips when no TPU is reachable."""
    import subprocess
    import sys

    env = {k: v for k, v in __import__("os").environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    code = r"""
import json, warnings
import jax, jax.numpy as jnp
if jax.default_backend() not in ("tpu", "axon") and \
        jax.devices()[0].platform != "tpu":
    print(json.dumps({"skip": True})); raise SystemExit
import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
fa = __import__("importlib").import_module("paddle_tpu.ops.pallas.flash_attention")
fa.reset_path_stats()
with warnings.catch_warnings():
    # a silent fallback would warn -> escalate only that message to error
    warnings.filterwarnings("error",
                            message="pallas flash_attention unavailable.*")
    q = paddle.randn([1, 256, 2, 64])
    q.stop_gradient = False
    out = F.scaled_dot_product_attention(q, q, q, is_causal=True,
                                         training=False)
    out.sum().backward()
print(json.dumps({"skip": False, "stats": fa.PATH_STATS,
                  "grad_finite": bool(np.isfinite(q.grad.numpy()).all())
                  if (np := __import__("numpy")) else None}))
"""
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    import json

    info = json.loads(r.stdout.strip().splitlines()[-1])
    if info.get("skip"):
        pytest.skip("no TPU backend reachable")
    assert info["stats"]["pallas"] >= 1, info
    assert info["stats"]["xla"] == 0, info
    assert info["grad_finite"]


def test_own_pallas_kernel_interpret_mode():
    """Run our kernel in pallas interpret mode on CPU for correctness."""
    import jax
    import jax.numpy as jnp

    import importlib

    fa = importlib.import_module("paddle_tpu.ops.pallas.flash_attention")

    rng = np.random.RandomState(1)
    B, S, H, D = 1, 256, 2, 64
    q = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))

    out = fa.pallas_sdpa_forward(q, k, v, causal=True,
                                 block_q=128, block_k=128, interpret=True)
    ref = _dense_ref(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-3)


def test_shortseq_attention_interpret_fwd_and_grad():
    """The fused encoder kernel (whole-seq per program, single-pass bwd)
    must match dense attention in value AND gradient — interpret mode
    exercises the exact kernel code on CPU."""
    import importlib

    import jax
    import jax.numpy as jnp

    fa = importlib.import_module("paddle_tpu.ops.pallas.flash_attention")

    rng = np.random.RandomState(0)
    B, S, H, D = 2, 256, 3, 64  # BH=6 exercises hb=6 head batching
    q = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))

    out = fa.shortseq_attention(q, k, v, interpret=True)
    ref = _dense_ref(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-3)

    def loss_kernel(q, k, v):
        return jnp.sum(fa.shortseq_attention(q, k, v, interpret=True) ** 2)

    def loss_dense(q, k, v):
        qh, kh, vh = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
        logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) / np.sqrt(D)
        p = jax.nn.softmax(logits, -1)
        o = jnp.swapaxes(jnp.einsum("bhqk,bhkd->bhqd", p, vh), 1, 2)
        return jnp.sum(o ** 2)

    gk = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gk, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-3,
                                   err_msg=f"d{name} mismatch")


def test_shortseq_hb_divisor():
    from paddle_tpu.ops.pallas.flash_attention import _shortseq_hb

    assert _shortseq_hb(768) == 6
    assert _shortseq_hb(8) == 4
    assert _shortseq_hb(7) == 1
    for bh in (2, 3, 4, 6, 12, 768):
        assert bh % _shortseq_hb(bh) == 0


def test_chunked_causal_attention_interpret_fwd_and_grad():
    """The chunked causal decoder kernel (whole head per program,
    prefix-k blocks, single-pass bwd) must match dense causal attention
    in value and gradient — interpret mode runs the kernel on CPU."""
    import importlib

    import jax
    import jax.numpy as jnp

    fa = importlib.import_module("paddle_tpu.ops.pallas.flash_attention")

    rng = np.random.RandomState(0)
    B, S, H, D = 1, 512, 2, 64
    q = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))

    out = fa.chunked_causal_attention(q, k, v, interpret=True)
    ref = _dense_ref(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-3)

    def loss_kernel(q, k, v):
        return jnp.sum(
            fa.chunked_causal_attention(q, k, v, interpret=True) ** 2)

    def loss_dense(q, k, v):
        qh, kh, vh = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
        logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) / np.sqrt(D)
        logits = jnp.where(jnp.tril(jnp.ones((S, S), bool)), logits,
                           -1e30)
        p = jax.nn.softmax(logits, -1)
        o = jnp.swapaxes(jnp.einsum("bhqk,bhkd->bhqd", p, vh), 1, 2)
        return jnp.sum(o ** 2)

    gk = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gk, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-3, err_msg=f"d{name}")


def test_causal_shape_gate():
    from paddle_tpu.ops.pallas.flash_attention import (
        _causal_bq, _shapes_ok_for_causal)

    assert _shapes_ok_for_causal(2048, 2048, 128)   # the GPT shape
    assert _shapes_ok_for_causal(512, 512, 64)
    assert not _shapes_ok_for_causal(2048, 1024, 128)  # cross-attn
    assert not _shapes_ok_for_causal(2048, 2048, 96)   # odd head dim
    assert not _shapes_ok_for_causal(16384, 16384, 128)  # VMEM blowout
    for S in (512, 1024, 2048, 4096):
        bq = _causal_bq(S, 128)
        assert bq and S % bq == 0 and bq >= 128
        assert 10 * bq * S <= 11 * 1024 * 1024


def test_shortseq_attention_key_mask_interpret():
    """The additive key (padding) mask path: masked keys contribute
    nothing, matching dense attention with the same mask — value AND
    gradients."""
    import importlib

    import jax
    import jax.numpy as jnp

    fa = importlib.import_module("paddle_tpu.ops.pallas.flash_attention")

    rng = np.random.RandomState(2)
    B, S, H, D = 2, 256, 3, 64
    q = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    # row 0 pads the last 56 keys, row 1 pads nothing
    km = np.zeros((B, S), np.float32)
    km[0, 200:] = -1e30
    kmj = jnp.asarray(km)

    def dense(q, k, v):
        qh, kh, vh = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
        logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) / np.sqrt(D)
        logits = logits + kmj[:, None, None, :]
        p = jax.nn.softmax(logits, -1)
        return jnp.swapaxes(jnp.einsum("bhqk,bhkd->bhqd", p, vh), 1, 2)

    out = fa.shortseq_attention(q, k, v, key_mask=kmj, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense(q, k, v)),
                               atol=2e-3)

    gk = jax.grad(lambda v: jnp.sum(fa.shortseq_attention(
        q, k, v, key_mask=kmj, interpret=True) ** 2))(v)
    gd = jax.grad(lambda v: jnp.sum(dense(q, k, v) ** 2))(v)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(gd), atol=5e-3)
    # padded keys receive zero dv
    assert np.abs(np.asarray(gk)[0, 200:]).max() == 0.0


def test_paged_decode_attention_interpret_mode():
    """The fused paged-attention decode kernel (ISSUE 3), kernel-tier:
    interpret mode on CPU must match a dense fp64 reference over a
    mixed-depth batch, write the incoming rows into the aliased pools,
    and leave every block outside the written rows untouched."""
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.paged_attention import paged_decode_attention

    L, nb, bs, H, D = 2, 10, 4, 2, 8
    B, maxb = 3, 3
    rng = np.random.RandomState(9)
    kpool = rng.randn(L, nb, bs, H, D).astype(np.float32)
    vpool = rng.randn(L, nb, bs, H, D).astype(np.float32)
    tables = np.zeros((B, maxb), np.int32)
    tables[0, :3] = [1, 2, 3]
    tables[1, :1] = [4]
    tables[2] = 0                       # idle slot: all-null, pos 0
    positions = np.asarray([8, 3, 0], np.int32)  # 8 = block boundary
    q = rng.randn(B, 1, H, D).astype(np.float32)
    kn = rng.randn(B, 1, H, D).astype(np.float32)
    vn = rng.randn(B, 1, H, D).astype(np.float32)

    layer = 1
    out, kp, vp = paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kn), jnp.asarray(vn),
        jnp.asarray(kpool), jnp.asarray(vpool), layer,
        jnp.asarray(tables), jnp.asarray(positions), interpret=True)
    out, kp, vp = (np.asarray(out), np.asarray(kp), np.asarray(vp))

    # fp64 oracle shared with the backend-seam tests (one reference to
    # keep correct); context reassembled by the dense_gather probe
    from paddle_tpu.ops.paged_attention import dense_gather_reference
    from test_paged_attention_backends import _np_step_reference

    for b in range(2):                  # live slots vs fp64 reference
        pos = int(positions[b])
        ctx_k, ctx_v = dense_gather_reference(
            jnp.asarray(kpool), jnp.asarray(vpool), layer, tables[b],
            pos)
        ref = _np_step_reference(q[b], kn[b], vn[b], ctx_k, ctx_v, pos)
        np.testing.assert_allclose(out[b], ref, rtol=2e-5, atol=2e-6)

    # fused writes landed: slot0 at (block 3, row 0), slot1 at
    # (block 4, row 3), idle slot at the null block row 0
    np.testing.assert_array_equal(kp[layer, 3, 0], kn[0, 0])
    np.testing.assert_array_equal(vp[layer, 4, 3], vn[1, 0])
    np.testing.assert_array_equal(kp[layer, 0, 0], kn[2, 0])
    # everything else is byte-identical to the input pools (the other
    # layer plane included: the kernel only touches `layer`)
    mask = np.ones((L, nb, bs), bool)
    for (lay, blk, row) in [(layer, 3, 0), (layer, 4, 3), (layer, 0, 0)]:
        mask[lay, blk, row] = False
    np.testing.assert_array_equal(kp[mask], kpool[mask])
    np.testing.assert_array_equal(vp[mask], vpool[mask])
