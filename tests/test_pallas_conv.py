"""Fused Pallas conv+BN+ReLU suite (ISSUE 14): interpreter-mode
numeric parity vs the dense `lax.conv_general_dilated` composition
across the nine ResNet-50 sweep shapes, the stride/ReLU/padding
matrix, the backend seam (env override, clean stem fallback), the
ConvBNReLU block + resnet50 wiring, inference-time BN folding, and
the CI satellites (import smoke, pending bench rows).

Shapes run at reduced batch: the (hw, cin, cout, k, s) tuple is the
shape CLASS the kernels tile by; batch only scales the grid."""
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.ops.pallas.conv import (
    CONV_PATH_STATS, conv_bn_relu_reference, conv_shapes_supported,
    fused_conv_bn_relu, normalize_conv_padding, reset_conv_path_stats,
    resolve_conv_backend,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the nine ResNet-50 sweep shapes — THE bench_ops table, not a copy
# (a corrected shape there must flow into these parity tests), run
# batch-reduced for the CPU interpreter; "SAME" matches the bench
# rows (asymmetric at stride 2 — the halo edge case rides along)
import bench_ops

SWEEP = list(bench_ops.CONV_SWEEP_SHAPES)
assert len(SWEEP) == 9

# stated numeric budgets (README "Pallas conv suite"): fp32 near-exact
# (only fp32 reduction order differs between the 9-tap implicit GEMM
# and XLA's conv reduction), bf16 inputs within the bench_ops budget
FP32_REL_TOL = 1e-5
BF16_REL_TOL = 0.03


def _rel_err(got, ref):
    g = np.asarray(got, np.float32)
    r = np.asarray(ref, np.float32)
    return np.max(np.abs(g - r)) / max(np.max(np.abs(r)), 1e-6)


def _case(hw, cin, cout, k, s, dtype, n=1, seed=0, padding="SAME"):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(n, hw, hw, cin).astype(np.float32)) \
        .astype(dtype)
    w = jnp.asarray((rng.randn(k, k, cin, cout) * 0.1)
                    .astype(np.float32)).astype(dtype)
    scale = jnp.asarray((rng.rand(cout) + 0.5).astype(np.float32))
    shift = jnp.asarray(rng.randn(cout).astype(np.float32))
    return x, w, scale, shift


def _check(hw, cin, cout, k, s, dtype, tol, relu=True, n=1,
           padding="SAME", seed=0):
    x, w, scale, shift = _case(hw, cin, cout, k, s, dtype, n=n,
                               seed=seed)
    got = fused_conv_bn_relu(x, w, scale, shift, stride=s,
                             padding=padding, relu=relu,
                             interpret=True)
    ref = conv_bn_relu_reference(x, w, scale, shift, stride=s,
                                 padding=padding, relu=relu)
    assert got.shape == ref.shape
    err = _rel_err(got, ref)
    assert err <= tol, f"rel err {err:.2e} > {tol}"
    return got


@pytest.mark.parametrize("name,hw,cin,cout,k,s", SWEEP,
                         ids=[r[0] for r in SWEEP])
def test_sweep_shape_parity_fp32(name, hw, cin, cout, k, s):
    """Acceptance: every sweep shape, fused vs the dense composition,
    fp32 under the CPU interpreter."""
    _check(hw, cin, cout, k, s, jnp.float32, FP32_REL_TOL)


@pytest.mark.parametrize("name,hw,cin,cout,k,s", SWEEP,
                         ids=[r[0] for r in SWEEP])
def test_sweep_shape_parity_bf16(name, hw, cin, cout, k, s):
    """bf16 inputs / fp32 accumulation, within the stated budget."""
    _check(hw, cin, cout, k, s, jnp.bfloat16, BF16_REL_TOL)


@pytest.mark.parametrize("k,cin,cout", [(1, 32, 64), (3, 32, 32)])
@pytest.mark.parametrize("s", [1, 2])
@pytest.mark.parametrize("relu", [True, False])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_stride_relu_dtype_matrix(k, cin, cout, s, relu, dtype):
    """Both kernel families x stride {1,2} x {with,without ReLU} x
    {fp32, bf16} at a small shape — the cross product the sweep rows
    fix at their native stride."""
    tol = FP32_REL_TOL if dtype == jnp.float32 else BF16_REL_TOL
    _check(16, cin, cout, k, s, dtype, tol, relu=relu, n=2)


@pytest.mark.slow
@pytest.mark.parametrize("name,hw,cin,cout,k,s", SWEEP,
                         ids=[r[0] for r in SWEEP])
@pytest.mark.parametrize("relu", [True, False])
def test_sweep_full_stride_matrix(name, hw, cin, cout, k, s, relu):
    """The full sweep x stride x ReLU cross product (3x3 shapes at
    both strides; 1x1 at stride 2 exercises the downsample slice)."""
    for stride in (1, 2):
        if k == 1 and stride == 2 and hw % 2:
            continue
        _check(hw, cin, cout, k, stride, jnp.float32, FP32_REL_TOL,
               relu=relu)


def test_padding_conventions_and_halos():
    """Symmetric paddle padding=1 vs asymmetric "SAME" at stride 2
    sample DIFFERENT input grids — both must match the dense foil
    (the border-halo rows/cols are where a wrong slab DMA shows)."""
    for padding in (1, "SAME", ((1, 1), (1, 1)), ((0, 1), (0, 1))):
        _check(14, 16, 16, 3, 2, jnp.float32, FP32_REL_TOL,
               padding=padding)
    # tiny image: every output pixel touches the halo
    _check(4, 16, 16, 3, 1, jnp.float32, FP32_REL_TOL, padding=1)
    assert normalize_conv_padding("SAME", (3, 3), (2, 2),
                                  in_hw=(56, 56)) == ((0, 1), (0, 1))
    assert normalize_conv_padding(1, (3, 3), (1, 1)) == ((1, 1), (1, 1))


def test_odd_row_count_pads_matmul_tile():
    """M = N*Ho*Wo with no pow2 divisor (the c5 7x7 grid at small
    batch) rides the zero-padded row tile and slices back exactly."""
    _check(7, 16, 24, 1, 1, jnp.float32, FP32_REL_TOL, n=2)


def test_unsupported_shapes_rejected_and_resolve_falls_back():
    """The 7x7/s2 stem (and grouped/dilated/ragged-channel convs)
    resolve `dense` cleanly whatever backend was requested; calling
    the kernel directly on such a shape is a loud ValueError."""
    assert not conv_shapes_supported((7, 7), (2, 2), 3, 64)
    assert not conv_shapes_supported((3, 3), (1, 1), 60, 64)
    assert not conv_shapes_supported((3, 3), (1, 1), 64, 64, groups=2)
    assert not conv_shapes_supported((3, 3), (1, 1), 64, 64,
                                     dilation=2)
    assert not conv_shapes_supported((3, 3), (3, 3), 64, 64)
    assert not conv_shapes_supported((1, 1), (1, 1), 64, 64,
                                     padding=1)
    assert conv_shapes_supported((1, 1), (2, 2), 64, 256)
    assert resolve_conv_backend("pallas", kernel=(7, 7), stride=(2, 2),
                                in_channels=3, out_channels=64,
                                padding=3) == "dense"
    with pytest.raises(ValueError, match="dense composition"):
        x = jnp.zeros((1, 16, 16, 3))
        w = jnp.zeros((7, 7, 3, 64))
        fused_conv_bn_relu(x, w, jnp.ones(64), jnp.zeros(64),
                           stride=2, padding=3, interpret=True)
    with pytest.raises(ValueError, match="backend"):
        resolve_conv_backend("mxu")


def test_untileable_geometry_falls_back_dense_at_forward():
    """Code-review regression: a resolved-pallas block hitting a 3x3
    geometry the kernel cannot tile (here 17 row tiles > the unroll
    bound) must run the dense composition at forward — never raise
    mid-model — and the dense dispatch must be counted (the
    'never a silent fallback' contract covers BOTH paths)."""
    import paddle_tpu.nn as nn
    from paddle_tpu.ops.pallas.conv import conv_geometry_tileable

    assert not conv_geometry_tileable(3, 1, 1, in_hw=(34, 34))
    assert conv_geometry_tileable(3, 1, 1, in_hw=(32, 32))
    assert conv_geometry_tileable(1, 1, 0, in_hw=(34, 34))

    paddle.seed(0)
    blk_p = nn.ConvBNReLU(8, 8, 3, padding=1, backend="pallas")
    paddle.seed(0)
    blk_d = nn.ConvBNReLU(8, 8, 3, padding=1, backend="dense")
    blk_p.eval()
    blk_d.eval()
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(1, 8, 34, 34).astype(np.float32))
    reset_conv_path_stats()
    out = blk_p(x)                        # must not raise
    assert CONV_PATH_STATS == {"dense": 1, "pallas": 0,
                               "dense_train": 0, "pallas_train": 0}
    np.testing.assert_array_equal(out.numpy(), blk_d(x).numpy())


def test_backend_env_override_wins(monkeypatch):
    """PADDLE_CONV_BACKEND beats the constructor argument (deploy
    semantics, the paged-attention seam contract) — both directions —
    and resolution happens ONCE at construction."""
    import paddle_tpu.nn as nn

    monkeypatch.setenv("PADDLE_CONV_BACKEND", "dense")
    blk = nn.ConvBNReLU(16, 16, 3, padding=1, backend="pallas")
    assert blk.backend == "dense"
    monkeypatch.setenv("PADDLE_CONV_BACKEND", "pallas")
    blk = nn.ConvBNReLU(16, 16, 3, padding=1, backend="dense")
    assert blk.backend == "pallas"
    monkeypatch.delenv("PADDLE_CONV_BACKEND")
    assert nn.ConvBNReLU(16, 16, 3, padding=1).backend == "dense"  # auto, CPU
    # the stem shape falls back whatever the env says
    monkeypatch.setenv("PADDLE_CONV_BACKEND", "pallas")
    stem = nn.ConvBNReLU(3, 64, 7, stride=2, padding=3)
    assert stem.backend == "dense"


def test_convbnrelu_block_parity_and_training_path():
    """The block contract: eval forward fused == dense composition
    within budget; train forward dispatches the fused custom_vjp op
    (ISSUE 16 — counted under `pallas_train`, matching the dense
    composition within the fp32 budget); gradients flow."""
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F

    paddle.seed(0)
    blk_p = nn.ConvBNReLU(16, 32, 3, padding=1, backend="pallas")
    paddle.seed(0)
    blk_d = nn.ConvBNReLU(16, 32, 3, padding=1, backend="dense")
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(2, 16, 8, 8).astype(np.float32))
    blk_p.eval()
    blk_d.eval()
    reset_conv_path_stats()
    out_p = blk_p(x)
    assert CONV_PATH_STATS["pallas"] == 1
    out_d = blk_d(x)
    assert _rel_err(out_p.numpy(), out_d.numpy()) <= FP32_REL_TOL
    assert out_p.stop_gradient      # fused path is forward-only

    # train mode: the pallas block runs the fused training op, the
    # dense block keeps the composition — numerics within budget
    blk_p.train()
    blk_d.train()
    reset_conv_path_stats()
    t_p = blk_p(x)
    assert CONV_PATH_STATS["pallas_train"] == 1, \
        "pallas-resolved block must dispatch the fused train op"
    t_d = blk_d(x)
    assert CONV_PATH_STATS["dense_train"] == 1
    assert _rel_err(t_p.numpy(), t_d.numpy()) <= FP32_REL_TOL
    loss = (t_p * t_p).mean()
    loss.backward()
    assert blk_p.conv.weight.grad is not None
    # act=None block (the bn3/downsample shape)
    blk = nn.ConvBNReLU(16, 16, 1, act=None, backend="pallas")
    blk.eval()
    out = blk(x)
    assert float(out.min()) < 0  # no ReLU applied
    with pytest.raises(ValueError, match="act"):
        nn.ConvBNReLU(8, 8, 3, act="gelu")


def test_resnet50_forward_uses_fused_seam():
    """Acceptance: resnet50 eval forward through the fused backend
    matches the dense backend, with every bottleneck conv dispatching
    through the Pallas kernels (the stem stays dense by design)."""
    from paddle_tpu.vision.models import resnet50

    paddle.seed(0)
    m_d = resnet50(num_classes=10)
    paddle.seed(0)
    m_p = resnet50(num_classes=10, conv_backend="pallas")
    m_d.eval()
    m_p.eval()
    x = paddle.to_tensor(np.random.RandomState(1)
                         .uniform(-1, 1, (2, 3, 32, 32))
                         .astype(np.float32))
    ref = m_d(x).numpy()
    reset_conv_path_stats()
    got = m_p(x).numpy()
    # 16 blocks x 3 convs + 4 downsamples = 52 fused dispatches
    assert CONV_PATH_STATS["pallas"] == 52
    assert _rel_err(got, ref) <= 1e-4


def test_bn_folding_exact_on_resnet50_eval():
    """ISSUE satellite: fold BatchNorm into conv weights/bias for eval
    and prove the resnet50 eval forward unchanged (up to the one
    folded-weight rounding)."""
    import paddle_tpu.nn as nn
    from paddle_tpu.vision.models import resnet50

    paddle.seed(0)
    m = resnet50(num_classes=10)
    m.eval()
    x = paddle.to_tensor(np.random.RandomState(2)
                         .uniform(-1, 1, (2, 3, 32, 32))
                         .astype(np.float32))
    ref = m(x).numpy()
    folded = nn.fuse_conv_bn(m)
    # 16 blocks x 3 + 4 downsamples + the stem conv1/bn1 pair
    assert folded == 53
    got = m(x).numpy()
    assert _rel_err(got, ref) <= 1e-5
    # idempotent: a second pass finds nothing left to fold
    assert nn.fuse_conv_bn(m) == 0


def test_fold_bn_into_conv_with_existing_bias():
    """Folding must scale a pre-existing conv bias into the shift."""
    import paddle_tpu.nn as nn

    paddle.seed(0)
    conv = nn.Conv2D(8, 8, 3, padding=1)          # bias ON
    bn = nn.BatchNorm2D(8)
    bn._mean.set_value(np.random.RandomState(3).randn(8)
                       .astype(np.float32))
    bn._variance.set_value((np.random.RandomState(4).rand(8) + 0.5)
                           .astype(np.float32))
    conv.eval()
    bn.eval()
    x = paddle.to_tensor(np.random.RandomState(5)
                         .randn(2, 8, 8, 8).astype(np.float32))
    ref = bn(conv(x)).numpy()
    nn.fold_bn_into_conv(conv, bn)
    got = conv(x).numpy()
    assert _rel_err(got, ref) <= 1e-5


def test_conv_kernel_import_has_no_backend_init():
    """Importing the kernel module must not initialize a JAX backend
    (the paged-attention smoke precedent): nn/fused.py imports it at
    block construction on serving hosts."""
    code = (
        "import paddle_tpu.ops.pallas.conv as ck\n"
        "from jax._src import xla_bridge\n"
        "assert not xla_bridge._backends, 'backend initialized'\n"
        "assert callable(ck.fused_conv_bn_relu)\n"
        "assert ck.resolve_conv_backend('dense') == 'dense'\n"
        "print('SMOKE_OK')\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PADDLE_CONV_BACKEND", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    res = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "SMOKE_OK" in res.stdout


def test_new_bench_rows_registered_and_pending():
    """The ISSUE-14 eval rows and ISSUE-16 training rows are in the
    suite (so a TPU run measures them) and stay --pending until a
    `--save` refresh adopts them."""
    import bench_ops

    names = bench_ops.suite_names()
    assert "conv_fused_sweep" in names
    assert "resnet50_fused_block" in names
    assert "conv_fused_bwd_sweep" in names
    assert "resnet50_fused_block_train" in names

    res = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "check_bench_result.py"),
         "--pending", os.path.join(REPO, "OPBENCH.json")],
        capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "PENDING: conv_fused_sweep" in res.stdout
    assert "PENDING: resnet50_fused_block" in res.stdout
    assert "PENDING: conv_fused_bwd_sweep" in res.stdout
    assert "PENDING: resnet50_fused_block_train" in res.stdout


def test_bench_runners_tiny():
    """Both lazy bench runners execute end-to-end at tiny shapes with
    their in-runner tolerance asserts live."""
    import bench_ops

    rec = bench_ops._conv_fused_sweep_case(
        shapes=(("conv_c2_1x1_64_256", 8, 16, 32, 1, 1),
                ("conv_c4_3x3_256_s2", 8, 16, 16, 3, 2)), batch=2)()
    assert set(rec["shapes"]) == {"conv_c2_1x1_64_256",
                                  "conv_c4_3x3_256_s2"}
    for curves in rec["shapes"].values():
        assert curves["rel_err"] <= bench_ops.CONV_FUSED_REL_TOL
    rec = bench_ops._resnet50_fused_block_case(batch=2, hw=8,
                                               inplanes=32, planes=8)()
    assert rec["rel_err"] <= bench_ops.CONV_FUSED_REL_TOL
    assert rec["dense_ms"] > 0 and rec["ms"] > 0
