"""PS service tier (VERDICT r3 missing #1): standalone table servers +
sync/async/geo communicator, launched 2-trainer + 2-server through the
launcher CLI.

Reference analogs: paddle/fluid/distributed/ps/service/brpc_ps_server.h,
python/paddle/distributed/communicator.py, the_one_ps.py
init_server/run_server, launch --servers.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "ps_service_worker.py")


def _launch_ps(mode, out_file, nprocs=2, servers=2, timeout=600):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    for k in ("PADDLE_TRAINER_ID", "PADDLE_TRAINERS_NUM",
              "TRAINING_ROLE", "PADDLE_PSERVER_ID", "PADDLE_PSERVER_NUM"):
        env.pop(k, None)
    args = [sys.executable, "-m", "paddle_tpu.distributed.launch",
            "--nprocs", str(nprocs), "--servers", str(servers),
            "--backend", "cpu", WORKER, mode, out_file]
    return subprocess.run(args, env=env, capture_output=True, text=True,
                          timeout=timeout)


def _run_mode(mode, tmp_path):
    out = str(tmp_path / f"ps_{mode}")
    res = _launch_ps(mode, out)
    assert res.returncode == 0, \
        f"stdout:\n{res.stdout}\nstderr:\n{res.stderr}"
    assert res.stdout.count("TRAINER_DONE") == 2
    assert res.stdout.count("SERVER_DONE") == 2
    results = []
    for tid in range(2):
        with open(f"{out}.{tid}") as f:
            results.append(json.load(f))
    return results


def test_ps_service_sync_trains(tmp_path):
    results = _run_mode("sync", tmp_path)
    for r in results:
        assert r["losses"][-1] < 0.45, r["losses"][-5:]
        assert r["losses"][-1] < r["losses"][0]
        # rows really live on the servers and are checkpointable
        assert r["touched"] > 0
        assert r["state_rows"] == r["touched"]


def test_ps_service_async_matches_sync(tmp_path):
    """a_sync communicator: same task converges to a comparable loss
    (bounded staleness, disjoint id slices per trainer)."""
    sync = _run_mode("sync", tmp_path)
    async_ = _run_mode("async", tmp_path)
    for rs, ra in zip(sync, async_):
        assert ra["losses"][-1] < 0.45, ra["losses"][-5:]
        assert abs(ra["losses"][-1] - rs["losses"][-1]) < 0.15, \
            (rs["losses"][-1], ra["losses"][-1])


def test_ps_service_geo_trains(tmp_path):
    results = _run_mode("geo", tmp_path)
    for r in results:
        # geo ships merged deltas every k steps: slower but converging
        assert r["losses"][-1] < r["losses"][0] * 0.8, r["losses"][-5:]


def test_communicator_geo_merges_locally():
    """Unit: geo mode accumulates per-id deltas and ships every k_steps
    pushes as ONE merged push (transport injected, no servers)."""
    from paddle_tpu.distributed.ps import Communicator

    sent = []

    class FakeClient:
        dim = 2

        def push_direct(self, ids, grads, wait=True):
            sent.append((np.asarray(ids).copy(), np.asarray(grads).copy()))

    comm = Communicator(mode="geo", k_steps=3)
    comm.bind(FakeClient())
    g = np.ones((2, 2), np.float32)
    comm.push(np.array([1, 2]), g)
    comm.push(np.array([2, 3]), g)
    assert sent == []  # nothing shipped before k_steps
    comm.push(np.array([1, 2]), g)
    assert len(sent) == 1
    ids, grads = sent[0]
    merged = dict(zip(ids.tolist(), grads.tolist()))
    np.testing.assert_allclose(merged[1], [2.0, 2.0])  # 2 pushes
    np.testing.assert_allclose(merged[2], [3.0, 3.0])  # 3 pushes
    np.testing.assert_allclose(merged[3], [1.0, 1.0])
    comm.push(np.array([5]), np.ones((1, 2), np.float32))
    comm.flush()  # remainder ships on flush
    assert len(sent) == 2


def test_communicator_async_flush_drains():
    from paddle_tpu.distributed.ps import Communicator

    import threading
    import time

    sent = []
    gate = threading.Event()

    class SlowClient:
        dim = 1

        def push_direct(self, ids, grads, wait=True):
            gate.wait(5)
            sent.append(len(ids))

    comm = Communicator(mode="async", queue_size=8)
    comm.bind(SlowClient())
    for _ in range(4):
        comm.push(np.array([1]), np.ones((1, 1), np.float32))
    assert sent == []  # drain thread blocked at the gate
    gate.set()
    comm.flush()
    assert sum(sent) == 4
    comm.stop()


def test_ps_service_ssd_tier_trains_and_spills(tmp_path):
    """Servers with the disk-spill tier (ssd_sparse_table.h analog):
    wide&deep still converges, rows really spill to disk, and the
    checkpoint covers hot+cold rows."""
    results = _run_mode("ssd", tmp_path)
    for r in results:
        assert r["losses"][-1] < 0.45, r["losses"][-5:]
        assert r["stats"]["disk_rows"] > 0, r["stats"]
        assert r["stats"]["mem_rows"] <= 2 * 64  # 2 servers x budget
        assert r["state_rows"] == r["touched"]


def test_ps_service_deepfm_trains(tmp_path):
    """VERDICT r4 next #10: DeepFM through the same 2-trainer +
    2-server launcher path as wide&deep (BASELINE row 5's
    'wide&deep/DeepFM' wording)."""
    results = _run_mode("deepfm", tmp_path)
    for r in results:
        assert r["losses"][-1] < 0.45, r["losses"][-5:]
        assert r["losses"][-1] < r["losses"][0]
        assert r["touched"] > 0
        assert r["state_rows"] == r["touched"]


def test_ps_service_graph_table(tmp_path):
    """GraphTableClient through the 2-trainer + 2-server launcher: a
    graph built by BOTH trainers is visible to each (rpc-shard routing
    by id % num_servers), weighted neighbor sampling and cross-trainer
    feature reads work."""
    results = _run_mode("graph", tmp_path)
    for tid, r in enumerate(results):
        assert r["stats"]["nodes"] == 7 and r["stats"]["edges"] == 6
        assert r["stats"]["nshards"] == 2
        # the OTHER trainer's source node links to {99, 110+(1-tid)}
        assert set(r["other_neighbors"]) == {99, 110 + (1 - tid)}
        # sorted global ids {10,11,20,21,99,110,111}: window [1,4)
        assert r["graph_window"] == [11, 20, 21]
        # and carries the feature the other trainer wrote
        assert r["other_feat"] == [[float(1 - tid), 1.0]]
