"""Observability subsystem tests (ISSUE 2 tentpole): metrics registry
semantics, Prometheus exposition golden + round-trip, loopback-only
/metrics endpoint on an ephemeral port, exact cross-rank snapshot
merges (including a real 4-process fold over the collectives), training
telemetry, the nan/inf event counter, and the hapi MetricsLogger glue.

Reference analogs: the profiler/monitor layers reproduce the span half;
this is the counters/gauges/histograms half serving systems scrape
(Orca/vLLM-style TTFT/TPOT/utilization reporting).
"""
import json
import os
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _registry():
    from paddle_tpu.observability import MetricsRegistry

    return MetricsRegistry()


# -- registry semantics ----------------------------------------------------

def test_counter_gauge_histogram_basics():
    reg = _registry()
    c = reg.counter("reqs_total", "Requests.")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError, match="only go up"):
        c.inc(-1)

    g = reg.gauge("depth", "Depth.")
    g.set(4)
    g.inc()
    g.dec(2)
    assert g.value == 3.0
    g.set_max(10)
    g.set_max(5)                     # high-water keeps the max
    assert g.value == 10.0

    h = reg.histogram("lat_seconds", "Latency.", buckets=(0.1, 1.0))
    for v in (0.05, 0.1, 0.5, 7.0):  # bounds are inclusive
        h.observe(v)
    assert h.count == 4 and h.sum == pytest.approx(7.65)
    snap = reg.snapshot()
    assert snap["lat_seconds"]["series"][0]["counts"] == [2, 1, 1]
    assert snap["reqs_total"]["type"] == "counter"
    assert snap["depth"]["type"] == "gauge"


def test_labeled_series_semantics():
    reg = _registry()
    c = reg.counter("hits_total", "Hits.", labelnames=("verb", "code"))
    c.labels(verb="GET", code=200).inc()
    c.labels("GET", "200").inc()             # same series, positional
    c.labels(verb="PUT", code=500).inc(3)
    snap = reg.snapshot()["hits_total"]
    assert snap["labelnames"] == ["verb", "code"]
    series = {tuple(s["labels"].items()): s["value"]
              for s in snap["series"]}
    assert series[(("verb", "GET"), ("code", "200"))] == 2.0
    assert series[(("verb", "PUT"), ("code", "500"))] == 3.0

    with pytest.raises(ValueError, match="missing label"):
        c.labels(verb="GET")
    with pytest.raises(ValueError, match="takes 2 label"):
        c.labels("GET")
    with pytest.raises(ValueError, match="is labeled"):
        c.inc()                              # labeled family needs labels
    # idempotent re-registration returns the same family...
    assert reg.counter("hits_total", labelnames=("verb", "code")) is c
    # ...and a conflicting declaration is loud
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("hits_total")
    with pytest.raises(ValueError, match="already registered"):
        reg.counter("hits_total", labelnames=("verb",))
    with pytest.raises(ValueError, match="reserved"):
        reg.histogram("h2", labelnames=("le",))
    with pytest.raises(ValueError, match="invalid metric name"):
        reg.counter("bad-name")


def test_prometheus_exposition_golden():
    """Byte-exact golden for the text format the scraper ingests:
    cumulative histogram buckets with +Inf, _sum/_count, labeled
    counter series in sorted order, HELP/TYPE headers."""
    reg = _registry()
    c = reg.counter("requests_total", "Total requests.",
                    labelnames=("verb",))
    c.labels(verb="GET").inc()
    c.labels(verb="GET").inc()
    c.labels(verb="POST").inc(3)
    reg.gauge("pool_utilization", "Used fraction.").set(0.25)
    h = reg.histogram("latency_seconds", "Request latency.",
                      buckets=(0.5, 1.0))
    for v in (0.25, 0.5, 2.0):
        h.observe(v)

    golden = """\
# HELP latency_seconds Request latency.
# TYPE latency_seconds histogram
latency_seconds_bucket{le="0.5"} 2
latency_seconds_bucket{le="1"} 2
latency_seconds_bucket{le="+Inf"} 3
latency_seconds_sum 2.75
latency_seconds_count 3
# HELP pool_utilization Used fraction.
# TYPE pool_utilization gauge
pool_utilization 0.25
# HELP requests_total Total requests.
# TYPE requests_total counter
requests_total{verb="GET"} 2
requests_total{verb="POST"} 3
"""
    assert reg.render_prometheus() == golden


def test_prometheus_round_trip():
    from paddle_tpu.observability import parse_prometheus

    reg = _registry()
    c = reg.counter("c_total", "with \"quotes\" and \\slashes",
                    labelnames=("k",))
    c.labels(k='va"l\\ue').inc(7)
    h = reg.histogram("h_seconds", "hist", buckets=(0.001, 0.1))
    h.observe(0.05)
    text = reg.render_prometheus()
    parsed = parse_prometheus(text)
    assert parsed["types"] == {"c_total": "counter",
                               "h_seconds": "histogram"}
    assert parsed["help"]["c_total"] == 'with "quotes" and \\slashes'
    samples = {(n, tuple(sorted(l.items()))): v
               for n, l, v in parsed["samples"]}
    assert samples[("c_total", (("k", 'va"l\\ue'),))] == 7.0
    assert samples[("h_seconds_bucket", (("le", "0.1"),))] == 1.0
    assert samples[("h_seconds_bucket", (("le", "+Inf"),))] == 1.0
    assert samples[("h_seconds_count", ())] == 1.0
    with pytest.raises(ValueError, match="malformed"):
        parse_prometheus("not a metric line\n")


def test_merge_snapshots_exact_and_quantiles():
    from paddle_tpu.observability import (
        merge_snapshots, quantile_from_buckets, series_total,
    )

    regs = [_registry() for _ in range(3)]
    for i, reg in enumerate(regs):
        c = reg.counter("n_total", "count", labelnames=("kind",))
        c.labels(kind="a").inc(i + 1)
        if i == 2:
            c.labels(kind="b").inc(10)       # series unique to rank 2
        reg.gauge("g", "gauge").set(float(i))
        h = reg.histogram("h_seconds", "hist", buckets=(0.1, 1.0))
        for _ in range(i + 1):
            h.observe(0.05)
        h.observe(5.0)

    merged = merge_snapshots([r.snapshot() for r in regs])
    assert series_total(merged, "n_total") == 1 + 2 + 3 + 10
    g = merged["g"]["series"][0]
    assert (g["min"], g["max"], g["mean"]) == (0.0, 2.0, 1.0)
    assert g["value"] == 1.0 and g["ranks"] == 3
    hs = merged["h_seconds"]["series"][0]
    assert hs["counts"] == [6, 0, 3] and hs["count"] == 9
    assert hs["sum"] == pytest.approx(6 * 0.05 + 3 * 5.0)

    # mismatched bucket bounds refuse to merge (exactness contract)
    bad = _registry()
    bad.histogram("h_seconds", "hist", buckets=(0.2, 2.0)).observe(0.1)
    with pytest.raises(ValueError, match="bucket bounds differ"):
        merge_snapshots([regs[0].snapshot(), bad.snapshot()])

    # quantiles interpolate inside fixed buckets
    assert quantile_from_buckets((1.0, 2.0), [0, 0], 0.5) is None
    assert quantile_from_buckets((1.0, 2.0), [2, 2], 0.25) \
        == pytest.approx(0.5)
    assert quantile_from_buckets((1.0, 2.0), [2, 2], 0.75) \
        == pytest.approx(1.5)
    assert quantile_from_buckets((1.0, 2.0), [0, 1], 1.0) == 2.0


def test_registry_reset_keeps_families_and_handles():
    reg = _registry()
    c = reg.counter("a_total", labelnames=("k",))
    handle = c.labels(k="x")                    # cached hot-path handle
    handle.inc(5)
    h = reg.histogram("h_seconds", buckets=(1.0,))
    h.observe(0.5)
    reg.reset()
    snap = reg.snapshot()
    assert snap["a_total"]["series"][0]["value"] == 0.0  # zeroed...
    assert snap["h_seconds"]["series"][0]["count"] == 0
    handle.inc()          # ...and cached handles STILL feed snapshots
    assert reg.snapshot()["a_total"]["series"][0]["value"] == 1.0


# -- /metrics endpoint -----------------------------------------------------

def test_metrics_server_loopback_ephemeral_port():
    from paddle_tpu.observability import MetricsServer, parse_prometheus

    reg = _registry()
    reg.counter("scraped_total", "Scrapes.").inc(4)
    with MetricsServer(reg) as srv:
        assert srv.port != 0                    # ephemeral, bound
        assert srv.url.startswith("http://127.0.0.1:")
        with urllib.request.urlopen(srv.url, timeout=10) as resp:
            assert resp.headers["Content-Type"].startswith("text/plain")
            text = resp.read().decode()
        parsed = parse_prometheus(text)
        assert ("scraped_total", {}, 4.0) in parsed["samples"]
        with urllib.request.urlopen(srv.url + ".json",
                                    timeout=10) as resp:
            snap = json.load(resp)
        assert snap == reg.snapshot()
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/other", timeout=10)
    with pytest.raises(ValueError, match="loopback-only"):
        MetricsServer(reg, host="0.0.0.0")


def test_observability_import_has_no_device_init_side_effects():
    """Tier-1 smoke: importing the package must not initialize a JAX
    backend (a metrics thread on a serving host must not race device
    init) and must work end-to-end without one."""
    code = (
        "import paddle_tpu.observability as obs\n"
        "from jax._src import xla_bridge\n"
        "assert not xla_bridge._backends, 'backend initialized'\n"
        "r = obs.MetricsRegistry()\n"
        "r.counter('a_total').inc()\n"
        "assert 'a_total 1' in r.render_prometheus()\n"
        "assert not xla_bridge._backends, 'render touched a backend'\n"
        "print('SMOKE_OK')\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    res = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "SMOKE_OK" in res.stdout


# -- distributed aggregation ----------------------------------------------

def test_aggregate_single_process_degenerates():
    from paddle_tpu.observability import aggregate, merge_snapshots

    reg = _registry()
    reg.counter("solo_total").inc(3)
    merged = aggregate(registry=reg)
    assert merged == merge_snapshots([reg.snapshot()])
    assert merged["solo_total"]["series"][0]["value"] == 3.0


def test_aggregate_four_rank_parity(tmp_path):
    """Acceptance: aggregate() over a 4-process group returns exact
    counter sums and exact merged histogram buckets, verified against a
    single-process replay of the same per-rank event traces through
    merge_snapshots. Every rank must also agree on the result (the
    fold is a collective)."""
    from tests.spawn_workers import (
        metrics_aggregate_worker, record_metric_events,
    )

    import paddle_tpu.distributed as dist
    from paddle_tpu.observability import MetricsRegistry, merge_snapshots

    dist.spawn(metrics_aggregate_worker, args=(str(tmp_path),),
               nprocs=4, backend="cpu")

    snaps = []
    for r in range(4):
        reg = MetricsRegistry()
        record_metric_events(reg, r)
        snaps.append(reg.snapshot())
    expected = json.loads(json.dumps(merge_snapshots(snaps),
                                     sort_keys=True))

    for r in range(4):
        with open(tmp_path / f"agg_rank{r}.json") as f:
            got = json.load(f)
        assert got == expected, f"rank {r} merged snapshot diverged"
    # spot-check the exactness the JSON equality already implies
    assert got["w_requests_total"]["series"] == [
        {"labels": {"verb": "GET"}, "value": 10.0},
        {"labels": {"verb": "PUT"}, "value": 4.0},
    ]
    total = sum(3 * (r + 1) for r in range(4))
    assert sum(got["w_latency_seconds"]["series"][0]["counts"]) == total


# -- training telemetry + nan/inf counter ----------------------------------

def test_training_telemetry_and_trainstep_integration():
    import paddle_tpu as paddle
    import paddle_tpu.jit as jit
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.observability import TrainingTelemetry

    reg = _registry()
    tel = TrainingTelemetry(registry=reg, tokens_per_step=64)
    tel.observe_step(0.5, grad_norm=1.25, loss=0.75)
    snap = reg.snapshot()
    assert snap["train_steps_total"]["series"][0]["value"] == 1.0
    assert snap["train_tokens_total"]["series"][0]["value"] == 64.0
    assert snap["train_tokens_per_second"]["series"][0]["value"] == 128.0
    assert snap["train_grad_norm"]["series"][0]["value"] == 1.25
    assert snap["train_loss"]["series"][0]["value"] == 0.75

    # memory watermark gauges ride device/memory.py
    stats = tel.record_memory()
    snap = reg.snapshot()
    kinds = {s["labels"]["kind"]: s["value"]
             for s in snap["train_device_memory_bytes"]["series"]}
    assert kinds["allocated"] == float(stats["allocated_bytes"])
    assert kinds["peak"] >= kinds["allocated"] - 1e-9

    # TrainStep(..., telemetry=...) times real compiled steps
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 8), nn.Tanh(), nn.Linear(8, 4))
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    step = jit.TrainStep(net, opt, F.mse_loss, telemetry=tel)
    rs = np.random.RandomState(0)
    x = paddle.to_tensor(rs.randn(4, 8).astype(np.float32))
    y = paddle.to_tensor(rs.randn(4, 4).astype(np.float32))
    for _ in range(3):
        step(x, y)
    snap = reg.snapshot()
    assert snap["train_steps_total"]["series"][0]["value"] == 4.0
    hist = snap["train_step_seconds"]["series"][0]
    assert hist["count"] == 4 and hist["sum"] > 0
    assert snap["train_loss"]["series"][0]["value"] > 0


def test_nan_inf_event_counter():
    import paddle_tpu as paddle
    from paddle_tpu.observability import get_registry, series_total

    before = series_total(get_registry().snapshot(),
                          "nan_inf_events_total")
    paddle.set_flags({"FLAGS_check_nan_inf": 1})
    try:
        x = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
        with pytest.raises(FloatingPointError):
            x / x                              # 0/0 -> nan
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": 0})
    after = series_total(get_registry().snapshot(),
                         "nan_inf_events_total")
    assert after == before + 1


# -- hapi glue -------------------------------------------------------------

def test_hapi_metrics_logger_callback():
    from paddle_tpu.hapi.callbacks import MetricsLogger

    reg = _registry()
    cb = MetricsLogger(registry=reg)
    cb.on_train_batch_end(0, {"loss": 0.5, "acc": [0.25],
                              "note": "skipme"})
    cb.on_train_batch_end(1, {"loss": 0.4})
    cb.on_epoch_end(0, {"loss": 0.4})
    cb.on_eval_end({"loss": 0.3, "acc": [0.5]})
    snap = reg.snapshot()
    assert snap["hapi_steps_total"]["series"][0]["value"] == 2.0
    assert snap["hapi_epochs_total"]["series"][0]["value"] == 1.0
    loss = {s["labels"]["phase"]: s["value"]
            for s in snap["hapi_loss"]["series"]}
    assert loss == {"train": 0.4, "eval": 0.3}
    acc = {s["labels"]["phase"]: s["value"]
           for s in snap["hapi_acc"]["series"]}
    assert acc == {"train": 0.25, "eval": 0.5}
    assert "hapi_note" not in snap                # non-numeric skipped


# -- bench gate pending detection ------------------------------------------

def test_check_bench_pending_logic(capsys):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import check_bench_result as gate

    base = {"op_a": {"ms": 1.0}}
    path = os.path.join(REPO, "OPBENCH.json")
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as f:
        json.dump(base, f)
        tmp = f.name
    try:
        rc = gate.check_pending(tmp, suite_names=["op_a", "op_b"])
        out = capsys.readouterr().out
        assert rc == 0 and "PENDING: op_b" in out
        rc = gate.check_pending(tmp, suite_names=["op_a", "op_b"],
                                strict=True)
        capsys.readouterr()
        assert rc == 1
        rc = gate.check_pending(tmp, suite_names=["op_a"])
        out = capsys.readouterr().out
        assert rc == 0 and "no pending rows" in out
    finally:
        os.unlink(tmp)
    # the real OPBENCH.json has not adopted the PR-1 engine rows yet:
    # the satellite exists precisely to make that visible
    with open(path) as f:
        real = json.load(f)
    if "gpt_engine_offered_load" not in real:
        rc = gate.check_pending(
            path, suite_names=["gpt_engine_offered_load"])
        out = capsys.readouterr().out
        assert "PENDING: gpt_engine_offered_load" in out
