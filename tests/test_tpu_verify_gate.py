"""Tier-1 tpu-verify gate: every registered compiled engine program,
abstractly traced over the full {dense,pallas} x K in {0,4} x
mp in {1,2} matrix on CPU, passes its declared trace contract and
matches the committed TRACE_BASELINE.json — and the two flagship
rules (TPU101 donation aliasing, TPU104 collective budget) are proven
against deliberately broken programs, so the gate's green is known to
be falsifiable.

conftest forces --xla_force_host_platform_device_count=8, so the REAL
mp=2 shard_map programs trace on a virtual device mesh.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu.analysis.trace as T
from paddle_tpu.jit import introspect

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def matrix_result():
    """One harvest+verify of the full matrix shared by the gate
    assertions (the committed TRACE_BASELINE.json is the default
    drift reference)."""
    return T.verify_matrix()


@pytest.fixture(scope="module")
def tiny_mp2_engine():
    import paddle_tpu as paddle
    from paddle_tpu.inference.engine import GenerationEngine
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    model = GPTForCausalLM(GPTConfig.tiny(vocab=64, hidden=32,
                                          layers=2, heads=4, seq=32))
    model.eval()
    return GenerationEngine(model, num_slots=2, block_size=8,
                            attention_backend="dense", mp_degree=2,
                            donate=True)


def _decode_args(eng):
    S, MB = eng.num_slots, eng.max_blocks
    return (eng._state_arrays(), eng.cache.kpool, eng.cache.vpool,
            jnp.asarray(np.zeros((S, 1), np.int32)),
            jnp.asarray(np.zeros(S, np.int32)),
            jnp.asarray(np.zeros((S, MB), np.int32)))


def test_matrix_is_contract_clean(matrix_result):
    """THE gate: any TPU1xx finding (or TRACE_BASELINE drift) on any
    program of the full config matrix fails tier-1. Fix the program,
    or (exceptionally) add a justified waiver/baseline entry."""
    res = matrix_result
    new = res.new_findings()
    assert new == [], "tpu-verify findings:\n" + "\n".join(
        f.render() for f in new)
    # the matrix must actually cover the serving stack: the 16
    # backend/K/kv-divergent decode/verify steps plus the 12 per-
    # (mp, kv_dtype) backend-invariant programs, every contract seen
    # — the kv=int8 half is the PR-11 quantized serving config (int8
    # per-block-scaled KV pools + int8 weights) — plus the 4 PR-13
    # adapter-threaded programs (LORA_CONFIGS: a plain fp mp=1
    # decode + both prefills, and the composed
    # pallas/K=4/mp=2/int8 verify step) — plus the 4 PR-15
    # sampling-threaded programs (SAMPLING_CONFIGS: a plain fp mp=1
    # sampled decode + both sampled prefills, and the composed
    # pallas/K=4/mp=2/int8 rejection-sampling verify step) — plus the
    # 4 PR-14 fused Pallas conv programs (both kernel families x
    # stride) — plus the 4 PR-16 backward programs (the train-mode
    # custom_vjp grad jaxprs, both families x stride; TPU103 must
    # walk the fused dInput/dWeight kernels too)
    assert len(res.programs) == 44
    assert sum(",int8" in p.config for p in res.programs) == 16
    assert sum(",lora" in p.config for p in res.programs) == 4
    assert sum(",sampling" in p.config for p in res.programs) == 4
    assert sum(p.contract.name.startswith("conv_bn_relu")
               for p in res.programs) == 8
    names = {p.contract.name for p in res.programs}
    assert names == {"engine_decode_step", "engine_verify_step",
                     "engine_prefill", "engine_prefill_chunk",
                     "engine_cow_copy", "conv_bn_relu_1x1",
                     "conv_bn_relu_3x3", "conv_bn_relu_1x1_bwd",
                     "conv_bn_relu_3x3_bwd"}
    assert res.stale_trace_baseline == []


def test_trace_baseline_is_committed_and_exact(matrix_result):
    """The committed TRACE_BASELINE.json matches the live snapshot
    key-for-key and count-for-count (drift would have produced TPU100
    findings above; this pins the file itself)."""
    base = T.load_trace_baseline(T.DEFAULT_TRACE_BASELINE)
    assert base == T.snapshot_of(matrix_result.programs)


def test_engine_consumes_introspect_donation_table(tiny_mp2_engine):
    """ISSUE satellite: donation metadata for the engine steps comes
    from the ONE introspect table both analyzers read — the engine
    must consume it, not restate magic argnums."""
    eng = tiny_mp2_engine
    assert eng._donate_argnums == introspect.ENGINE_STEP_DONATE_ARGNUMS
    for step in ("engine_prefill", "engine_prefill_chunk",
                 "engine_decode_step", "engine_verify_step"):
        assert introspect.ENGINE_STEP_DONATION[step] == \
            introspect.ENGINE_STEP_DONATE_ARGNUMS
        assert T.get_contract(step).donate_argnums == \
            introspect.ENGINE_STEP_DONATION[step]
    assert T.get_contract("engine_cow_copy").donate_argnums == \
        introspect.ENGINE_COW_DONATE_ARGNUMS
    # and the constants resolve through DONATION_CONSTANTS (TPU004)
    assert introspect.DONATION_CONSTANTS[
        "ENGINE_STEP_DONATE_ARGNUMS"] == (1, 2)
    assert introspect.DONATION_CONSTANTS[
        "ENGINE_COW_DONATE_ARGNUMS"] == (0, 1)


def test_tpu101_fires_when_sharded_donation_is_demoted(tiny_mp2_engine):
    """Deliberate contract break #1 (and the regression test for the
    PR's engine fix): lowering the mp=2 decode step WITHOUT the
    engine's explicit out_shardings demotes donate_argnums to
    best-effort `jax.buffer_donor` markers — no pinned aliases, the
    paged pools may silently double. TPU101 must fail that program;
    the engine's own jit (WITH out_shardings) must pass it."""
    eng = tiny_mp2_engine
    args = _decode_args(eng)
    contract = T.get_contract("engine_decode_step")

    def prog_from(lowered_text):
        return T.TracedProgram(
            contract=contract, config="dense,K=0,mp=2", mp=2,
            num_layers=2, jaxpr=jax.make_jaxpr(eng._decode_pure)(*args),
            lowered_text=lowered_text, donated_leaves=2)

    # the pre-fix engine shape: donation declared, out_shardings inferred
    broken = jax.jit(eng._decode_pure,
                     donate_argnums=(1, 2)).lower(*args).as_text()
    assert broken.count("tf.aliasing_output") == 0
    assert broken.count("jax.buffer_donor") == 2
    from paddle_tpu.analysis.trace.rules import check_tpu101

    found = check_tpu101(prog_from(broken))
    assert [f.rule for f in found] == ["TPU101"]
    assert "demoted" in found[0].message

    # the engine's real jit: pinned aliases, rule passes
    fixed = eng._decode.lower(*args).as_text()
    assert fixed.count("tf.aliasing_output") == 2
    assert fixed.count("jax.buffer_donor") == 0
    assert check_tpu101(prog_from(fixed)) == []


def test_tpu104_fires_on_an_extra_all_gather(tiny_mp2_engine):
    """Deliberate contract break #2: one accidental extra all-gather
    appended to the mp=2 decode step busts the declared per-layer
    budget (9 = 4/layer x 2 layers + 1 fixed) and TPU104 says so."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.analysis.trace.rules import check_tpu104

    eng = tiny_mp2_engine
    args = _decode_args(eng)
    contract = T.get_contract("engine_decode_step")

    extra = shard_map(
        lambda t: jax.lax.all_gather(t, "mp", axis=0, tiled=True),
        mesh=eng.mesh, in_specs=(P(),), out_specs=P(),
        check_rep=False)

    def broken_step(*a):
        nxt, kp, vp = eng._decode_pure(*a)
        return extra(nxt)[: nxt.shape[0]], kp, vp

    def prog_from(fn):
        return T.TracedProgram(
            contract=contract, config="dense,K=0,mp=2", mp=2,
            num_layers=2, jaxpr=jax.make_jaxpr(fn)(*args),
            lowered_text="", donated_leaves=0)

    found = check_tpu104(prog_from(broken_step))
    assert [f.rule for f in found] == ["TPU104"]
    assert "all_gather appears 10x" in found[0].message
    assert "allowed 9" in found[0].message
    assert check_tpu104(prog_from(eng._decode_pure)) == []


def test_sharded_cow_step_pins_aliases(tiny_mp2_engine):
    """The COW block-copy donates both sharded pools too — same
    pinned-alias contract as the decode step (the fix covers every
    compiled program, not just the four steps)."""
    eng = tiny_mp2_engine
    low = eng._cow.lower(eng.cache.kpool, eng.cache.vpool,
                         jnp.int32(1), jnp.int32(2)).as_text()
    assert low.count("tf.aliasing_output") == 2
    assert low.count("jax.buffer_donor") == 0


def test_sharded_engine_still_token_exact_after_donation_fix():
    """The out_shardings donation fix must not perturb serving
    results: the mp=2 engine's outputs stay identical to mp=1 on a
    small mixed trace (the PR 8 exactness contract, re-proven over
    the changed jit configuration)."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.engine import GenerationEngine
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    model = GPTForCausalLM(GPTConfig.tiny(vocab=64, hidden=32,
                                          layers=2, heads=4, seq=32))
    model.eval()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 64, size=n).tolist()
               for n in (3, 9, 17)]

    def serve(mp):
        eng = GenerationEngine(model, num_slots=2, block_size=8,
                               attention_backend="dense",
                               mp_degree=mp, donate=True)
        for i, p in enumerate(prompts):
            eng.add_request(p, max_new_tokens=6, req_id=i)
        return eng.run()

    assert serve(1) == serve(2)


def test_harvest_accepts_legacy_matrix_shapes():
    """Pre-sampling callers hold 3/4/5-tuple explicit matrix entries:
    the normalizer must pad the MISSING trailing fields with their
    defaults (kv=None, lora=False, sampling=False) — positional
    slicing once handed a 5-tuple samp=None and tripped the
    PADDLE_SERVE_SAMPLING leak guard on a clean environment."""
    from paddle_tpu.analysis.trace.harvest import harvest

    programs = harvest(matrix=(("dense", 0, 1, None, False),))
    # a dense K=0 mp=1 fp config: decode + both prefills + cow
    assert len(programs) == 4
    assert all(",sampling" not in p.config for p in programs)


def test_cli_acceptance_command_exits_zero():
    """The ISSUE acceptance command, verbatim: the CLI runs the full
    contract matrix self-clean on CPU."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tpu_verify.py")],
        env=env, capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "tpu-verify clean: 44 programs" in res.stdout
