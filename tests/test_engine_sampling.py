"""Probabilistic serving (ISSUE 15): per-slot on-device sampling +
rejection-sampling speculative acceptance.

The contracts, proven the way PRs 7/8/11 proved theirs:

- GREEDY IS BIT-EXACT: a sampling-enabled engine serving
  temperature-0 (or param-less) requests emits token streams
  identical to a sampling-OFF engine across the
  {dense,pallas} x K in {0,4} x mp in {1,2} matrix — and a mixed
  greedy/sampled batch never perturbs its greedy lanes.
- PARAMS ARE DATA: `decode_traces == 1` per (backend, K, mp) for any
  live mix of sampling params, with steady-state `expect_traces(0)`.
- SEEDED RUNS REPLAY: same (seed, trace, config) => same tokens —
  across backends, prefill modes, cold/warm caches, and the
  disaggregated prefill->decode handoff (the slot's key state is a
  pure function of (seed, position), so adoption re-derives it).
- REJECTION SAMPLING PRESERVES THE TARGET DISTRIBUTION: chi-square of
  the device draws against the independent CPU oracle
  (`inference.sampling.oracle_probs`) over >= 10k draws on a tiny
  vocab — for the rejected-draft marginal, the bonus draw, and the
  plain sampled token.
- the `GptDrafter` learned drafter never changes greedy output
  tokens; `best_of_n` seats the shared prompt blocks ONCE.
"""
import dataclasses
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.jit as jit
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.inference import (GenerationEngine, GptDrafter,
                                  NgramDrafter, SamplingParams,
                                  ServingFleet)
from paddle_tpu.inference.sampling import key_row, oracle_probs
from paddle_tpu.observability.metrics import series_total
from paddle_tpu.ops import sampling as sops

VOCAB = 64     # mp=2-divisible (vocab-parallel embedding)


def _model(seed=0, heads=4):
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(seed)
    cfg = GPTConfig.tiny(vocab=VOCAB, hidden=32, layers=2,
                         heads=heads, seq=64)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return _model()


_PROMPTS = [(9, 12), (17, 10), (5, 12), (20, 8)]


def _trace(rng_seed=0):
    rng = np.random.RandomState(rng_seed)
    return [(rng.randint(0, VOCAB, plen).astype(np.int32), max_new)
            for plen, max_new in _PROMPTS]


SAMPLED = SamplingParams(temperature=0.9, top_k=20, top_p=0.95,
                         seed=77)


def _serve(model, trace, params_of, **kw):
    eng = GenerationEngine(model, num_slots=4, block_size=8, **kw)
    ids = [eng.add_request(p, max_new_tokens=mn, req_id=i,
                           sampling_params=params_of(i))
           for i, (p, mn) in enumerate(trace)]
    out = eng.run()
    return [out[i] for i in ids], eng


# -- the greedy bit-exactness matrix ------------------------------------

_MATRIX = [("dense", 0, 1),
           pytest.param("dense", 4, 1, marks=pytest.mark.slow),
           pytest.param("pallas", 4, 1, marks=pytest.mark.slow),
           pytest.param("dense", 0, 2, marks=pytest.mark.slow),
           pytest.param("pallas", 0, 1, marks=pytest.mark.slow),
           pytest.param("pallas", 0, 2, marks=pytest.mark.slow),
           pytest.param("dense", 4, 2, marks=pytest.mark.slow),
           pytest.param("pallas", 4, 2, marks=pytest.mark.slow)]


@pytest.mark.parametrize("backend,k,mp", _MATRIX)
def test_greedy_bit_exact_and_one_trace_per_config(model, backend, k,
                                                   mp):
    """temperature=0 requests on a sampling-enabled engine are
    token-identical to the pre-sampling (sampling=False) engine — in
    an ALL-greedy batch and in a mixed batch whose other lanes sample
    — and one compiled decode program serves the whole mix."""
    trace = _trace()
    kw = dict(attention_backend=backend, spec_decode_k=k,
              mp_degree=mp)
    ref, _ = _serve(model, trace, lambda i: None, **kw)
    all_greedy, eng = _serve(model, trace, lambda i: None,
                             sampling=True, **kw)
    assert all_greedy == ref
    mixed, eng2 = _serve(model, trace,
                         lambda i: SAMPLED if i in (1, 3) else None,
                         sampling=True, **kw)
    assert mixed[0] == ref[0] and mixed[2] == ref[2], \
        "sampled lanes perturbed a greedy lane"
    assert eng.decode_traces == 1
    assert eng2.decode_traces == 1


def test_steady_state_never_retraces(model):
    """Any live param mix reuses the one compiled program: after the
    first mixed run, further mixed traffic traces NOTHING."""
    eng = GenerationEngine(model, num_slots=4, block_size=8,
                           sampling=True, spec_decode_k=2)
    trace = _trace()
    for i, (p, mn) in enumerate(trace):
        eng.add_request(p, mn, sampling_params=SAMPLED if i % 2
                        else None)
    eng.run()
    assert eng.decode_traces == 1 and eng.prefill_traces == 1
    with jit.expect_traces(eng._decode_pure, 0), \
            jit.expect_traces(eng._prefill_pure, 0):
        for i, (p, mn) in enumerate(_trace(1)):
            eng.add_request(
                p, mn, sampling_params=None if i % 2 else
                SamplingParams(temperature=0.4, top_k=3, seed=i))
        eng.run()


# -- seeded reproducibility ---------------------------------------------

@pytest.mark.slow
def test_sampled_streams_reproduce_and_agree_across_paths(model):
    """Same (seed, trace, config) => same tokens; and because draws
    are keyed by (seed, absolute position) on logits both backends
    compute bit-identically, the sampled streams agree across
    dense/pallas, chunked/bucketed prefill, and cold/warm caches."""
    trace = _trace()
    params = lambda i: dataclasses.replace(SAMPLED, seed=100 + i)
    base, _ = _serve(model, trace, params, sampling=True)
    again, _ = _serve(model, trace, params, sampling=True)
    assert again == base
    pallas, _ = _serve(model, trace, params, sampling=True,
                       attention_backend="pallas")
    assert pallas == base
    bucketed, _ = _serve(model, trace, params, sampling=True,
                         prefill_buckets=(32, 64))
    assert bucketed == base
    # warm: the same engine serves the same sampled requests twice —
    # the second pass seats the prompts from the prefix cache and
    # must replay the identical stream (keys are position-pure)
    eng = GenerationEngine(model, num_slots=4, block_size=8,
                           sampling=True)
    ids = [eng.add_request(p, mn, sampling_params=params(i))
           for i, (p, mn) in enumerate(trace)]
    out = eng.run()
    cold = [out[i] for i in ids]
    assert cold == base
    ids = [eng.add_request(p, mn, sampling_params=params(i))
           for i, (p, mn) in enumerate(trace)]
    out = eng.run()
    warm = [out[i] for i in ids]
    assert warm == base
    assert eng.prefix_hit_tokens > 0     # the warm pass actually hit


@pytest.mark.slow
def test_none_seed_resolves_deterministically(model):
    """A None seed draws from the engine's counter: two fresh engines
    serving the same trace produce the same streams (and the resolved
    request carries its seed)."""
    p = SamplingParams(temperature=1.0)
    assert p.seed is None
    one, _ = _serve(model, _trace(), lambda i: p, sampling=True)
    two, _ = _serve(model, _trace(), lambda i: p, sampling=True)
    assert one == two


@pytest.mark.slow
def test_spec_sampled_reproduces_and_preserves_greedy(model):
    """Speculation + sampling: same-seed reproducibility at K=4, and
    the drafter cannot perturb a greedy lane (exact acceptance)."""
    trace = _trace()
    params = lambda i: dataclasses.replace(SAMPLED, seed=50 + i)
    a, enga = _serve(model, trace, params, sampling=True,
                     spec_decode_k=4)
    b, _ = _serve(model, trace, params, sampling=True,
                  spec_decode_k=4)
    assert a == b
    assert enga.decode_traces == 1
    # cross-backend identity holds under speculation too
    c, _ = _serve(model, trace, params, sampling=True,
                  spec_decode_k=4, attention_backend="pallas")
    assert c == a


# -- distribution preservation (the statistical acceptance test) --------

def _chi2_crit(dof):
    """chi-square critical value at alpha=1e-3 (scipy's table — the
    tests are seed-deterministic, so pass/fail never flakes)."""
    from scipy import stats

    return float(stats.chi2.isf(1e-3, dof))


def _chi2(counts, probs, n):
    exp = probs * n
    keep = exp > 0
    assert counts[~keep].sum() == 0, \
        "draws landed on zero-probability tokens"
    return float(((counts[keep] - exp[keep]) ** 2 / exp[keep]).sum()), \
        int(keep.sum()) - 1


N_DRAWS = 20000


def _draw_rows(n=N_DRAWS):
    """n independent per-slot key rows (distinct requests' seeds)."""
    return jnp.asarray(np.asarray(jax.random.split(
        jax.random.PRNGKey(123), n), np.uint32))


def test_rejection_sampling_preserves_target_distribution():
    """The Leviathan guarantee, measured: with a deterministic draft
    token d, the emitted marginal `accept ? d : resample` must equal
    the target distribution p — for a mid-probability d, for a
    top-probability d, and for a d the masking zeroed out. Chi-square
    vs the CPU oracle over 20k device draws on an 8-token vocab."""
    rng = np.random.RandomState(3)
    logits = rng.randn(8).astype(np.float32) * 1.5
    params = SamplingParams(temperature=0.8, top_k=6, top_p=0.92,
                            seed=0)
    p = oracle_probs(logits, params)
    order = np.argsort(-p)
    keys = _draw_rows()
    B = keys.shape[0]
    lg = jnp.asarray(np.tile(logits, (B, 2, 1)))
    temps = jnp.full(B, params.temperature, jnp.float32)
    tks = jnp.full(B, params.top_k, jnp.int32)
    tps = jnp.full(B, params.top_p, jnp.float32)
    dlens = jnp.ones(B, jnp.int32)
    pos = jnp.zeros(B, jnp.int32)
    vw = jax.jit(sops.verify_window)
    for d in (int(order[2]),       # mid-probability draft
              int(order[0]),       # the argmax itself
              int(order[-1])):     # masked out (p == 0): always reject
        tokens = jnp.asarray(
            np.stack([np.zeros(B), np.full(B, d)], axis=1)
            .astype(np.int32))
        choices, accepts = vw(lg, tokens, dlens, temps, tks, tps,
                              keys, pos)
        choices, accepts = np.asarray(choices), np.asarray(accepts)
        emitted = np.where(accepts[:, 0], d, choices[:, 0])
        if p[d] == 0:
            assert not accepts[:, 0].any()
        stat, dof = _chi2(np.bincount(emitted, minlength=8), p, B)
        assert stat < _chi2_crit(dof), \
            (f"draft {d}: chi2={stat:.1f} over dof={dof} exceeds the "
             f"0.001 critical value — distribution not preserved")
        # the bonus draw (row 1 carries no draft) is a plain sample
        # from p, whatever happened at row 0
        stat, dof = _chi2(np.bincount(choices[:, 1], minlength=8), p,
                          B)
        assert stat < _chi2_crit(dof)


def test_sample_token_matches_oracle_distribution():
    """The plain (K=0 decode / prefill first-token) draw: chi-square
    of `sample_token` against the CPU oracle, with masking on."""
    rng = np.random.RandomState(4)
    logits = rng.randn(8).astype(np.float32)
    params = SamplingParams(temperature=1.3, top_k=5, top_p=0.85,
                            seed=0)
    p = oracle_probs(logits, params)
    keys = _draw_rows()
    B = keys.shape[0]
    toks = np.asarray(jax.jit(sops.sample_token)(
        jnp.asarray(np.tile(logits, (B, 1))),
        jnp.full(B, params.temperature, jnp.float32),
        jnp.full(B, params.top_k, jnp.int32),
        jnp.full(B, params.top_p, jnp.float32), keys,
        jnp.zeros(B, jnp.int32)))
    stat, dof = _chi2(np.bincount(toks, minlength=8), p, B)
    assert stat < _chi2_crit(dof)
    # temperature=0 rows are the literal argmax, whatever the knobs
    g = np.asarray(jax.jit(sops.sample_token)(
        jnp.asarray(np.tile(logits, (4, 1))),
        jnp.zeros(4, jnp.float32), jnp.full(4, 2, jnp.int32),
        jnp.full(4, 0.5, jnp.float32), _draw_rows(4),
        jnp.arange(4, dtype=jnp.int32)))
    assert (g == int(np.argmax(logits))).all()


def test_verify_window_greedy_rows_reproduce_equality_contract():
    """Greedy rows of `verify_window`: accepts is exact argmax
    equality on the drafted columns, choices pins the argmax chain —
    the device form of the PR 7 host walk."""
    rng = np.random.RandomState(5)
    lg = jnp.asarray(rng.randn(3, 3, 8).astype(np.float32))
    am = np.asarray(jnp.argmax(lg, axis=-1))
    tokens = np.zeros((3, 3), np.int32)
    tokens[0, 1:] = am[0, :2]          # perfect draft: all accepted
    tokens[1, 1] = (am[1, 0] + 1) % 8  # wrong first draft
    tokens[2, 1:] = am[2, :2]          # drafts beyond dlen ignored
    choices, accepts = sops.verify_window(
        lg, jnp.asarray(tokens), jnp.asarray([2, 2, 0]),
        jnp.zeros(3, jnp.float32), jnp.zeros(3, jnp.int32),
        jnp.ones(3, jnp.float32),
        jnp.zeros((3, 2), jnp.uint32), jnp.zeros(3, jnp.int32))
    choices, accepts = np.asarray(choices), np.asarray(accepts)
    assert (choices == am).all()
    assert accepts[0].tolist() == [True, True, False]
    assert accepts[1].tolist() == [False, False, False]
    assert accepts[2].tolist() == [False, False, False]  # dlen = 0


# -- best_of_n ----------------------------------------------------------

def test_best_of_n_shares_prompt_blocks_once(model):
    """The fan-out convenience: n candidates of one prompt, the
    prompt's FULL blocks registered by candidate 0 and seated
    read-only ((n-1) full-prefix hits) — never re-prefilled, never
    duplicated — and a fixed base seed replays all candidates."""
    eng = GenerationEngine(model, num_slots=4, block_size=8,
                           sampling=True)
    prompt = _trace()[1][0]            # 17 tokens -> 2 full blocks
    params = SamplingParams(temperature=1.0, seed=5)
    cands = eng.best_of_n(prompt, 3, 10, sampling_params=params)
    assert len(cands) == 3
    plen = len(prompt)
    shared = (plen // 8) * 8
    for c in cands:
        assert c[:plen] == list(map(int, prompt))
    # seated once: candidates 1..2 each hit the whole registered
    # prefix; the cache holds ONE copy of the prompt's full blocks
    assert eng.prefix_hit_tokens == 2 * shared
    assert eng.cache.num_cached_blocks == plen // 8
    # replay: a fresh engine with the same base seed reproduces all n
    eng2 = GenerationEngine(model, num_slots=4, block_size=8,
                            sampling=True)
    assert eng2.best_of_n(prompt, 3, 10,
                          sampling_params=params) == cands
    # and a greedy request is a usage error, not n duplicates
    with pytest.raises(ValueError, match="temperature > 0"):
        eng.best_of_n(prompt, 2, 4,
                      sampling_params=SamplingParams(temperature=0))
    # a None-seed fan-out claims the WHOLE seed range from the
    # counter: a later None-seed request must not replay a candidate
    eng3 = GenerationEngine(model, num_slots=4, block_size=8,
                            sampling=True)
    eng3.best_of_n(prompt, 2, 2,
                   sampling_params=SamplingParams(temperature=1.0))
    assert eng3._seed_counter == 2
    # a load-shed candidate is a LOUD error, never a silent None in
    # the returned list (max_queue pressure, same-priority lanes)
    eng4 = GenerationEngine(model, num_slots=1, block_size=8,
                            sampling=True, max_queue=1)
    with pytest.raises(RuntimeError, match="shed"):
        eng4.best_of_n(prompt, 4, 2,
                       sampling_params=SamplingParams(temperature=1.0,
                                                      seed=3))


@pytest.mark.slow
def test_fleet_best_of_n(model):
    fleet = ServingFleet(model, num_replicas=2, num_slots=4,
                         block_size=8, sampling=True)
    prompt = _trace()[1][0]
    cands = fleet.best_of_n(prompt, 3, 8,
                            sampling_params=SamplingParams(
                                temperature=1.0, seed=9))
    assert len(cands) == 3
    plen = len(prompt)
    for c in cands:
        assert c[:plen] == list(map(int, prompt))
    # candidates 1..n-1 routed to the replica candidate 0 warmed and
    # hit its whole registered prefix (seated once fleet-wide)
    snap = fleet.metrics_snapshot()
    assert series_total(
        snap, "fleet_affinity_hit_tokens_total") == 2 * (plen // 8) * 8
    # wrong-typed params take the engine's validation path (loud
    # TypeError, not an AttributeError inside the fleet)
    with pytest.raises(TypeError, match="SamplingParams"):
        fleet.best_of_n(prompt, 2, 4,
                        sampling_params={"temperature": 0.8})
    # None-seed fan-out claims the whole range fleet-side too
    before = fleet._seed_counter
    fleet.best_of_n(prompt, 2, 2,
                    sampling_params=SamplingParams(temperature=1.0))
    assert fleet._seed_counter == before + 2
    # the prefix-cache guard holds fleet-side (bucketed-prefill
    # replicas have no cache — n-1 silent re-prefills otherwise)
    nocache = ServingFleet(model, num_replicas=1, num_slots=4,
                           block_size=8, sampling=True,
                           prefill_buckets=(32, 64))
    with pytest.raises(ValueError, match="prefix cache"):
        nocache.best_of_n(prompt, 2, 4,
                          sampling_params=SamplingParams(
                              temperature=1.0, seed=1))


# -- fleet plumbing (sampled handoff) -----------------------------------

@pytest.mark.slow
def test_fleet_single_replica_matches_bare_engine(model):
    trace = _trace()
    params = lambda i: dataclasses.replace(SAMPLED, seed=200 + i)
    ref, _ = _serve(model, trace, params, sampling=True)
    fleet = ServingFleet(model, num_replicas=1, num_slots=4,
                         block_size=8, sampling=True)
    ids = [fleet.add_request(p, mn, req_id=i,
                             sampling_params=params(i))
           for i, (p, mn) in enumerate(trace)]
    out = fleet.run()
    assert [out[i] for i in ids] == ref


def test_disaggregated_sampled_handoff_token_identical(model):
    """The satellite contract: prefill->decode adoption keeps the
    slot's key state — a temperature>0 request with a fixed seed is
    token-identical colocated vs disaggregated (the seed travels with
    the handoff and the decode replica re-derives the same key
    row)."""
    trace = _trace()
    params = lambda i: dataclasses.replace(SAMPLED, seed=300 + i) \
        if i != 2 else None            # one greedy lane rides along
    ref, _ = _serve(model, trace, params, sampling=True)
    fleet = ServingFleet(model, num_replicas=1,
                         num_prefill_replicas=1, num_slots=4,
                         block_size=8, sampling=True)
    ids = [fleet.add_request(p, mn, req_id=i,
                             sampling_params=params(i))
           for i, (p, mn) in enumerate(trace)]
    out = fleet.run()
    assert [out[i] for i in ids] == ref


@pytest.mark.slow
def test_fleet_resolves_none_seed_before_handoff(model):
    """A None seed must pin fleet-side: the prefill replica's first
    token and the decode replica's adopted lane share one seed, so
    two identical fleets replay each other."""
    def serve_fleet():
        fleet = ServingFleet(model, num_replicas=1,
                             num_prefill_replicas=1, num_slots=4,
                             block_size=8, sampling=True)
        ids = [fleet.add_request(p, mn, req_id=i,
                                 sampling_params=SamplingParams(
                                     temperature=1.0))
               for i, (p, mn) in enumerate(_trace())]
        out = fleet.run()
        return [out[i] for i in ids]

    assert serve_fleet() == serve_fleet()


def test_adopt_requires_resolved_seed(model):
    eng = GenerationEngine(model, num_slots=2, block_size=8,
                           sampling=True)
    with pytest.raises(ValueError, match="explicit seed"):
        eng.adopt_request(np.arange(8, dtype=np.int32), 3,
                          blocks=[1], max_new_tokens=4,
                          sampling_params=SamplingParams(
                              temperature=1.0))


# -- the learned drafter ------------------------------------------------

@pytest.mark.slow
def test_gpt_drafter_never_changes_greedy_tokens(model):
    """The PR 7 follow-up: a tiny draft GPT through the propose()
    protocol — greedy output stays token-identical to K=0 whatever
    the drafter's quality (here: a DIFFERENT random model)."""
    draft = _model(seed=9, heads=2)
    trace = _trace()
    ref, _ = _serve(model, trace, lambda i: None)
    out, eng = _serve(model, trace, lambda i: None, spec_decode_k=3,
                      drafter=GptDrafter(draft))
    assert out == ref
    assert eng.decode_traces == 1


@pytest.mark.slow
def test_gpt_drafter_mechanics(model):
    draft = _model(seed=9, heads=2)
    d = GptDrafter(draft)
    prompt = np.arange(5, dtype=np.int32)
    out = d.propose(prompt, [1, 2], 3)
    assert len(out) == 3
    assert all(0 <= t < VOCAB for t in out)
    # proposals are the draft model's own greedy continuation: token
    # i+1 conditions on token i (re-fed, not parallel-sampled)
    again = d.propose(prompt, [1, 2], 3)
    assert again == out                # deterministic
    assert d.propose(prompt, [1, 2], 0) == []
    # out-of-vocab context (disjoint tokenizer): refuse to guess
    assert d.propose(np.asarray([VOCAB + 5]), [], 3) == []
    # max_context=0 is a loud range error, never silently coerced to
    # the full window by falsy-zero defaulting
    with pytest.raises(ValueError, match="max_context"):
        GptDrafter(draft, max_context=0)
    # an eval-less dropout model is a usage error
    drop = _model(seed=3, heads=2)
    drop.config.dropout = 0.1
    drop.train()
    with pytest.raises(ValueError, match="eval"):
        GptDrafter(drop)
    # and GptDrafter composes with sampling: rejection acceptance
    # reproduces under the learned drafter too
    params = lambda i: dataclasses.replace(SAMPLED, seed=400 + i)
    a, _ = _serve(model, _trace(), params, sampling=True,
                  spec_decode_k=3, drafter=GptDrafter(draft))
    b, _ = _serve(model, _trace(), params, sampling=True,
                  spec_decode_k=3, drafter=GptDrafter(draft))
    assert a == b


# -- validation, knobs, metrics -----------------------------------------

def test_sampling_params_validation(model):
    with pytest.raises(ValueError, match="temperature"):
        SamplingParams(temperature=-0.5)
    with pytest.raises(ValueError, match="top_k"):
        SamplingParams(top_k=-1)
    with pytest.raises(ValueError, match="top_p"):
        SamplingParams(top_p=0.0)
    with pytest.raises(ValueError, match="top_p"):
        SamplingParams(top_p=1.5)
    # a sampling request on a greedy-only engine is a loud error
    eng = GenerationEngine(model, num_slots=2, block_size=8)
    with pytest.raises(ValueError, match="sampling=True"):
        eng.add_request(np.arange(4, dtype=np.int32), 2,
                        sampling_params=SamplingParams())
    with pytest.raises(TypeError, match="SamplingParams"):
        GenerationEngine(model, num_slots=2, block_size=8,
                         sampling=True).add_request(
            np.arange(4, dtype=np.int32), 2,
            sampling_params={"temperature": 1.0})
    # best_of_n needs the subsystem (and the prefix cache)
    with pytest.raises(ValueError, match="sampling=True"):
        eng.best_of_n(np.arange(8, dtype=np.int32), 2, 4)
    with pytest.raises(ValueError, match="prefix cache"):
        GenerationEngine(model, num_slots=2, block_size=8,
                         sampling=True, prefill_buckets=(64,)
                         ).best_of_n(np.arange(8, dtype=np.int32), 2,
                                     4)


def test_env_override_enables_sampling(model, monkeypatch):
    monkeypatch.setenv("PADDLE_SERVE_SAMPLING", "1")
    eng = GenerationEngine(model, num_slots=2, block_size=8)
    assert eng.sampling is True
    monkeypatch.setenv("PADDLE_SERVE_SAMPLING", "0")
    eng = GenerationEngine(model, num_slots=2, block_size=8,
                           sampling=True)
    assert eng.sampling is False       # env wins, both directions
    monkeypatch.setenv("PADDLE_SERVE_SAMPLING", "maybe")
    with pytest.raises(ValueError, match="PADDLE_SERVE_SAMPLING"):
        GenerationEngine(model, num_slots=2, block_size=8)


def test_sampling_metrics(model):
    """The info gauge says which programs this engine runs; the
    sampled-token counter counts ONLY temperature>0 lanes (and only
    exists on sampling engines — plain exposition unchanged)."""
    trace = _trace()
    outs, eng = _serve(model, trace,
                       lambda i: SAMPLED if i == 1 else None,
                       sampling=True)
    snap = eng.metrics_snapshot()
    fam = {s["labels"]["enabled"]: s["value"]
           for s in snap["engine_sampling_info"]["series"]}
    assert fam == {"1": 1.0}
    # exactly the sampled lane's generated tokens, nothing from the
    # greedy lanes
    sampled = series_total(snap, "engine_sampled_tokens_total")
    assert sampled == len(outs[1]) - len(trace[1][0])
    _, plain = _serve(model, trace, lambda i: None)
    assert "engine_sampled_tokens_total" not in plain.metrics_snapshot()
    assert {s["labels"]["enabled"]: s["value"]
            for s in plain.metrics_snapshot()
            ["engine_sampling_info"]["series"]} == {"0": 1.0}


def test_key_row_is_seed_pure():
    assert (key_row(7) == key_row(7)).all()
    assert (key_row(7) != key_row(8)).any()
    assert key_row(7).dtype == np.uint32 and key_row(7).shape == (2,)
    # the full 64-bit seed range stays distinct: seeds congruent mod
    # 2^31 / 2^32 (hash-derived seeds, negatives) must not collide
    assert (key_row(7) != key_row(7 + 2**31)).any()
    assert (key_row(7) != key_row(7 + 2**32)).any()
    assert (key_row(-1) != key_row(2**31 - 1)).any()


# -- bench runner (tiny) ------------------------------------------------

@pytest.mark.slow
def test_sampling_bench_runner_tiny():
    """The gpt_engine_sampling row's runner at CI scale: structure +
    in-runner assertions (greedy identity, seeded reproducibility,
    best-of-n block sharing) on a tiny config."""
    import bench_ops
    from paddle_tpu.models import GPTConfig

    paddle.seed(0)
    rec = bench_ops._engine_sampling_case(
        model_cfg=GPTConfig.tiny(vocab=VOCAB, hidden=32, layers=2,
                                 heads=2, seq=64),
        num_requests=3, num_slots=2, block_size=8, max_new=6,
        best_n=2)()
    for key in ("tokens_per_s_greedy_off", "tokens_per_s_greedy",
                "tokens_per_s_sampled", "tokens_per_s_best_of_n",
                "sampled_tokens", "best_of_n_hit_tokens"):
        assert key in rec, rec
    assert rec["sampled_tokens"] > 0
    assert rec["best_of_n_hit_tokens"] > 0


def test_suite_rows_carry_sampling_row():
    import bench_ops

    assert "gpt_engine_sampling" in bench_ops.SUITE_ROWS
