"""text + audio namespace tests (SURVEY item 36).

viterbi_decode is checked against brute-force path enumeration; audio
features against scipy.signal / closed-form DSP references.
"""
import itertools

import os

import numpy as np
import pytest
from scipy import signal as spsignal

import paddle_tpu as paddle
from paddle_tpu.audio import MFCC, MelSpectrogram, Spectrogram
from paddle_tpu.audio.functional import (compute_fbank_matrix, create_dct,
                                         get_window, hz_to_mel, mel_to_hz,
                                         power_to_db)
from paddle_tpu.text import ViterbiDecoder, viterbi_decode


# -- viterbi ------------------------------------------------------------
def _brute_force(emis, trans, length, bos_eos):
    n = emis.shape[1]
    best, best_path = -np.inf, None
    for path in itertools.product(range(n), repeat=length):
        s = emis[0, path[0]]
        if bos_eos:
            s += trans[-1, path[0]]
        for t in range(1, length):
            s += trans[path[t - 1], path[t]] + emis[t, path[t]]
        if bos_eos:
            s += trans[path[length - 1], -2]
        if s > best:
            best, best_path = s, path
    return best, np.array(best_path)


@pytest.mark.parametrize("bos_eos", [False, True])
def test_viterbi_matches_brute_force(bos_eos):
    rs = np.random.RandomState(0)
    B, T, N = 3, 5, 4
    emis = rs.uniform(-1, 1, (B, T, N)).astype(np.float32)
    trans = rs.uniform(-1, 1, (N, N)).astype(np.float32)
    lengths = np.array([5, 3, 1], np.int64)
    scores, paths = viterbi_decode(paddle.to_tensor(emis),
                                   paddle.to_tensor(trans),
                                   paddle.to_tensor(lengths),
                                   include_bos_eos_tag=bos_eos)
    scores = np.asarray(scores._array)
    paths = np.asarray(paths._array)
    for b in range(B):
        want_s, want_p = _brute_force(emis[b], trans, int(lengths[b]),
                                      bos_eos)
        np.testing.assert_allclose(scores[b], want_s, rtol=1e-5,
                                   err_msg=f"batch {b}")
        np.testing.assert_array_equal(paths[b, :lengths[b]], want_p)
        assert (paths[b, lengths[b]:] == 0).all()


def test_viterbi_decoder_layer_jittable():
    import jax

    rs = np.random.RandomState(1)
    emis = rs.uniform(-1, 1, (2, 6, 3)).astype(np.float32)
    trans = rs.uniform(-1, 1, (3, 3)).astype(np.float32)
    dec = ViterbiDecoder(paddle.to_tensor(trans),
                         include_bos_eos_tag=False)
    s1, p1 = dec(paddle.to_tensor(emis),
                 paddle.to_tensor(np.array([6, 6], np.int64)))

    from paddle_tpu.text import _viterbi

    jitted = jax.jit(lambda e, t, ln: _viterbi(e, t, ln, False))
    s2, p2 = jitted(emis, trans, np.array([6, 6]))
    np.testing.assert_allclose(np.asarray(s1._array), np.asarray(s2),
                               rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(p1._array), np.asarray(p2))


# -- audio --------------------------------------------------------------
def test_window_matches_scipy():
    for name in ("hann", "hamming", "blackman"):
        got = np.asarray(get_window(name, 64))
        want = spsignal.get_window(name, 64, fftbins=True)
        np.testing.assert_allclose(got, want, atol=1e-6)


def test_mel_scale_roundtrip():
    f = np.array([0.0, 440.0, 1000.0, 4000.0, 8000.0])
    np.testing.assert_allclose(np.asarray(mel_to_hz(hz_to_mel(f))), f,
                               rtol=1e-4, atol=1e-3)
    # htk closed form
    np.testing.assert_allclose(float(np.asarray(hz_to_mel(1000.0,
                                                          htk=True))),
                               2595.0 * np.log10(1 + 1000 / 700),
                               rtol=1e-6)


def test_spectrogram_matches_scipy_stft():
    rs = np.random.RandomState(0)
    x = rs.randn(1, 2048).astype(np.float32)
    n_fft, hop = 256, 128
    layer = Spectrogram(n_fft=n_fft, hop_length=hop, window="hann",
                        power=2.0, center=False)
    got = np.asarray(layer(paddle.to_tensor(x))._array)[0]
    _, _, Z = spsignal.stft(x[0], window="hann", nperseg=n_fft,
                            noverlap=n_fft - hop, boundary=None,
                            padded=False)
    want = np.abs(Z * n_fft / 2) ** 2  # undo scipy's win.sum() scaling
    # scipy scales by 1/win.sum(); hann sum = n_fft/2
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_mel_and_mfcc_shapes_and_dct():
    rs = np.random.RandomState(1)
    x = rs.randn(2, 4096).astype(np.float32)
    mel = MelSpectrogram(sr=16000, n_fft=512, n_mels=40, center=True)
    m = np.asarray(mel(paddle.to_tensor(x))._array)
    assert m.shape[0] == 2 and m.shape[1] == 40
    assert (m >= 0).all()
    mfcc = MFCC(sr=16000, n_mfcc=13, n_fft=512, n_mels=40)
    c = np.asarray(mfcc(paddle.to_tensor(x))._array)
    assert c.shape[:2] == (2, 13)
    # ortho DCT columns are orthonormal
    d = np.asarray(create_dct(13, 40))
    np.testing.assert_allclose(d.T @ d, np.eye(13), atol=1e-5)


def test_power_to_db():
    s = np.array([1.0, 10.0, 100.0])
    db = np.asarray(power_to_db(s, top_db=None))
    np.testing.assert_allclose(db, [0.0, 10.0, 20.0], atol=1e-5)
    db2 = np.asarray(power_to_db(np.array([1e-9, 100.0]), top_db=80.0))
    assert db2[0] == pytest.approx(20.0 - 80.0)


def test_fbank_rows_nonzero():
    fb = np.asarray(compute_fbank_matrix(16000, 512, n_mels=40))
    assert fb.shape == (40, 257)
    assert (fb.sum(axis=1) > 0).all()


# -- text datasets (text/datasets parity, local-file parsers) -----------
def _write_imdb_tar(tmp):
    import io
    import tarfile

    path = os.path.join(tmp, "aclImdb.tar.gz")
    docs = {
        "train/pos/0.txt": "a great great movie",
        "train/neg/1.txt": "a terrible movie",
        "test/pos/0.txt": "great fun",
        "test/neg/1.txt": "terrible bore",
    }
    with tarfile.open(path, "w:gz") as tf:
        for name, text in docs.items():
            raw = text.encode()
            info = tarfile.TarInfo("aclImdb/" + name)
            info.size = len(raw)
            tf.addfile(info, io.BytesIO(raw))
    return path


def test_imdb_dataset(tmp_path):
    from paddle_tpu.text import Imdb

    path = _write_imdb_tar(str(tmp_path))
    ds = Imdb(data_file=path, mode="train", cutoff=0, seq_len=6)
    assert len(ds) == 2
    ids, label = ds[0]
    assert ids.shape == (6,) and label in (0, 1)
    # vocabulary from train split covers its tokens
    assert "great" in ds.word_idx and "movie" in ds.word_idx
    test = Imdb(data_file=path, mode="test", cutoff=0)
    assert len(test) == 2
    import pytest as _pytest

    with _pytest.raises(RuntimeError, match="egress"):
        Imdb(download=True)


def test_conll_dataset(tmp_path):
    from paddle_tpu.text import Conll05st

    p = tmp_path / "conll.txt"
    p.write_text("The DET\ncat NOUN\nsat VERB\n\nA DET\ndog NOUN\n")
    ds = Conll05st(data_file=str(p), seq_len=4)
    assert len(ds) == 2
    ids, labs = ds[0]
    assert ids.shape == (4,) and labs.shape == (4,)
    assert len(ds.label_dict) == 4  # 3 tags + <pad>
    pad = ds.label_dict["<pad>"]
    assert (labs[3:] == pad).all()  # padding never aliases a real tag


def test_uci_housing(tmp_path):
    from paddle_tpu.text import UCIHousing

    rows = np.random.RandomState(0).rand(10, 14)
    p = tmp_path / "housing.data"
    np.savetxt(p, rows)
    tr = UCIHousing(data_file=str(p), mode="train")
    te = UCIHousing(data_file=str(p), mode="test")
    assert len(tr) == 8 and len(te) == 2
    x, y = tr[0]
    assert x.shape == (13,) and y.shape == (1,)
