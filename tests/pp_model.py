"""Shared pipeline-model builder for the cross-process pp parity test:
the worker (launch_worker.run_pp) and the in-process baseline
(test_multiprocess.test_two_process_pipeline_parity) must construct
byte-identical models, so the definition lives once, importable by both
(tests/ is on the worker's sys.path)."""
import numpy as np


def build_pp_model(num_stages, seed=3):
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import (DistributedTrainStep, LayerDesc,
                                        PipelineLayer)

    class Block(nn.Layer):
        def __init__(self, hidden):
            super().__init__()
            self.fc = nn.Linear(hidden, hidden)

        def forward(self, x):
            return paddle.tanh(self.fc(x)) + x

    class Embed(nn.Layer):
        def __init__(self, vocab, hidden):
            super().__init__()
            self.emb = nn.Embedding(vocab, hidden)

        def forward(self, ids):
            return self.emb(ids)

    class Head(nn.Layer):
        def __init__(self, hidden, vocab):
            super().__init__()
            self.proj = nn.Linear(hidden, vocab)

        def forward(self, x):
            return self.proj(x)

    paddle.seed(seed)
    model = PipelineLayer(
        [LayerDesc(Embed, 64, 16),
         *[LayerDesc(Block, 16) for _ in range(4)],
         LayerDesc(Head, 16, 64)],
        num_stages=num_stages, num_microbatches=4)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    step = DistributedTrainStep(
        model, opt,
        lambda out, lab: F.cross_entropy(
            out.reshape([-1, 64]), lab.reshape([-1])))
    return model, step


def run_pp_losses(step, paddle, steps=4):
    rng = np.random.RandomState(7)
    losses = []
    for _ in range(steps):
        ids = paddle.to_tensor(rng.randint(0, 64, (8, 12), np.int32))
        losses.append(float(step(ids, ids)))
    return losses
