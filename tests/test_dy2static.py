"""dy2static control-flow + input_spec tests (VERDICT r2 #9): python
if/while on Tensor values compile to lax.cond/lax.while_loop under
to_static; input_spec is enforced and dynamic dims can bucket.

Reference analogs: python/paddle/jit/dy2static/convert_operators.py,
program_translator.py:519 (spec-driven concretization).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.jit as jit
import paddle_tpu.nn as nn
from paddle_tpu.jit.api import InputSpec


def test_tensor_if_compiles_and_branches():
    @jit.to_static
    def f(x):
        if x.mean() > 0:
            y = x * 2.0
        else:
            y = x - 1.0
        return y

    xp = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    xn = paddle.to_tensor(np.array([-1.0, -2.0], np.float32))
    np.testing.assert_allclose(np.asarray(f(xp)._array), [2.0, 4.0])
    np.testing.assert_allclose(np.asarray(f(xn)._array), [-2.0, -3.0])
    # same shape/dtype -> ONE compiled program serves both branches
    assert len(f._cache) == 1


def test_tensor_if_var_defined_only_in_branches():
    @jit.to_static
    def f(x):
        if x.sum() > 0:
            sign = paddle.to_tensor(1.0) * x.sum() / x.sum()
        else:
            sign = paddle.to_tensor(-1.0) * x.sum() / x.sum()
        return sign

    xp = paddle.to_tensor(np.array([2.0], np.float32))
    xn = paddle.to_tensor(np.array([-2.0], np.float32))
    assert float(f(xp)._array) == 1.0
    assert float(f(xn)._array) == -1.0


def test_elif_chain():
    @jit.to_static
    def f(x):
        s = x.sum()
        if s > 1.0:
            r = x * 0.0 + 2.0
        elif s > -1.0:
            r = x * 0.0 + 1.0
        else:
            r = x * 0.0
        return r

    for val, want in [(5.0, 2.0), (0.1, 1.0), (-5.0, 0.0)]:
        x = paddle.to_tensor(np.array([val], np.float32))
        assert float(f(x)._array[0]) == want


def test_both_branches_return():
    @jit.to_static
    def f(x):
        if x.mean() > 0:
            return x + 10.0
        else:
            return x - 10.0

    assert float(f(paddle.to_tensor(np.array([1.0], np.float32)))._array[0]) == 11.0
    assert float(f(paddle.to_tensor(np.array([-1.0], np.float32)))._array[0]) == -11.0


def test_python_bool_if_keeps_python_semantics():
    @jit.to_static
    def f(x, flag=True):
        if flag:
            return x * 2.0
        return x * 3.0

    x = paddle.to_tensor(np.array([1.0], np.float32))
    assert float(f(x)._array[0]) == 2.0
    assert float(f(x, flag=False)._array[0]) == 3.0


def test_tensor_while_loop():
    @jit.to_static
    def f(x):
        # double until the sum passes 100 (data-dependent trip count)
        while x.sum() < 100.0:
            x = x * 2.0
        return x

    out = f(paddle.to_tensor(np.array([1.0, 2.0], np.float32)))
    # 3 * 2^6 = 192 >= 100, 3 * 2^5 = 96 < 100
    np.testing.assert_allclose(np.asarray(out._array), [64.0, 128.0])


def test_while_loop_eager_transform():
    from paddle_tpu.jit.dy2static import transform_function

    def f(x, n):
        i = 0
        acc = x
        while i < n:  # python ints: python loop
            acc = acc + 1.0
            i += 1
        return acc

    g = transform_function(f)
    assert getattr(g, "__jst_transformed__", False)
    x = paddle.to_tensor(np.array([0.0], np.float32))
    assert float(g(x, 3)._array[0]) == 3.0


def test_layer_forward_with_tensor_if():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            h = self.fc(x)
            if h.mean() > 0:
                out = h * 2.0
            else:
                out = h * -1.0
            return out

    paddle.seed(0)
    net = jit.to_static(Net())
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    y = net(x)
    # gradient flows through the chosen branch
    loss = y.sum()
    loss.backward()
    assert net.fc.weight.grad is not None
    assert float(np.abs(np.asarray(net.fc.weight.grad._array)).sum()) > 0


# -- input_spec ---------------------------------------------------------
def test_input_spec_validation():
    @jit.to_static(input_spec=[InputSpec([None, 4], "float32")])
    def f(x):
        return x * 2.0

    f(paddle.to_tensor(np.ones((3, 4), np.float32)))  # ok
    with pytest.raises(ValueError, match="rank"):
        f(paddle.to_tensor(np.ones((3, 4, 1), np.float32)))
    with pytest.raises(TypeError, match="dtype"):
        f(paddle.to_tensor(np.ones((3, 4), np.int32)))
    with pytest.raises(ValueError, match="requires 4"):
        f(paddle.to_tensor(np.ones((3, 5), np.float32)))


def test_input_spec_kwarg_tensor_ok():
    @jit.to_static(input_spec=[InputSpec([None, 4], "float32")])
    def f(x):
        return x + 1.0

    y = f(x=paddle.to_tensor(np.ones((2, 4), np.float32)))
    assert np.asarray(y._array).shape == (2, 4)


def test_input_spec_dtype_object():
    @jit.to_static(input_spec=[InputSpec([None, 2], np.int32)])
    def f(x):
        return x * 2

    f(paddle.to_tensor(np.ones((3, 2), np.int32)))  # np.dtype spec works
    with pytest.raises(TypeError, match="dtype"):
        f(paddle.to_tensor(np.ones((3, 2), np.float32)))


def test_layer_bucketing_passthrough():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 2)

        def forward(self, x):
            return self.fc(x)

    paddle.seed(0)
    net = jit.to_static(Net(), input_spec=[InputSpec([None, 4], "float32")],
                        build_strategy={"dynamic_dim_buckets": True})
    for b in (5, 7, 8):
        y = net(paddle.to_tensor(np.ones((b, 4), np.float32)))
        assert np.asarray(y._array).shape == (b, 2)
    assert len(net.forward._cache) == 1


_GLOBAL_THRESHOLD = 1.0


def test_transform_sees_live_globals():
    from paddle_tpu.jit.dy2static import transform_function

    def f(x):
        if x.sum() > _GLOBAL_THRESHOLD:
            y = x * 0.0 + 1.0
        else:
            y = x * 0.0
        return y

    g = transform_function(f)
    x = paddle.to_tensor(np.array([2.0], np.float32))
    assert float(g(x)._array[0]) == 1.0
    global _GLOBAL_THRESHOLD
    old = _GLOBAL_THRESHOLD
    try:
        _GLOBAL_THRESHOLD = 5.0  # rebinding must be visible
        assert float(g(x)._array[0]) == 0.0
    finally:
        _GLOBAL_THRESHOLD = old


def test_input_spec_dynamic_bucketing():
    calls = []

    @jit.to_static(input_spec=[InputSpec([None, 4], "float32")],
                   build_strategy={"dynamic_dim_buckets": True})
    def f(x):
        calls.append(x.shape[0])
        return x * 2.0 + 1.0

    outs = {}
    for b in (5, 6, 7, 8):
        x = np.arange(b * 4, dtype=np.float32).reshape(b, 4)
        y = f(paddle.to_tensor(x))
        assert np.asarray(y._array).shape == (b, 4)
        np.testing.assert_allclose(np.asarray(y._array), x * 2.0 + 1.0)
        outs[b] = y
    # 5..8 all pad to the 8-bucket: ONE trace, one compiled program
    assert len(f._cache) == 1
    assert calls == [8]


# -- for-loop conversion (VERDICT r3 missing #5) ---------------------------

def test_for_range_python_semantics():
    @jit.to_static
    def f(x):
        acc = x * 0
        for i in range(4):
            acc = acc + x * i
        return acc

    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    np.testing.assert_allclose(np.asarray(f(x)._array), [6.0, 12.0])


def test_for_range_tensor_bound_compiles_to_loop():
    """A Tensor stop bound becomes a lax.while_loop — ONE program, no
    unrolling, the bound may change between calls without recompile."""
    calls = {"n": 0}

    def raw(x, n):
        acc = x * 0
        for i in range(n):
            acc = acc + x + i
        return acc

    from paddle_tpu.jit.dy2static import transform_function

    fn = transform_function(raw)

    import jax

    @jax.jit
    def run(xa, na):
        calls["n"] += 1
        from paddle_tpu.core.tensor import Tensor

        return fn(Tensor._wrap(xa), Tensor._wrap(na))._array

    x = np.array([10.0], np.float32)
    got3 = np.asarray(run(x, np.int32(3)))
    got5 = np.asarray(run(x, np.int32(5)))
    np.testing.assert_allclose(got3, [33.0])   # 3*10 + (0+1+2)
    np.testing.assert_allclose(got5, [60.0])   # 5*10 + (0+..+4)
    assert calls["n"] == 1, "tensor-bound for must not retrace per n"


def test_for_tensor_iteration():
    @jit.to_static
    def f(xs, b):
        acc = b * 0
        for row in xs:
            acc = acc + row
        return acc

    xs = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(4, 3))
    b = paddle.to_tensor(np.zeros((3,), np.float32))
    np.testing.assert_allclose(np.asarray(f(xs, b)._array),
                               np.arange(12, dtype=np.float32)
                               .reshape(4, 3).sum(0))


def test_for_over_python_list_unchanged():
    @jit.to_static
    def f(x):
        acc = x * 0
        for w in [1.0, 2.0, 3.0]:
            acc = acc + x * w
        return acc

    x = paddle.to_tensor(np.array([2.0], np.float32))
    np.testing.assert_allclose(np.asarray(f(x)._array), [12.0])


def test_for_with_break_keeps_python_semantics():
    @jit.to_static
    def f(x):
        acc = x * 0
        for i in range(10):
            if i >= 2:
                break
            acc = acc + x
        return acc

    x = paddle.to_tensor(np.array([5.0], np.float32))
    np.testing.assert_allclose(np.asarray(f(x)._array), [10.0])


# -- greedy decode under to_static (the real data-dependent loop) ----------

def test_gpt_generate_eager_compiled_parity():
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig.tiny(vocab=64, hidden=32, layers=2, heads=2, seq=16)
    model = GPTForCausalLM(cfg)
    model.eval()
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 64, (2, 4)).astype(np.int32))

    eager = np.asarray(model.generate(ids, max_length=12)._array)
    assert eager.shape == (2, 12)
    # prompt preserved, continuation in-vocab
    np.testing.assert_array_equal(eager[:, :4], np.asarray(ids._array))
    assert (eager >= 0).all() and (eager < 64).all()

    compiled = jit.to_static(model.generate)
    got = np.asarray(compiled(ids, max_length=12)._array)
    np.testing.assert_array_equal(got, eager)


def test_gpt_generate_eos_freezes_rows():
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(1)
    cfg = GPTConfig.tiny(vocab=16, hidden=16, layers=1, heads=2, seq=12)
    model = GPTForCausalLM(cfg)
    model.eval()
    ids = paddle.to_tensor(
        np.random.RandomState(1).randint(0, 16, (1, 3)).astype(np.int32))
    out = np.asarray(model.generate(ids, max_length=10,
                                    eos_token_id=3)._array)
    hits = np.where(out[0, 3:] == 3)[0]
    if len(hits):  # once EOS fires, the row stays EOS
        tail = out[0, 3 + hits[0]:]
        assert (tail == 3).all(), out


def test_for_tensor_bound_loop_var_after_loop():
    """The loop variable stays bound after a traced-bound loop (python
    leaves the last value; review fix r4)."""
    from paddle_tpu.jit.dy2static import transform_function

    def raw(x, n):
        acc = x * 0
        for i in range(n):
            acc = acc + x
        return acc + i

    fn = transform_function(raw)

    import jax

    from paddle_tpu.core.tensor import Tensor

    @jax.jit
    def run(xa, na):
        return fn(Tensor._wrap(xa), Tensor._wrap(na))._array

    got = np.asarray(run(np.array([10.0], np.float32), np.int32(3)))
    np.testing.assert_allclose(got, [32.0])  # 3*10 + i=2


def test_gpt_generate_kv_cache_matches_uncached():
    """The fixed-buffer KV-cache decode (prefill + forward_decode) must
    produce the SAME tokens as the recompute-everything path, eager and
    under to_static."""
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(3)
    cfg = GPTConfig.tiny(vocab=64, hidden=32, layers=2, heads=2, seq=16)
    model = GPTForCausalLM(cfg)
    model.eval()
    ids = paddle.to_tensor(
        np.random.RandomState(5).randint(0, 64, (2, 4)).astype(np.int32))

    plain = np.asarray(model.generate(ids, max_length=12)._array)
    cached = np.asarray(model.generate(ids, max_length=12,
                                       use_cache=True)._array)
    np.testing.assert_array_equal(cached, plain)

    compiled = jit.to_static(
        lambda t: model.generate(t, max_length=12, use_cache=True))
    got = np.asarray(compiled(ids)._array)
    np.testing.assert_array_equal(got, plain)


def test_gpt_generate_kv_cache_eos():
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(4)
    cfg = GPTConfig.tiny(vocab=16, hidden=16, layers=1, heads=2, seq=12)
    model = GPTForCausalLM(cfg)
    model.eval()
    ids = paddle.to_tensor(
        np.random.RandomState(6).randint(0, 16, (1, 3)).astype(np.int32))
    a = np.asarray(model.generate(ids, max_length=10,
                                  eos_token_id=3)._array)
    b = np.asarray(model.generate(ids, max_length=10, eos_token_id=3,
                                  use_cache=True)._array)
    np.testing.assert_array_equal(a, b)
