"""Native (C++) batch loader tests — the data_feed.cc analog: builds
the shared library with the system toolchain, checks batch correctness,
deterministic shuffling, multi-array lockstep, drop_last, multi-epoch
reshuffle, and that prefetch overlaps (smoke).
"""
import numpy as np
import pytest

from paddle_tpu.io import NativeArrayLoader, native_available

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="no native toolchain")


def test_sequential_batches_exact():
    x = np.arange(25 * 3, dtype=np.float32).reshape(25, 3)
    loader = NativeArrayLoader(x, batch_size=4)
    got = list(loader)
    assert len(got) == len(loader) == 7
    np.testing.assert_array_equal(np.concatenate(got), x)
    assert got[-1].shape == (1, 3)  # remainder kept without drop_last


def test_drop_last():
    x = np.arange(25, dtype=np.int64)
    loader = NativeArrayLoader(x, batch_size=4, drop_last=True)
    got = list(loader)
    assert len(got) == 6
    assert all(len(b) == 4 for b in got)


def test_shuffle_is_permutation_and_seeded():
    x = np.arange(100, dtype=np.int64)
    a = np.concatenate(list(NativeArrayLoader(x, 16, shuffle=True, seed=7)))
    b = np.concatenate(list(NativeArrayLoader(x, 16, shuffle=True, seed=7)))
    c = np.concatenate(list(NativeArrayLoader(x, 16, shuffle=True, seed=8)))
    np.testing.assert_array_equal(np.sort(a), x)      # a permutation
    np.testing.assert_array_equal(a, b)               # seed-deterministic
    assert not np.array_equal(a, c)                   # seed matters
    assert not np.array_equal(a, x)                   # actually shuffled


def test_multi_epoch_reshuffles():
    x = np.arange(64, dtype=np.int64)
    loader = NativeArrayLoader(x, 8, shuffle=True, seed=3)
    e1 = np.concatenate(list(loader))
    e2 = np.concatenate(list(loader))
    np.testing.assert_array_equal(np.sort(e2), x)
    assert not np.array_equal(e1, e2)  # new epoch, new order


def test_two_arrays_lockstep():
    rs = np.random.RandomState(0)
    imgs = rs.randn(50, 4, 4).astype(np.float32)
    labels = np.arange(50, dtype=np.int64)
    loader = NativeArrayLoader((imgs, labels), 8, shuffle=True, seed=11)
    for xb, yb in loader:
        # each label must still index its own image row
        np.testing.assert_array_equal(xb, imgs[yb])


def test_early_break_then_reiterate():
    """Abandoning an epoch mid-iteration must not corrupt or deadlock
    the next one (the new_epoch quiesce path)."""
    x = np.arange(200, dtype=np.int64)
    loader = NativeArrayLoader(x, 8, shuffle=True, seed=1, workers=4,
                               prefetch=6)
    for trial in range(10):
        it = iter(loader)
        for _ in range(3):  # consume a few batches, then abandon
            next(it)
        del it
        full = np.concatenate(list(loader))
        np.testing.assert_array_equal(np.sort(full), x)


def test_trains_a_model_end_to_end():
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    import paddle_tpu.jit as jit

    rs = np.random.RandomState(1)
    X = rs.randn(256, 8).astype(np.float32)
    W = rs.randn(8, 4).astype(np.float32)
    Y = X @ W
    paddle.seed(0)
    net = nn.Linear(8, 4)
    opt = paddle.optimizer.Adam(learning_rate=0.05,
                                parameters=net.parameters())
    step = jit.TrainStep(net, opt, F.mse_loss)
    loader = NativeArrayLoader((X, Y), 64, shuffle=True, seed=5)
    losses = []
    for _ in range(30):
        for xb, yb in loader:
            loss = step(paddle.to_tensor(xb), paddle.to_tensor(yb))
        losses.append(float(loss))
    assert losses[-1] < 0.05 * losses[0]
