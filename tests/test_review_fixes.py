"""Regression tests for code-review findings (round 1)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_cross_entropy_ignore_index_default():
    logits = paddle.randn([4, 5])
    labels = paddle.to_tensor([1, -100, 2, -100])
    loss = F.cross_entropy(logits, labels)
    # reference: mean over the 2 valid positions only
    lg = logits.numpy()
    p = np.exp(lg) / np.exp(lg).sum(-1, keepdims=True)
    expect = -np.log(p[[0, 2], [1, 2]]).mean()
    np.testing.assert_allclose(float(loss), expect, rtol=1e-4)
    # all-ignored must not NaN
    loss2 = F.cross_entropy(logits, paddle.to_tensor([-100] * 4))
    assert np.isfinite(float(loss2))


def test_cross_entropy_ignore_index_grad_zero_at_ignored():
    logits = paddle.randn([3, 4])
    logits.stop_gradient = False
    labels = paddle.to_tensor([0, -100, 1])
    F.cross_entropy(logits, labels).backward()
    g = logits.grad.numpy()
    np.testing.assert_allclose(g[1], 0.0, atol=1e-7)
    assert np.abs(g[0]).sum() > 0


def test_adamw_decay_param_fun():
    from paddle_tpu.core.tensor import Parameter

    w = Parameter(np.ones(2, np.float32), name="layer.weight")
    b = Parameter(np.ones(2, np.float32), name="layer.bias")
    opt = paddle.optimizer.AdamW(
        learning_rate=0.1, weight_decay=0.5, parameters=[w, b],
        apply_decay_param_fun=lambda n: "bias" not in n)
    w.grad = paddle.zeros([2])
    b.grad = paddle.zeros([2])
    opt.step()
    np.testing.assert_allclose(w.numpy(), [0.95, 0.95], rtol=1e-5)  # decayed
    np.testing.assert_allclose(b.numpy(), [1.0, 1.0], rtol=1e-6)  # not decayed


def test_grad_api_does_not_pollute_other_leaves():
    from paddle_tpu.core.tensor import Parameter

    w = Parameter(np.array([2.0], np.float32))
    x = paddle.to_tensor([3.0])
    x.stop_gradient = False
    loss = (w * x).sum()
    (gx,) = paddle.grad(loss, [x], retain_graph=True)
    np.testing.assert_allclose(gx.numpy(), [2.0])
    assert w.grad is None  # must not be polluted
    assert x.grad is None


def test_grad_scaler_unscale_then_step():
    from paddle_tpu.core.tensor import Parameter

    p = Parameter(np.array([1.0], np.float32))
    opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[p])
    scaler = paddle.amp.GradScaler(init_loss_scaling=8.0)
    loss = (p * 2.0).sum()  # dL/dp = 2
    scaled = scaler.scale(loss)
    scaled.backward()
    scaler.unscale_(opt)  # user unscales manually (e.g. to clip)
    np.testing.assert_allclose(p.grad.numpy(), [2.0], rtol=1e-6)
    scaler.step(opt)  # must NOT unscale a second time
    scaler.update()
    np.testing.assert_allclose(p.numpy(), [-1.0], rtol=1e-6)


def test_split_non_divisible_raises():
    with pytest.raises(ValueError, match="not divisible"):
        paddle.split(paddle.ones([10, 2]), 3, axis=0)


def test_batch_norm_bias_only_training():
    x = paddle.randn([8, 3, 4, 4])
    rm, rv = paddle.zeros([3]), paddle.ones([3])
    b = paddle.to_tensor([1.0, 2.0, 3.0])
    out = F.batch_norm(x, rm, rv, weight=None, bias=b, training=True)
    means = out.numpy().mean(axis=(0, 2, 3))
    np.testing.assert_allclose(means, [1.0, 2.0, 3.0], atol=1e-4)


def test_nll_loss_4d():
    n, c, h, w = 2, 5, 3, 3
    logp = F.log_softmax(paddle.randn([n, c, h, w]), axis=1)
    target = paddle.randint(0, c, [n, h, w])
    loss = F.nll_loss(logp, target)
    lp = logp.numpy()
    t = target.numpy()
    ref = -np.take_along_axis(lp, t[:, None], axis=1)[:, 0].mean()
    np.testing.assert_allclose(float(loss), ref, rtol=1e-5)


def test_interpolate_align_corners():
    x = paddle.to_tensor(
        np.arange(4, dtype=np.float32).reshape(1, 1, 2, 2))
    out = F.interpolate(x, size=(3, 3), mode="bilinear", align_corners=True)
    # corners preserved exactly under align_corners=True
    o = out.numpy()[0, 0]
    np.testing.assert_allclose(
        [o[0, 0], o[0, 2], o[2, 0], o[2, 2]], [0, 1, 2, 3], atol=1e-5)
    np.testing.assert_allclose(o[1, 1], 1.5, atol=1e-5)
