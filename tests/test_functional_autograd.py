"""paddle.incubate.autograd functional surface (VERDICT r4 missing #2):
vjp/jvp/Jacobian/Hessian/forward_grad against the reference's documented
example values (functional.py:22,:80,:171,:260) and numeric finite
differences.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.incubate import autograd as fa


def _mm(x):
    return paddle.matmul(x, x)


def test_vjp_matches_reference_docstring():
    x = paddle.ones([2, 2], dtype="float32")
    out, g = fa.vjp(_mm, x)
    np.testing.assert_allclose(np.asarray(out), np.full((2, 2), 2.0))
    np.testing.assert_allclose(np.asarray(g), np.full((2, 2), 4.0))
    v = paddle.to_tensor(np.array([[1.0, 0.0], [0.0, 0.0]], np.float32))
    _, g2 = fa.vjp(_mm, x, v)
    np.testing.assert_allclose(np.asarray(g2),
                               np.array([[2.0, 1.0], [1.0, 0.0]]))


def test_jvp_matches_reference_docstring():
    x = paddle.ones([2, 2], dtype="float32")
    out, dy = fa.jvp(_mm, x)
    np.testing.assert_allclose(np.asarray(dy), np.full((2, 2), 4.0))
    v = paddle.to_tensor(np.array([[1.0, 0.0], [0.0, 0.0]], np.float32))
    _, dy2 = fa.jvp(_mm, x, v)
    # d(x@x)[v] = v@x + x@v with x = ones
    np.testing.assert_allclose(np.asarray(dy2),
                               np.array([[2.0, 1.0], [1.0, 0.0]]))


def test_vjp_multi_input_output_and_shape_check():
    def f(a, b):
        return a * b, (a + b).sum()

    a = paddle.to_tensor(np.arange(4, dtype=np.float32))
    b = paddle.to_tensor(np.ones(4, np.float32) * 2)
    (ya, yb), (ga, gb) = fa.vjp(f, [a, b])
    np.testing.assert_allclose(np.asarray(ya),
                               np.arange(4, dtype=np.float32) * 2)
    assert float(yb) == 14.0  # sum(0..3) + 4*2
    # d(a*b)/da * 1 + d(sum(a+b))/da * 1 = b + 1
    np.testing.assert_allclose(np.asarray(ga), np.full(4, 3.0))
    np.testing.assert_allclose(np.asarray(gb),
                               np.arange(4, dtype=np.float32) + 1)
    with pytest.raises(RuntimeError, match="shape"):
        fa.vjp(_mm, paddle.ones([2, 2]), paddle.ones([3, 3]))


def test_jacobian_matches_reference_docstring():
    x = paddle.to_tensor(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
    J = fa.Jacobian(lambda a, b: paddle.matmul(a, b), [x, x])
    assert J.shape == (4, 8)
    expect = np.array(
        [[1., 3., 0., 0., 1., 0., 2., 0.],
         [2., 4., 0., 0., 0., 1., 0., 2.],
         [0., 0., 1., 3., 3., 0., 4., 0.],
         [0., 0., 2., 4., 0., 3., 0., 4.]], np.float32)
    np.testing.assert_allclose(np.asarray(J[:, :]), expect, atol=1e-6)
    np.testing.assert_allclose(np.asarray(J[0, :]), expect[0], atol=1e-6)
    np.testing.assert_allclose(np.asarray(J[:, 0]), expect[:, 0],
                               atol=1e-6)


def test_jacobian_lazy_rows_cached():
    x = paddle.to_tensor(np.linspace(0.1, 1.0, 4).astype(np.float32))
    J = fa.Jacobian(lambda a: paddle.exp(a), x)
    _ = J[1, :]
    assert set(J._rows) == {1}  # only the requested row evaluated
    _ = J[1, :]
    assert set(J._rows) == {1}  # memoized
    # column fast path: no rows materialized, column memoized
    J2 = fa.Jacobian(lambda a: paddle.exp(a), x)
    col = np.asarray(J2[:, 2])
    assert not J2._rows and set(J2._cols) == {2}
    # fast path survives a prior partial row access
    _ = J2[0, :]
    _ = J2[:, 1]
    assert set(J2._rows) == {0} and set(J2._cols) == {1, 2}
    expect = np.zeros(4, np.float32)
    expect[2] = np.exp(np.linspace(0.1, 1.0, 4).astype(np.float32)[2])
    np.testing.assert_allclose(col, expect, rtol=1e-6)


def test_jacobian_numeric_diff():
    rng = np.random.RandomState(0)
    x0 = rng.randn(3).astype(np.float32)

    def f(a):
        return paddle.tanh(a) * paddle.concat(
            [a[1:], a[:1]]) + (a * a).sum()

    J = np.asarray(fa.Jacobian(f, paddle.to_tensor(x0))[:, :])
    eps = 1e-3
    for j in range(3):
        xp, xm = x0.copy(), x0.copy()
        xp[j] += eps
        xm[j] -= eps
        fp = np.asarray(f(paddle.to_tensor(xp)))
        fm = np.asarray(f(paddle.to_tensor(xm)))
        np.testing.assert_allclose(J[:, j], (fp - fm) / (2 * eps),
                                   atol=5e-3)


def test_jacobian_batched():
    rng = np.random.RandomState(1)
    x0 = rng.randn(3, 2).astype(np.float32)
    w = paddle.to_tensor(rng.randn(2, 2).astype(np.float32))

    def f(a):
        return paddle.matmul(a, w)

    J = fa.Jacobian(f, paddle.to_tensor(x0), is_batched=True)
    assert J.shape == (3, 2, 2)
    got = np.asarray(J[:, :, :])
    expect = np.broadcast_to(np.asarray(w).T, (3, 2, 2))
    np.testing.assert_allclose(got, expect, atol=1e-6)
    np.testing.assert_allclose(np.asarray(J[:, 1, 0]), expect[:, 1, 0],
                               atol=1e-6)


def test_hessian_matches_reference_docstring():
    x = paddle.to_tensor(np.random.RandomState(2)
                         .rand(2, 2).astype(np.float32))
    h = fa.Hessian(lambda a: (a * a).sum(), x)
    assert h.shape == (4, 4)
    np.testing.assert_allclose(np.asarray(h[:]),
                               2.0 * np.eye(4, dtype=np.float32),
                               atol=1e-5)


def test_hessian_batched_and_scalar_check():
    x = paddle.to_tensor(np.random.RandomState(3)
                         .rand(3, 2).astype(np.float32))
    h = fa.Hessian(lambda a: (a * a).sum(axis=-1, keepdim=True), x,
                   is_batched=True)
    got = np.asarray(h[:, :, :])
    expect = np.broadcast_to(2.0 * np.eye(2, dtype=np.float32), (3, 2, 2))
    np.testing.assert_allclose(got, expect, atol=1e-5)
    with pytest.raises(RuntimeError, match="single element"):
        fa.Hessian(lambda a: a * a, paddle.to_tensor(np.ones(2, np.float32)))[:]


def test_forward_grad_functional_form():
    x = paddle.ones([2, 2], dtype="float32")
    dy = fa.forward_grad(_mm, x)
    np.testing.assert_allclose(np.asarray(dy), np.full((2, 2), 4.0))
    with pytest.raises(TypeError, match="static"):
        fa.forward_grad(x, x)


def test_namespace_import_paths():
    import paddle_tpu.incubate as incubate

    assert incubate.autograd.vjp is fa.vjp
    assert incubate.autograd.Jacobian is fa.Jacobian
