"""Test harness: run everything on CPU with 8 virtual XLA devices so the
multi-chip sharding paths compile and execute without TPU hardware —
SURVEY §4 "multi-node testing without a cluster" TPU equivalent.
Must run before jax initializes a backend.
"""
import os

# Host env points JAX_PLATFORMS at the axon TPU plugin, and the axon
# sitecustomize imports jax at interpreter start — so env vars alone are
# too late. XLA_FLAGS is read lazily at backend init, and jax.config can
# still flip the platform before first use.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# numerical-parity tests need exact fp32 matmuls; production keeps the
# fast MXU default (bf16 passes) — this only affects the test process.
jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: exhaustive sweeps excluded from the timed tier-1 gate "
        "(ROADMAP runs with -m 'not slow')")


@pytest.fixture(autouse=True)
def _seed_all():
    import paddle_tpu

    paddle_tpu.seed(42)
    np.random.seed(42)
    yield
