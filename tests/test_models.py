"""Model-zoo tests: BERT/ERNIE encoder family + ResNet bf16 training.

Mirrors the reference's model test tier (the PaddleNLP BERT the CI bench
drives via tools/ci_model_benchmark.sh, and hybrid_parallel tests' tiny
transformers): build small configs, check shapes, train a few steps and
assert the loss moves the right way in both eager and compiled paths.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.jit as jit
import paddle_tpu.nn.functional as F
from paddle_tpu.models import (BertConfig, BertForPretraining,
                               BertForSequenceClassification, BertModel,
                               ErnieModel)


@pytest.fixture
def tiny_cfg():
    return BertConfig.tiny(vocab=97, hidden=32, layers=2, heads=2, seq=16)


def test_bert_forward_shapes(tiny_cfg):
    paddle.seed(0)
    model = BertModel(tiny_cfg)
    ids = paddle.to_tensor(np.random.randint(0, 97, (3, 16), np.int32))
    hidden, pooled = model(ids)
    assert hidden.shape == [3, 16, 32]
    assert pooled.shape == [3, 32]


def test_bert_attention_mask_effect(tiny_cfg):
    """Masked positions must not influence other positions' outputs."""
    paddle.seed(0)
    model = BertModel(tiny_cfg)
    model.eval()
    ids = np.random.randint(0, 97, (1, 16), np.int32)
    mask = np.ones((1, 16), np.float32)
    mask[0, 8:] = 0.0
    h1, _ = model(paddle.to_tensor(ids),
                  attention_mask=paddle.to_tensor(mask))
    ids2 = ids.copy()
    ids2[0, 8:] = (ids2[0, 8:] + 1) % 97  # change only masked tokens
    h2, _ = model(paddle.to_tensor(ids2),
                  attention_mask=paddle.to_tensor(mask))
    np.testing.assert_allclose(np.asarray(h1._array)[0, :8],
                               np.asarray(h2._array)[0, :8],
                               rtol=2e-5, atol=2e-5)


def test_bert_classifier_trains_eager(tiny_cfg):
    paddle.seed(0)
    model = BertForSequenceClassification(tiny_cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    ids = paddle.to_tensor(np.random.randint(0, 97, (8, 16), np.int32))
    labels = paddle.to_tensor(np.random.randint(0, 2, (8,), np.int64))
    losses = []
    for _ in range(8):
        loss = model(ids, labels=labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_bert_classifier_trainstep_parity(tiny_cfg):
    """Compiled TrainStep must match the eager loop step for step."""
    ids_np = np.random.randint(0, 97, (8, 16), np.int32)
    lab_np = np.random.randint(0, 2, (8,), np.int64)

    def run(compiled):
        paddle.seed(0)
        model = BertForSequenceClassification(tiny_cfg)
        model.eval()
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        ids = paddle.to_tensor(ids_np)
        labels = paddle.to_tensor(lab_np)
        out = []
        if compiled:
            step = jit.TrainStep(model, opt, model.loss_fn)
            for _ in range(4):
                out.append(float(step(ids, labels)))
        else:
            for _ in range(4):
                loss = model(ids, labels=labels)
                loss.backward()
                opt.step()
                opt.clear_grad()
                out.append(float(loss))
        return out

    eager = run(False)
    comp = run(True)
    np.testing.assert_allclose(eager, comp, rtol=1e-4, atol=1e-5)


def test_bert_pretraining_loss(tiny_cfg):
    paddle.seed(0)
    model = BertForPretraining(tiny_cfg)
    ids = paddle.to_tensor(np.random.randint(0, 97, (2, 16), np.int32))
    mlm = np.full((2, 16), -100, np.int64)
    mlm[:, :4] = np.random.randint(0, 97, (2, 4))
    nsp = paddle.to_tensor(np.array([0, 1], np.int64))
    loss = model(ids, mlm_labels=paddle.to_tensor(mlm), nsp_labels=nsp)
    assert np.isfinite(float(loss))


def test_ernie_is_bert_graph(tiny_cfg):
    paddle.seed(0)
    model = ErnieModel(tiny_cfg)
    ids = paddle.to_tensor(np.random.randint(0, 97, (2, 16), np.int32))
    hidden, pooled = model(ids)
    assert hidden.shape == [2, 16, 32]


def test_resnet_bf16_trainstep():
    """bf16 conv training through the compiled step (the resnet50 bench
    path, shrunk): regression for the conv transpose-rule dtype crash."""
    from paddle_tpu.vision.models import resnet18

    paddle.seed(0)
    model = resnet18(num_classes=10)
    model.to(dtype="bfloat16")
    opt = paddle.optimizer.Momentum(learning_rate=0.01, momentum=0.9,
                                    parameters=model.parameters())
    step = jit.TrainStep(model, opt, F.cross_entropy)
    imgs = paddle.to_tensor(np.random.uniform(
        -1, 1, (2, 4, 3, 32, 32)).astype(np.float32)).astype("bfloat16")
    labels = paddle.to_tensor(np.random.randint(0, 10, (2, 4), np.int64))
    losses = step.run_scan(imgs, labels)
    assert np.all(np.isfinite(np.asarray(losses._array)))
