"""Module-level worker functions for dist.spawn tests (the spawn start
method pickles targets by reference, so they must be importable)."""
import json
import os

import numpy as np


def allreduce_worker(out_dir):
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    dist.init_parallel_env()
    r = dist.get_rank()
    t = paddle.to_tensor(np.full((2,), float(r + 1), np.float32))
    dist.all_reduce(t)
    with open(os.path.join(out_dir, f"rank{r}.json"), "w") as f:
        json.dump(np.asarray(t._array).tolist(), f)


def failing_worker():
    raise ValueError("boom from a rank")


def record_metric_events(reg, rank):
    """Deterministic per-rank metric trace, shared by the aggregation
    worker and the test's single-process replay so the two folds see
    bit-identical events."""
    c = reg.counter("w_requests_total", "requests", labelnames=("verb",))
    for _ in range(rank + 1):
        c.labels(verb="GET").inc()
    if rank % 2:
        c.labels(verb="PUT").inc(2)          # series absent on even ranks
    reg.gauge("w_depth", "queue depth").set(10.0 * rank + 1.0)
    h = reg.histogram("w_latency_seconds", "latency",
                      buckets=(0.001, 0.01, 0.1, 1.0))
    for i in range(3 * (rank + 1)):
        h.observe(0.0007 * (i + 1) * (rank + 1))


def metrics_aggregate_worker(out_dir):
    """Each rank records its own events, then folds snapshots over the
    group collectives; every rank writes the merged result (they must
    agree — the fold is a collective)."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.observability import MetricsRegistry, aggregate

    dist.init_parallel_env()
    r = dist.get_rank()
    reg = MetricsRegistry()
    record_metric_events(reg, r)
    merged = aggregate(registry=reg)
    with open(os.path.join(out_dir, f"agg_rank{r}.json"), "w") as f:
        json.dump(merged, f, sort_keys=True)
