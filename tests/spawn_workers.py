"""Module-level worker functions for dist.spawn tests (the spawn start
method pickles targets by reference, so they must be importable)."""
import json
import os

import numpy as np


def allreduce_worker(out_dir):
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    dist.init_parallel_env()
    r = dist.get_rank()
    t = paddle.to_tensor(np.full((2,), float(r + 1), np.float32))
    dist.all_reduce(t)
    with open(os.path.join(out_dir, f"rank{r}.json"), "w") as f:
        json.dump(np.asarray(t._array).tolist(), f)


def failing_worker():
    raise ValueError("boom from a rank")
