"""Speculative decoding on the paged engine (ISSUE 7).

The exact-acceptance contract, proven the way PR 3/6 proved theirs:
speculative output must be TOKEN-IDENTICAL to the non-speculative
engine (and to the single-request compiled-decode oracle) for every
(backend, prefill-mode, cache-state, K) combination and for ANY
drafter — a perfect drafter only compresses steps, an adversarial one
only wastes verify columns. Plus: `decode_traces == 1` per
(backend, K) with steady-state `expect_traces(0)`; speculative writes
into shared/registered prefix blocks COW-promote first (cached KV
byte-identical via `dense_gather_reference`, rollback never resurrects
a shared block); multi-token TPOT/accepted-tokens accounting; K=0
building today's decode step bit-for-bit; the `PADDLE_SPEC_DECODE_K`
env override; and the n-gram drafter's lookup mechanics.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.jit as jit
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.inference import GenerationEngine, NgramDrafter
from paddle_tpu.observability.metrics import series_total

VOCAB = 61


def _model(seed=0):
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(seed)
    cfg = GPTConfig.tiny(vocab=VOCAB, hidden=32, layers=2, heads=2,
                         seq=64)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return _model()


def _reference(model, prompt, max_new, eos=None):
    out = model.generate(
        Tensor._wrap(np.asarray(prompt, np.int32)[None]),
        max_length=len(prompt) + max_new, eos_token_id=eos,
        use_cache=True)
    return np.asarray(out._array)[0]


class OracleDrafter:
    """A PERFECT drafter: proposes the oracle continuation itself, so
    every verify step must accept its whole window. This is the seam a
    tiny draft GPT plugs into, driven at its best case — and the
    exact-acceptance contract probed from the other side (accepting
    everything must still emit exactly the oracle stream)."""

    def __init__(self):
        self.table = {}

    def register(self, model, prompt, max_new):
        full = _reference(model, prompt, max_new)
        self.table[np.asarray(prompt, np.int32).tobytes()] = \
            [int(t) for t in full]

    def propose(self, prompt, generated, k):
        cont = self.table.get(np.asarray(prompt, np.int32).tobytes())
        if cont is None:
            return []
        start = len(np.asarray(prompt).reshape(-1)) + len(generated)
        return cont[start:start + k]


class WrongDrafter(OracleDrafter):
    """An ADVERSARIAL drafter: proposes a token guaranteed to mismatch
    the target's argmax (oracle token + 1 mod vocab), so NOTHING is
    ever accepted beyond the target's own next token — and the output
    must still be exact."""

    def propose(self, prompt, generated, k):
        return [(t + 1) % VOCAB
                for t in super().propose(prompt, generated, k)]


# ---------------------------------------------------------------------------
# satellite: the n-gram / prompt-lookup drafter
# ---------------------------------------------------------------------------

def test_ngram_drafter_lookup_mechanics():
    d = NgramDrafter(max_ngram=3, min_ngram=1)
    # suffix [8, 9] last occurred earlier, followed by 10, 11
    assert d.propose([1, 8, 9, 10, 11, 8, 9], [], 2) == [10, 11]
    # proposals cap at k and at the context end
    assert d.propose([1, 8, 9, 10, 11, 8, 9], [], 1) == [10]
    assert d.propose([8, 9, 10, 8, 9], [], 8) == [10, 8, 9]
    # generated tokens extend the searchable context
    assert d.propose([5, 6, 7], [5, 6], 2) == [7, 5]
    # longest n-gram wins: suffix ..., 2, 3 matches the 2-gram at the
    # front (-> 4), not the more recent 1-gram [3] (-> 9)
    assert d.propose([2, 3, 4, 3, 9, 2, 3], [], 1) == [4]
    # no earlier occurrence -> no proposal
    assert d.propose([1, 2, 3, 4], [], 4) == []
    # min_ngram > available match length -> no proposal
    assert NgramDrafter(max_ngram=3, min_ngram=2).propose(
        [7, 1, 2, 3, 7], [], 2) == []
    with pytest.raises(ValueError):
        NgramDrafter(max_ngram=1, min_ngram=2)
    with pytest.raises(ValueError):
        NgramDrafter(min_ngram=0)


# ---------------------------------------------------------------------------
# tentpole: token-exact parity across the whole serving matrix
# ---------------------------------------------------------------------------

def _trace(rng, n):
    return [(rng.randint(0, VOCAB, rng.randint(1, 14)).astype(np.int32),
             int(rng.randint(2, 9))) for _ in range(n)]


def _run_trace(eng, reqs, midrun=True):
    ids = [eng.add_request(p, n) for p, n in reqs[:len(reqs) // 2]]
    if midrun:
        for _ in range(2):
            eng.step()                 # admissions land mid-decode
    ids += [eng.add_request(p, n) for p, n in reqs[len(reqs) // 2:]]
    out = eng.run()
    return [np.asarray(out[rid]) for rid in ids]


@pytest.mark.parametrize("backend", ["dense", "pallas"])
def test_spec_token_identical_across_modes(model, monkeypatch, backend):
    """THE acceptance gate: one mixed trace (repetitive prompts the
    n-gram drafter hits, shared prefixes, a block-aligned full-prefix
    hit, mid-run admissions) through the speculative engine in
    (a) chunked + prefix cache, cold, (b) same engine warm,
    (c) legacy bucketed prefill — all token-identical to the
    single-request oracle, under both paged-attention backends, with
    decode_traces == 1 per (backend, K) and steady state retracing
    NOTHING."""
    monkeypatch.delenv("PADDLE_SPEC_DECODE_K", raising=False)
    monkeypatch.delenv("PADDLE_PAGED_ATTENTION_BACKEND", raising=False)
    rng = np.random.RandomState(11)
    base = _trace(rng, 4)
    motif = rng.randint(0, VOCAB, 4)
    shared = rng.randint(0, VOCAB, 8).astype(np.int32)   # hot prefix
    reqs = base + [
        (np.tile(motif, 5).astype(np.int32), 8),   # drafter food
        (np.concatenate([shared, rng.randint(0, VOCAB, 3)])
         .astype(np.int32), 4),
        (shared.copy(), 4),            # block-aligned full-prefix hit
    ]
    K = 2

    def mk(**kw):
        return GenerationEngine(model, num_slots=3, block_size=4,
                                num_blocks=64, spec_decode_k=K,
                                attention_backend=backend, **kw)

    eng = mk(prefill_chunk=8)
    outs_cold = _run_trace(eng, reqs)
    outs_warm = _run_trace(eng, reqs, midrun=False)   # same engine
    eng_bucketed = mk(prefill_buckets=(16, 64))
    outs_bucketed = _run_trace(eng_bucketed, reqs)

    for (p, n), a, b, c in zip(reqs, outs_cold, outs_warm,
                               outs_bucketed):
        want = _reference(model, p, n)
        np.testing.assert_array_equal(a, want)
        np.testing.assert_array_equal(b, want)
        np.testing.assert_array_equal(c, want)

    # the warm pass re-served the prompts from the prefix cache
    assert eng.prefix_hit_tokens > 0
    # ONE verify program per (backend, K) across all of that churn;
    # prefill traces stay bounded by the chunk shape (1) / bucket count
    for e in (eng, eng_bucketed):
        assert e.decode_traces == 1
        assert e._decode_pure.__name__ == "engine_verify_step"
    assert eng.prefill_traces == 1
    assert eng_bucketed.prefill_traces <= 2   # one per bucket hit
    # steady state: a warmed speculative engine retraces NOTHING
    with jit.expect_traces(eng._decode_pure, 0), \
            jit.expect_traces(eng._prefill_pure, 0):
        eng.add_request(np.tile(motif, 4).astype(np.int32), 4)
        eng.run()
    assert eng.cache.num_free == eng.cache.num_blocks - 1


def test_spec_eos_early_stop_mid_window(model):
    """An EOS the verify step accepts mid-window must truncate the
    emission AT the EOS — trailing accepted tokens are dropped exactly
    like the one-token path never would have produced them."""
    rng = np.random.RandomState(5)
    prompt = rng.randint(0, VOCAB, 6).astype(np.int32)
    plain = _reference(model, prompt, 12)
    eos = int(plain[len(prompt) + 2])            # 3rd generated token
    ref_eos = _reference(model, prompt, 12, eos=eos)

    oracle = OracleDrafter()
    oracle.register(model, prompt, 12)           # drafts PAST the eos
    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           num_blocks=64, prefill_chunk=8,
                           spec_decode_k=4, drafter=oracle)
    rid = eng.add_request(prompt, 12, eos_token_id=eos)
    got = list(eng.run()[rid])
    assert got[-1] == eos and len(got) < len(prompt) + 12
    np.testing.assert_array_equal(got, ref_eos[:len(got)])


@pytest.mark.parametrize("drafter_cls, want_rate",
                         [(OracleDrafter, 1.0), (WrongDrafter, 0.0)])
def test_drafter_quality_never_changes_tokens(model, drafter_cls,
                                              want_rate):
    """The drafter seam driven at both extremes: a perfect drafter
    accepts every window (fewer verify steps than tokens, hit rate 1)
    and an adversarial drafter accepts nothing (hit rate 0) — both
    emit exactly the oracle stream."""
    rng = np.random.RandomState(7)
    reqs = [(rng.randint(0, VOCAB, 5).astype(np.int32), 9)
            for _ in range(2)]
    drafter = drafter_cls()
    for p, n in reqs:
        drafter.register(model, p, n)
    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           num_blocks=64, prefill_chunk=8,
                           spec_decode_k=3, drafter=drafter)
    ids = [eng.add_request(p, n) for p, n in reqs]
    out = eng.run()
    for (p, n), rid in zip(reqs, ids):
        np.testing.assert_array_equal(np.asarray(out[rid]),
                                      _reference(model, p, n))
    snap = eng.metrics_snapshot()
    rate = snap["engine_spec_draft_hit_rate"]["series"][0]["value"]
    assert rate == want_rate
    fam = snap["engine_spec_accepted_tokens"]["series"][0]
    # every generated token was emitted by a verify step (prompts are
    # 5 tokens into 4-token blocks: no full-prefix hits, so the first
    # token comes from prefill and the rest from verify windows)
    assert fam["sum"] == series_total(
        snap, "engine_tokens_generated_total") - len(reqs)
    if drafter_cls is OracleDrafter:
        # K=3 windows emit up to 4 tokens: strictly fewer steps than
        # tokens is the whole point of speculation
        assert fam["count"] < fam["sum"]
    else:
        assert fam["count"] == fam["sum"]      # 1 token per step


# ---------------------------------------------------------------------------
# satellite: speculative writes vs the prefix cache (COW + rollback)
# ---------------------------------------------------------------------------

def test_spec_cow_keeps_cached_blocks_byte_identical(model):
    """A warm-cache speculative run: the second request seats ALL its
    blocks read-only from the prefix cache and its verify windows
    write straight into that footprint — every touched block must
    COW-promote BEFORE the verify step writes, the cached KV must stay
    byte-identical (dense_gather_reference), and rollback must never
    resurrect a shared block (a fresh match still returns the original
    block ids, pristine)."""
    from paddle_tpu.ops.paged_attention import dense_gather_reference

    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           num_blocks=32, prefill_chunk=8,
                           spec_decode_k=3)
    rng = np.random.RandomState(5)
    prompt = rng.randint(0, VOCAB, 8).astype(np.int32)  # 2 full blocks
    want = _reference(model, prompt, 6)

    ra = eng.add_request(prompt, 6)
    np.testing.assert_array_equal(np.asarray(eng.run()[ra]), want)
    cached, hit = eng.cache.match_prefix(prompt)
    assert hit == 8
    row = np.zeros(eng.max_blocks, np.int32)
    row[:len(cached)] = cached
    gk0, gv0 = dense_gather_reference(eng.cache.kpool, eng.cache.vpool,
                                      0, row, 8)
    eng.cache.free(cached)

    # second serve: full-prefix hit -> the FIRST verify window's write
    # position sits inside a registered cached block
    cow0 = series_total(eng.metrics_snapshot(),
                        "engine_cow_copies_total")
    rb = eng.add_request(prompt, 6)
    np.testing.assert_array_equal(np.asarray(eng.run()[rb]), want)
    snap = eng.metrics_snapshot()
    assert series_total(snap, "engine_cow_copies_total") > cow0
    # the cached blocks' KV is byte-identical after the speculative
    # run (accepted writes AND rolled-back rejects both landed in the
    # private COW copy, never the shared block)
    gk1, gv1 = dense_gather_reference(eng.cache.kpool, eng.cache.vpool,
                                      0, row, 8)
    np.testing.assert_array_equal(np.asarray(gk0), np.asarray(gk1))
    np.testing.assert_array_equal(np.asarray(gv0), np.asarray(gv1))
    # rollback never resurrected the shared blocks: a fresh match
    # still serves the ORIGINAL block ids, and a third request served
    # from them is exact
    again, hit = eng.cache.match_prefix(prompt)
    assert hit == 8 and again == cached
    eng.cache.free(again)
    rc = eng.add_request(prompt, 6)
    np.testing.assert_array_equal(np.asarray(eng.run()[rc]), want)


def test_spec_cow_pressure_sheds_draft_instead_of_deadlocking(model):
    """An oversubscribed pool where the COW copy for a warm-cache lane
    cannot be served WHILE that lane holds freshly-allocated window
    blocks: the lane must shed its draft and return the surplus tail
    blocks so the plain one-token window can proceed — not sit on
    them and deadlock a pool the K=0 engine completes on."""

    class GreedyDrafter:
        def propose(self, prompt, generated, k):
            return [0] * k             # always drafts a full window

    eng = GenerationEngine(model, num_slots=1, block_size=4,
                           num_blocks=4, prefill_chunk=8,
                           spec_decode_k=4, drafter=GreedyDrafter())
    rng = np.random.RandomState(5)
    prompt = rng.randint(0, VOCAB, 8).astype(np.int32)  # 2 full blocks
    want = _reference(model, prompt, 4)
    ra = eng.add_request(prompt, 4)    # fills + registers the cache
    np.testing.assert_array_equal(np.asarray(eng.run()[ra]), want)
    # second serve: full-prefix hit seats both cached blocks, the
    # window grabs the last free block, and the COW copy for the
    # feed block then has NOTHING left — the draft must be shed
    rb = eng.add_request(prompt, 4)
    np.testing.assert_array_equal(np.asarray(eng.run()[rb]), want)
    snap = eng.metrics_snapshot()
    assert series_total(snap, "engine_cow_copies_total") >= 1
    # the shed path actually fired: the COW copy DID fail under
    # pressure and the lane DEGRADED (ran draftless) — which must not
    # read as a skipped-iteration decode stall
    stalls = {s["labels"]["path"]: s["value"]
              for s in snap["engine_block_stalls_total"]["series"]}
    assert stalls.get("spec_degrade", 0) >= 1
    assert stalls.get("decode", 0) == 0
    assert eng.cache.num_free == eng.cache.num_blocks - 1


# ---------------------------------------------------------------------------
# satellite: multi-token-step latency + speculation accounting
# ---------------------------------------------------------------------------

def test_spec_multi_token_step_accounting(model):
    """With speculation, a decode step emits SEVERAL tokens: every
    accepted token must land in the TPOT histogram against its
    producing step (so per-request TPOT observations still equal
    generated-tokens - 1), engine_spec_accepted_tokens must record
    per-step emission counts, and the tokens counter must integrate
    exactly."""
    rng = np.random.RandomState(3)
    prompt = rng.randint(0, VOCAB, 5).astype(np.int32)
    oracle = OracleDrafter()
    oracle.register(model, prompt, 6)
    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           num_blocks=32, prefill_chunk=8,
                           spec_decode_k=2, drafter=oracle)
    rid = eng.add_request(prompt, 6, priority="interactive")
    np.testing.assert_array_equal(np.asarray(eng.run()[rid]),
                                  _reference(model, prompt, 6))
    snap = eng.metrics_snapshot()
    assert series_total(snap, "engine_tokens_generated_total") == 6
    # prefill emits token 1; perfect K=2 windows emit 3 then 2:
    # exactly 2 verify steps for the remaining 5 tokens
    fam = snap["engine_spec_accepted_tokens"]["series"][0]
    assert fam["count"] == 2 and fam["sum"] == 5
    # TPOT: one observation per token after the first, in the
    # request's priority series
    tpot = {s["labels"]["priority"]: s["count"]
            for s in snap["engine_tpot_seconds"]["series"]}
    assert tpot == {"interactive": 5}
    ttft = {s["labels"]["priority"]: s["count"]
            for s in snap["engine_ttft_seconds"]["series"]}
    assert ttft == {"interactive": 1}
    assert snap["engine_spec_draft_hit_rate"]["series"][0]["value"] \
        == 1.0


def test_spec_instant_finish_stays_visible(model):
    """The PR-6 instant-finish contract under speculation: a
    max_new==1 full-prefix-hit request takes its single token from a
    verify step and must still record that token's producing-step
    latency in the TPOT histogram."""
    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           num_blocks=32, prefill_chunk=8,
                           spec_decode_k=2)
    rng = np.random.RandomState(9)
    p = rng.randint(0, VOCAB, 8).astype(np.int32)   # block-aligned
    eng.add_request(p, 1)
    eng.run()
    eng.add_request(p, 1)                 # full hit -> verify path
    eng.run()
    snap = eng.metrics_snapshot()
    assert sum(s["count"]
               for s in snap["engine_tpot_seconds"]["series"]) == 2
    assert series_total(snap, "engine_tokens_generated_total") == 2


# ---------------------------------------------------------------------------
# satellite: K=0 recovers today's path; env override
# ---------------------------------------------------------------------------

def test_spec_k0_is_exactly_todays_decode_path(model, monkeypatch):
    monkeypatch.delenv("PADDLE_SPEC_DECODE_K", raising=False)
    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           num_blocks=32, prefill_chunk=8,
                           spec_decode_k=0)
    # K=0 builds the ORIGINAL one-token decode step (same function,
    # not a degenerate verify window) and loads no drafter
    assert eng._decode_pure.__name__ == "engine_decode_step"
    assert eng.drafter is None and eng.spec_decode_k == 0
    rng = np.random.RandomState(13)
    p = rng.randint(0, VOCAB, 6).astype(np.int32)
    rid = eng.add_request(p, 5)
    np.testing.assert_array_equal(np.asarray(eng.run()[rid]),
                                  _reference(model, p, 5))


# ---------------------------------------------------------------------------
# satellite: bench row (CI-scale runner + suite registration)
# ---------------------------------------------------------------------------

def test_speculative_bench_row(monkeypatch):
    """The gpt_engine_speculative SUITE_ROWS runner at test scale: the
    record must carry net tokens/s for both K=spec_k and the K=0
    baseline (token-identical outputs — asserted inside the runner),
    accepted-tokens/step >= 1 (every verify step nets a token), and
    the draft hit rate."""
    monkeypatch.delenv("PADDLE_SPEC_DECODE_K", raising=False)
    monkeypatch.delenv("PADDLE_PAGED_ATTENTION_BACKEND", raising=False)
    import bench_ops
    from paddle_tpu.models import GPTConfig

    cfg = GPTConfig.tiny(vocab=32, hidden=16, layers=1, heads=2, seq=64)
    paddle.seed(0)
    rec = bench_ops._engine_speculative_case(
        model_cfg=cfg, num_requests=3, num_slots=2, block_size=4,
        prefill_chunk=8, spec_k=3, max_new=8)()
    assert rec["tokens_per_s"] > 0 and rec["tokens_per_s_k0"] > 0
    assert rec["accepted_tokens_per_step"] >= 1.0
    assert rec["verify_steps"] > 0
    assert 0.0 <= rec["draft_hit_rate"] <= 1.0
    assert rec["decode_recompiles"] == 0
    assert "gpt_engine_speculative" in bench_ops.suite_names()


def test_spec_env_override_wins(model, monkeypatch):
    monkeypatch.setenv("PADDLE_SPEC_DECODE_K", "3")
    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           num_blocks=32, prefill_chunk=8,
                           spec_decode_k=0)
    assert eng.spec_decode_k == 3
    assert eng._decode_pure.__name__ == "engine_verify_step"
    assert isinstance(eng.drafter, NgramDrafter)
    monkeypatch.setenv("PADDLE_SPEC_DECODE_K", "-1")
    with pytest.raises(ValueError, match="spec_decode_k"):
        GenerationEngine(model, num_slots=2, block_size=4,
                         prefill_chunk=8)
