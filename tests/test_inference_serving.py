"""paddle.inference serving tier tests: Config/create_predictor over a
saved artifact (AnalysisPredictor analog) and DistModel mesh-sharded
micro-batch streaming (fleet_executor/dist_model.cc analog) — including
mp=2 tensor-parallel serving parity on the virtual 8-device mesh.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import inference
from paddle_tpu.jit.api import InputSpec


def _net(d=8, h=16, out=4):
    paddle.seed(0)
    return nn.Sequential(nn.Linear(d, h), nn.ReLU(), nn.Linear(h, out))


def test_config_create_predictor_run(tmp_path):
    net = _net()
    path = str(tmp_path / "m")
    paddle.jit.save(net, path, input_spec=[InputSpec([None, 8])])

    cfg = inference.Config(path)
    pred = inference.create_predictor(cfg)
    x = np.random.RandomState(0).randn(5, 8).astype(np.float32)
    (out,) = pred.run([x])
    ref = net(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_predictor_input_names(tmp_path):
    net = _net()
    path = str(tmp_path / "m")
    paddle.jit.save(net, path, input_spec=[InputSpec([None, 8], name="x")])
    pred = inference.create_predictor(inference.Config(path))
    assert pred.get_input_names() == ["x"]


def test_dist_model_micro_batching_matches_full_batch():
    net = _net()
    cfg = inference.DistModelConfig(layer=net, dp=4, micro_batch_size=4)
    dm = inference.DistModel(cfg).init()
    x = np.random.RandomState(1).randn(16, 8).astype(np.float32)
    (out,) = dm.run([x])
    ref = net(paddle.to_tensor(x)).numpy()
    assert out.shape == (16, 4)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_dist_model_tensor_parallel_serving():
    """mp=2 serving: ColumnParallel/RowParallel weights shard over the
    mesh; output equals the single-device reference."""
    from paddle_tpu.distributed import (
        ColumnParallelLinear,
        RowParallelLinear,
    )
    from paddle_tpu.distributed.topology import (
        HybridCommunicateGroup,
        set_hybrid_communicate_group,
    )

    set_hybrid_communicate_group(HybridCommunicateGroup(dp=1, mp=2))
    paddle.seed(0)

    class MP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = ColumnParallelLinear(8, 32, gather_output=False)
            self.fc2 = RowParallelLinear(32, 4, input_is_parallel=True)

        def forward(self, x):
            return self.fc2(F.gelu(self.fc1(x)))

    mp_net = MP()
    x = np.random.RandomState(2).randn(6, 8).astype(np.float32)
    ref = mp_net(paddle.to_tensor(x)).numpy()

    dm = inference.DistModel(
        inference.DistModelConfig(layer=mp_net, dp=1, mp=2,
                                  micro_batch_size=3)).init()
    (out,) = dm.run([x])
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_dist_model_from_saved_artifact(tmp_path):
    net = _net()
    path = str(tmp_path / "m")
    paddle.jit.save(net, path, input_spec=[InputSpec([None, 8])])
    dm = inference.DistModel(
        inference.DistModelConfig(model_path=path,
                                  micro_batch_size=2)).init()
    x = np.random.RandomState(3).randn(6, 8).astype(np.float32)
    (out,) = dm.run([x])
    np.testing.assert_allclose(out, net(paddle.to_tensor(x)).numpy(),
                               rtol=1e-4, atol=1e-5)


def test_dist_model_rejects_oversubscription():
    with pytest.raises(ValueError, match="exceeds"):
        inference.DistModel(
            inference.DistModelConfig(layer=_net(), dp=64, mp=2)).init()


def test_dist_model_pads_nondivisible_tail():
    """Batch 18, dp=4, mbs=4: tail chunk of 2 pads to 4 and trims."""
    net = _net()
    dm = inference.DistModel(inference.DistModelConfig(
        layer=net, dp=4, micro_batch_size=4)).init()
    x = np.random.RandomState(5).randn(18, 8).astype(np.float32)
    (out,) = dm.run([x])
    assert out.shape == (18, 4)
    np.testing.assert_allclose(out, net(paddle.to_tensor(x)).numpy(),
                               rtol=1e-4, atol=1e-5)


def test_predictor_micro_batch_streaming(tmp_path):
    net = _net()
    path = str(tmp_path / "m")
    paddle.jit.save(net, path, input_spec=[InputSpec([None, 8])])
    cfg = inference.Config(path)
    cfg.set_micro_batch_size(4)
    pred = inference.create_predictor(cfg)
    x = np.random.RandomState(6).randn(10, 8).astype(np.float32)
    (out,) = pred.run([x])
    np.testing.assert_allclose(out, net(paddle.to_tensor(x)).numpy(),
                               rtol=1e-4, atol=1e-5)


def test_mixed_precision_requires_bf16_artifact(tmp_path):
    net = _net()
    path = str(tmp_path / "m32")
    paddle.jit.save(net, path, input_spec=[InputSpec([None, 8])])
    cfg = inference.Config(path)
    cfg.enable_mixed_precision()
    with pytest.raises(ValueError, match="bfloat16"):
        inference.create_predictor(cfg)
    # a convert='bfloat16' artifact passes the gate
    path2 = str(tmp_path / "mbf")
    paddle.jit.save(net, path2, input_spec=[InputSpec([None, 8])],
                    convert="bfloat16")
    cfg2 = inference.Config(path2)
    cfg2.enable_mixed_precision()
    pred = inference.create_predictor(cfg2)
    (out,) = pred.run([np.zeros((2, 8), np.float32)])
    assert out.shape == (2, 4)


def test_saved_artifact_serves_dp_sharded(tmp_path):
    """VERDICT r3 weak #6: save on one device, serve dp=4 on the mesh —
    the outer pjit reshards the deserialized exported program; outputs
    match the unsharded predictor."""
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.inference as infer
    import paddle_tpu.jit as jit
    import paddle_tpu.nn as nn
    from paddle_tpu.jit.api import InputSpec

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(6, 16), nn.Tanh(), nn.Linear(16, 3))
    net.eval()
    path = str(tmp_path / "dp_model")
    jit.save(net, path, input_spec=[InputSpec([8, 6], "float32")])

    x = np.random.RandomState(0).randn(8, 6).astype(np.float32)

    cfg1 = infer.Config(path)
    plain = infer.create_predictor(cfg1).run([x])[0]

    cfg4 = infer.Config(path)
    cfg4.set_dist_degrees(dp=4)
    pred = infer.create_predictor(cfg4)
    sharded = pred.run([x])[0]
    np.testing.assert_allclose(sharded, plain, rtol=1e-5, atol=1e-6)

    # mp over an artifact with NO recorded weight shardings refuses
    # with guidance (plain Linear layers carry no dist_spec)
    cfg_mp = infer.Config(path)
    cfg_mp.set_dist_degrees(dp=1, mp=2)
    with pytest.raises(ValueError, match="dist_specs"):
        infer.create_predictor(cfg_mp)

    # ragged batch: pad_to=dp trims back to the true rows
    x5 = x[:5]
    got5 = pred.run([x5])[0]
    np.testing.assert_allclose(got5, plain[:5], rtol=1e-5, atol=1e-6)


def test_distmodel_from_saved_path_dp(tmp_path):
    import paddle_tpu as paddle
    import paddle_tpu.inference as infer
    import paddle_tpu.jit as jit
    import paddle_tpu.nn as nn
    from paddle_tpu.jit.api import InputSpec

    paddle.seed(1)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    net.eval()
    path = str(tmp_path / "dm_model")
    jit.save(net, path, input_spec=[InputSpec([8, 4], "float32")])

    x = np.random.RandomState(1).randn(8, 4).astype(np.float32)
    want = infer.create_predictor(infer.Config(path)).run([x])[0]

    dm = infer.DistModel(infer.DistModelConfig(model_path=path, dp=4))
    dm.init()
    got = dm.run([x])[0]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_saved_artifact_serves_mp_sharded(tmp_path):
    """VERDICT r4 missing #3: save an mp-layered model on ONE device,
    serve it dp=2 x mp=2 on the 8-CPU mesh — jit.save records each
    weight's dist_spec (ColumnParallelLinear P(None,'mp'),
    RowParallelLinear P('mp',None)) and the serving pjit lays the
    weights out tensor-parallel; outputs match single-device serving."""
    import paddle_tpu as paddle
    import paddle_tpu.inference as infer
    import paddle_tpu.jit as jit
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed.mp_layers import (
        ColumnParallelLinear, RowParallelLinear,
    )
    from paddle_tpu.jit.api import InputSpec

    paddle.seed(1)

    class MpNet(nn.Layer):
        def __init__(self):
            super().__init__()
            self.col = ColumnParallelLinear(6, 16, gather_output=False)
            self.row = RowParallelLinear(16, 4, input_is_parallel=True)

        def forward(self, x):
            return self.row(paddle.tanh(self.col(x)))

    net = MpNet()
    net.eval()
    path = str(tmp_path / "mp_model")
    jit.save(net, path, input_spec=[InputSpec([8, 6], "float32")])

    # the artifact recorded the layer-level shardings
    import json as _json

    with open(path + ".json") as f:
        meta = _json.load(f)
    assert [None, "mp"] in meta["state_dist_specs"]  # column weight
    assert ["mp", None] in meta["state_dist_specs"]  # row weight

    x = np.random.RandomState(1).randn(8, 6).astype(np.float32)
    plain = infer.create_predictor(infer.Config(path)).run([x])[0]

    cfg = infer.Config(path)
    cfg.set_dist_degrees(dp=2, mp=2)
    pred = infer.create_predictor(cfg)
    sharded = pred.run([x])[0]
    np.testing.assert_allclose(sharded, plain, rtol=1e-5, atol=1e-6)

    # DistModel over the same artifact, same layout
    dm = infer.DistModel(infer.DistModelConfig(model_path=path, dp=2,
                                               mp=2)).init()
    np.testing.assert_allclose(dm.run([x])[0], plain, rtol=1e-5,
                               atol=1e-6)


def test_foreign_axis_dist_specs_serve_replicated(tmp_path):
    """A weight sharded over an axis the serving mesh doesn't model
    (e.g. MoE 'ep') is served replicated along that dim instead of
    crashing predictor construction — dp serving of re-saved MoE
    artifacts keeps working."""
    import json as _json

    import paddle_tpu as paddle
    import paddle_tpu.inference as infer
    import paddle_tpu.jit as jit
    import paddle_tpu.nn as nn
    from paddle_tpu.jit.api import InputSpec

    paddle.seed(2)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    net.eval()
    # simulate an expert-parallel weight annotation
    from jax.sharding import PartitionSpec as P

    net[0].weight.dist_spec = P("ep", None)
    path = str(tmp_path / "ep_model")
    jit.save(net, path, input_spec=[InputSpec([8, 4], "float32")])
    with open(path + ".json") as f:
        assert ["ep", None] in _json.load(f)["state_dist_specs"]

    x = np.random.RandomState(7).randn(8, 4).astype(np.float32)
    plain = infer.create_predictor(infer.Config(path)).run([x])[0]
    cfg = infer.Config(path)
    cfg.set_dist_degrees(dp=2)
    out = infer.create_predictor(cfg).run([x])[0]
    np.testing.assert_allclose(out, plain, rtol=1e-5, atol=1e-6)


def test_distmodel_weights_only_artifact_rejects_dist_degrees(tmp_path):
    """A weights-only artifact (saved without input_spec) cannot honor
    dp/mp>1 — DistModel must refuse loudly, not silently serve
    single-device."""
    net = _net()
    path = str(tmp_path / "weights_only")
    paddle.jit.save(net, path)  # no input_spec: no exported program
    with pytest.raises(ValueError, match="weights-only"):
        inference.DistModel(
            inference.DistModelConfig(model_path=path, mp=2)).init()
