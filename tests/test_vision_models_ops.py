"""Vision detection ops (roi_align/deform_conv2d/box_coder, reference
python/paddle/vision/ops.py) and the MobileNetV2/VGG/AlexNet model
families (python/paddle/vision/models/).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.vision import ops
from paddle_tpu.vision.models import (
    AlexNet,
    MobileNetV2,
    alexnet,
    mobilenet_v2,
    vgg11,
    vgg16,
)


# -- roi_align -----------------------------------------------------------
def test_roi_align_constant_feature_is_exact():
    """On a constant feature map every bilinear sample equals the
    constant, whatever the box."""
    x = np.full((1, 2, 8, 8), 3.5, np.float32)
    boxes = np.array([[0.7, 1.3, 5.2, 6.9]], np.float32)
    out = ops.roi_align(x, boxes, np.array([1]), output_size=3)
    assert out.shape == [1, 2, 3, 3]
    np.testing.assert_allclose(out.numpy(), 3.5, rtol=1e-6)


def test_roi_align_linear_ramp():
    """On f(y,x) = x the bin average equals the bin-center x coord."""
    W = 16
    ramp = np.tile(np.arange(W, dtype=np.float32), (W, 1))
    x = ramp[None, None]
    boxes = np.array([[2.0, 2.0, 10.0, 10.0]], np.float32)
    out = ops.roi_align(x, boxes, np.array([1]), output_size=2,
                        aligned=False)
    # box width 8, 2 bins of 4: centers at x=4 and x=8 -> sampled at
    # pixel centers (continuous coords minus the .5 alignment)
    v = out.numpy()[0, 0]
    assert v[0, 0] < v[0, 1]
    np.testing.assert_allclose(v[:, 1] - v[:, 0], 4.0, atol=1e-4)


def test_roi_align_batch_routing():
    """boxes_num routes rois to the right image."""
    x = np.zeros((2, 1, 4, 4), np.float32)
    x[0] = 1.0
    x[1] = 2.0
    boxes = np.array([[0, 0, 3, 3]] * 3, np.float32)
    out = ops.roi_align(x, boxes, np.array([2, 1]), output_size=1)
    np.testing.assert_allclose(out.numpy().ravel(), [1, 1, 2], rtol=1e-6)


# -- deform_conv2d -------------------------------------------------------
def test_deform_conv_zero_offset_equals_conv():
    rs = np.random.RandomState(0)
    x = rs.randn(2, 3, 8, 8).astype(np.float32)
    w = rs.randn(4, 3, 3, 3).astype(np.float32)
    off = np.zeros((2, 2 * 9, 8, 8), np.float32)
    out = ops.deform_conv2d(x, off, w, padding=1)
    ref = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w), padding=1)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-4)


def test_deform_conv_integer_shift():
    """A +1-pixel x-offset on every tap equals convolving the shifted
    image (interior pixels)."""
    rs = np.random.RandomState(1)
    x = rs.randn(1, 1, 10, 10).astype(np.float32)
    w = rs.randn(1, 1, 3, 3).astype(np.float32)
    off = np.zeros((1, 2 * 9, 10, 10), np.float32)
    off[:, 1::2] = 1.0  # dx = +1 on every tap
    out = ops.deform_conv2d(x, off, w, padding=1).numpy()
    x_shift = np.roll(x, -1, axis=3)
    ref = ops.deform_conv2d(x_shift, np.zeros_like(off), w,
                            padding=1).numpy()
    np.testing.assert_allclose(out[..., 2:-2, 2:-2], ref[..., 2:-2, 2:-2],
                               rtol=1e-4, atol=1e-4)


def test_deform_conv_v2_mask():
    """mask=0 kills the output entirely; mask=1 matches v1."""
    rs = np.random.RandomState(2)
    x = rs.randn(1, 2, 6, 6).astype(np.float32)
    w = rs.randn(3, 2, 3, 3).astype(np.float32)
    off = np.zeros((1, 2 * 9, 6, 6), np.float32)
    out0 = ops.deform_conv2d(x, off, w, padding=1,
                             mask=np.zeros((1, 9, 6, 6), np.float32))
    np.testing.assert_allclose(out0.numpy(), 0.0, atol=1e-6)
    out1 = ops.deform_conv2d(x, off, w, padding=1,
                             mask=np.ones((1, 9, 6, 6), np.float32))
    ref = ops.deform_conv2d(x, off, w, padding=1)
    np.testing.assert_allclose(out1.numpy(), ref.numpy(), rtol=1e-5)


# -- box_coder -----------------------------------------------------------
def test_box_coder_encode_decode_roundtrip():
    priors = np.array([[0, 0, 4, 4], [2, 2, 8, 8]], np.float32)
    var = np.full((2, 4), 0.1, np.float32)
    targets = np.array([[1, 1, 5, 5], [0, 0, 6, 6]], np.float32)
    enc = ops.box_coder(priors, var, targets).numpy()  # [T,P,4]
    assert enc.shape == (2, 2, 4)
    dec = ops.box_coder(priors, var, enc,
                        code_type="decode_center_size").numpy()
    # decoding each target's encoding against its prior recovers it
    for t in range(2):
        np.testing.assert_allclose(dec[t, t], targets[t], atol=1e-4)


# -- model families ------------------------------------------------------
@pytest.mark.parametrize("ctor,kw,feat", [
    (mobilenet_v2, {"num_classes": 10}, None),
    (mobilenet_v2, {"num_classes": 10, "scale": 0.5}, None),
    (vgg11, {"num_classes": 10}, None),
    (alexnet, {"num_classes": 10}, None),
])
def test_model_families_forward(ctor, kw, feat):
    paddle.seed(0)
    m = ctor(**kw)
    m.eval()
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(2, 3, 224, 224).astype(np.float32))
    out = m(x)
    assert out.shape == [2, 10]
    assert np.isfinite(out.numpy()).all()


def test_mobilenet_trains():
    from paddle_tpu.jit import TrainStep

    paddle.seed(0)
    m = mobilenet_v2(num_classes=4, scale=0.25)
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=m.parameters())
    step = TrainStep(m, opt, F.cross_entropy)
    rng = np.random.RandomState(0)
    x = rng.randn(8, 3, 32, 32).astype(np.float32)
    y = rng.randint(0, 4, (8,))
    losses = [float(step(paddle.to_tensor(x), label=paddle.to_tensor(y)))
              for _ in range(6)]
    assert losses[-1] < losses[0]


def test_vgg16_structure():
    m = vgg16(num_classes=10)
    convs = [l for _, l in m.named_sublayers()
             if isinstance(l, nn.Conv2D)]
    assert len(convs) == 13  # the "16" = 13 conv + 3 fc


def test_roi_align_and_deform_conv_are_differentiable():
    rs = np.random.RandomState(3)
    x = paddle.to_tensor(rs.randn(1, 2, 8, 8).astype(np.float32))
    x.stop_gradient = False
    out = ops.roi_align(x, np.array([[1, 1, 6, 6]], np.float32),
                        np.array([1]), output_size=2)
    out.sum().backward()
    assert x.grad is not None and \
        float(np.abs(np.asarray(x.grad._array)).sum()) > 0

    from paddle_tpu.core.tensor import Parameter

    x2 = paddle.to_tensor(rs.randn(1, 2, 6, 6).astype(np.float32))
    x2.stop_gradient = False
    w = Parameter(rs.randn(3, 2, 3, 3).astype(np.float32))
    off = paddle.to_tensor(np.zeros((1, 18, 6, 6), np.float32))
    off.stop_gradient = False
    b = Parameter(rs.randn(3).astype(np.float32))
    out2 = ops.deform_conv2d(x2, off, w, bias=b, padding=1)
    out2.sum().backward()
    for t in (x2, w, b, off):
        assert t.grad is not None, f"no grad for {t}"
