"""ISSUE 3: the fused Pallas paged-attention decode kernel and its
backend-dispatching seam.

Covers the tentpole and satellites: pallas-(interpret)-vs-dense token
exactness for a FULL engine run (mid-run admissions, EOS early-stops,
lane evictions) with decode-traces == 1 per backend and the pool-parity
probe via `dense_gather_reference`; block-table edge cases under both
backends (block-boundary positions, single-block contexts, a slot at
max_model_len - 1, idle all-null slots never polluting live blocks);
the dense fallback's fp32 PV-accumulation numerics against an fp64
reference at bf16; the import smoke (no JAX backend init); and the two
new bench rows being registered + `--pending`-flagged until a TPU
`--save` refresh adopts them.
"""
import json
import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.inference import GenerationEngine
from paddle_tpu.models import GPTConfig, GPTForCausalLM

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
VOCAB = 61


def _model(seed=0):
    paddle.seed(seed)
    cfg = GPTConfig.tiny(vocab=VOCAB, hidden=32, layers=2, heads=2,
                         seq=64)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return _model()


def _reference(model, prompt, max_new, eos=None):
    out = model.generate(
        Tensor._wrap(np.asarray(prompt, np.int32)[None]),
        max_length=len(prompt) + max_new, eos_token_id=eos,
        use_cache=True)
    return np.asarray(out._array)[0]


# -- op-level: block-table edge cases under both backends -----------------

def _np_step_reference(q, k_new, v_new, ctx_k, ctx_v, pos):
    """fp64 dense attention over one slot's context + this token."""
    kd = np.concatenate([ctx_k[:pos], k_new], 0).astype(np.float64)
    vd = np.concatenate([ctx_v[:pos], v_new], 0).astype(np.float64)
    d = q.shape[-1]
    logits = np.einsum("qhd,khd->hqk", q.astype(np.float64), kd) \
        / np.sqrt(d)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("hqk,khd->qhd", p, vd)


@pytest.mark.parametrize("backend", ["dense", "pallas"])
def test_block_table_edge_cases(backend):
    """Position exactly on a block boundary (the write opens a fresh
    block), a single-block context, a slot at max_model_len - 1 (full
    table walked), and an idle all-null slot whose garbage write must
    land in block 0 and nowhere else."""
    from paddle_tpu.ops.paged_attention import (
        dense_gather_reference, paged_attention_step)

    bs, maxb, H, D = 4, 4, 2, 8
    B, nb = 4, 20                       # slots; spare blocks stay 0
    rng = np.random.RandomState(3)
    tables = np.zeros((B, maxb), np.int32)
    tables[0, :2] = [1, 2]              # pos 4 = boundary: block 1 full,
    positions = np.zeros(B, np.int32)   # write opens block 2
    positions[0] = 4
    tables[1, :1] = [3]                 # single-block context, pos 2
    positions[1] = 2
    tables[2] = [4, 5, 6, 7]            # max_model_len - 1 = 15
    positions[2] = bs * maxb - 1
    # slot 3 idle: all-null table, pos 0, HUGE values — any pollution
    # of a live block or output would be macroscopic

    kpool = np.zeros((1, nb, bs, H, D), np.float32)
    vpool = np.zeros((1, nb, bs, H, D), np.float32)
    ctx_k = rng.randn(B, bs * maxb, H, D).astype(np.float32)
    ctx_v = rng.randn(B, bs * maxb, H, D).astype(np.float32)
    for b in range(3):
        for t in range(positions[b]):
            kpool[0, tables[b, t // bs], t % bs] = ctx_k[b, t]
            vpool[0, tables[b, t // bs], t % bs] = ctx_v[b, t]
    q = rng.randn(B, 1, H, D).astype(np.float32)
    k_new = rng.randn(B, 1, H, D).astype(np.float32)
    v_new = rng.randn(B, 1, H, D).astype(np.float32)
    k_new[3] = 1e4
    v_new[3] = 1e4

    out, kp, vp = paged_attention_step(q, k_new, v_new, kpool, vpool, 0,
                                       tables, positions,
                                       backend=backend)
    out = np.asarray(out._array)
    kp, vp = np.asarray(kp._array), np.asarray(vp._array)

    for b in range(3):                  # live slots: exact attention
        ref = _np_step_reference(q[b], k_new[b], v_new[b], ctx_k[b],
                                 ctx_v[b], int(positions[b]))
        np.testing.assert_allclose(out[b], ref, rtol=2e-4, atol=2e-5)
        # the written row landed at (table[pos//bs], pos%bs) and the
        # reassembled context is exactly [ctx[:pos], k_new]
        gk, gv = dense_gather_reference(kp, vp, 0, tables[b],
                                        int(positions[b]) + 1)
        np.testing.assert_allclose(
            gk, np.concatenate([ctx_k[b, :positions[b]], k_new[b]], 0),
            rtol=1e-6)
        np.testing.assert_allclose(
            gv, np.concatenate([ctx_v[b, :positions[b]], v_new[b]], 0),
            rtol=1e-6)

    # idle slot: its write went to the null block...
    np.testing.assert_allclose(kp[0, 0, 0], k_new[3, 0], rtol=1e-6)
    np.testing.assert_allclose(vp[0, 0, 0], v_new[3, 0], rtol=1e-6)
    # ...and nowhere else: every spare block is still zero, and no live
    # block picked up the 1e4 garbage
    np.testing.assert_array_equal(kp[0, 8:], 0.0)
    np.testing.assert_array_equal(vp[0, 8:], 0.0)
    assert np.abs(kp[0, 1:8]).max() < 100.0
    assert np.abs(vp[0, 1:8]).max() < 100.0


@pytest.mark.parametrize("backend", ["dense", "pallas"])
def test_shared_prefix_blocks_read_only_in_both_backends(backend):
    """The prefix-cache layout: two slots whose tables alias the SAME
    context blocks (a shared system prompt seated read-only) but own
    private write blocks — the post-COW invariant the engine
    guarantees. Both backends must (a) compute each slot's attention
    over the shared context exactly, and (b) leave the shared blocks'
    bytes untouched: the step's only writes land in each slot's own
    block."""
    from paddle_tpu.ops.paged_attention import (
        dense_gather_reference, paged_attention_step)

    bs, maxb, H, D = 4, 4, 2, 8
    nb = 12
    rng = np.random.RandomState(13)
    shared_blocks = [1, 2]              # 8 shared prefix tokens
    tables = np.zeros((2, maxb), np.int32)
    tables[0, :3] = shared_blocks + [3]   # slot 0 writes into block 3
    tables[1, :3] = shared_blocks + [4]   # slot 1 into block 4
    positions = np.asarray([8, 8], np.int32)   # both at the boundary

    kpool = np.zeros((1, nb, bs, H, D), np.float32)
    vpool = np.zeros((1, nb, bs, H, D), np.float32)
    ctx_k = rng.randn(2 * bs, H, D).astype(np.float32)
    ctx_v = rng.randn(2 * bs, H, D).astype(np.float32)
    for t in range(2 * bs):
        kpool[0, shared_blocks[t // bs], t % bs] = ctx_k[t]
        vpool[0, shared_blocks[t // bs], t % bs] = ctx_v[t]
    shared_k0 = kpool[0, shared_blocks].copy()
    shared_v0 = vpool[0, shared_blocks].copy()

    q = rng.randn(2, 1, H, D).astype(np.float32)
    k_new = rng.randn(2, 1, H, D).astype(np.float32)
    v_new = rng.randn(2, 1, H, D).astype(np.float32)
    out, kp, vp = paged_attention_step(q, k_new, v_new, kpool, vpool,
                                       0, tables, positions,
                                       backend=backend)
    out = np.asarray(out._array)
    kp, vp = np.asarray(kp._array), np.asarray(vp._array)

    ctx = np.broadcast_to(ctx_k, (2,) + ctx_k.shape)
    ctxv = np.broadcast_to(ctx_v, (2,) + ctx_v.shape)
    for b in range(2):
        ref = _np_step_reference(q[b], k_new[b], v_new[b], ctx[b],
                                 ctxv[b], 8)
        np.testing.assert_allclose(out[b], ref, rtol=2e-4, atol=2e-5)
        gk, gv = dense_gather_reference(kp, vp, 0, tables[b], 9)
        np.testing.assert_allclose(gk[-1], k_new[b, 0], rtol=1e-6)
        np.testing.assert_allclose(gv[-1], v_new[b, 0], rtol=1e-6)
    # the aliased context blocks are byte-identical to before the step
    np.testing.assert_array_equal(kp[0, shared_blocks], shared_k0)
    np.testing.assert_array_equal(vp[0, shared_blocks], shared_v0)


def test_backends_agree_bitwise_on_pool_writes():
    """The two backends must produce the SAME pool bytes (writes are
    scatter-vs-DMA of identical rows) and outputs within float
    tolerance of each other at a mixed-depth batch."""
    from paddle_tpu.ops.paged_attention import paged_attention_step

    bs, maxb, H, D = 4, 3, 2, 8
    B, nb = 3, 12
    rng = np.random.RandomState(11)
    kpool = rng.randn(1, nb, bs, H, D).astype(np.float32)
    vpool = rng.randn(1, nb, bs, H, D).astype(np.float32)
    tables = np.zeros((B, maxb), np.int32)
    tables[0, :3] = [1, 2, 3]
    tables[1, :1] = [4]
    tables[2, :2] = [5, 6]
    positions = np.asarray([9, 0, 7], np.int32)
    q = rng.randn(B, 1, H, D).astype(np.float32)
    kn = rng.randn(B, 1, H, D).astype(np.float32)
    vn = rng.randn(B, 1, H, D).astype(np.float32)

    res = {}
    for backend in ("dense", "pallas"):
        out, kp, vp = paged_attention_step(q, kn, vn, kpool, vpool, 0,
                                           tables, positions,
                                           backend=backend)
        res[backend] = (np.asarray(out._array), np.asarray(kp._array),
                        np.asarray(vp._array))
    np.testing.assert_array_equal(res["dense"][1], res["pallas"][1])
    np.testing.assert_array_equal(res["dense"][2], res["pallas"][2])
    np.testing.assert_allclose(res["dense"][0], res["pallas"][0],
                               rtol=2e-5, atol=2e-6)


# -- satellite: dense-fallback bf16 numerics ------------------------------

def test_dense_bf16_pv_accumulation_fp32(model=None):
    """The PV product must accumulate in fp32 across the block loop
    and cast to bf16 ONCE at the end. Near-uniform attention (tiny
    irregular logits) over large alternating +/-A value rows makes the
    true output a small residual that survives only if neither the
    probs nor a partial accumulator rounds to bf16 — the pre-fix path
    (probs cast to q.dtype, PV materialized at q.dtype) leaves an
    O(A * bf16_eps) ~ 2.0 error where the fixed path lands within
    ~1e-2. fp64 reference computed from the same bf16-rounded
    inputs."""
    import jax.numpy as jnp

    from paddle_tpu.ops.paged_attention import paged_attention_step

    bs, maxb, H, D = 8, 16, 2, 8
    ctx = bs * maxb - 1                 # 127 cached + 1 incoming
    nb = maxb + 1
    rng = np.random.RandomState(5)
    A = 512.0
    # value rows: +/-A alternating (pairs cancel under near-uniform
    # weights) plus an O(1) signal that IS the answer
    signal = rng.randn(ctx + 1, H, D).astype(np.float32)
    v_rows = (np.where((np.arange(ctx + 1) % 2 == 0), A, -A)
              [:, None, None] + signal).astype(np.float32)
    v16 = np.asarray(jnp.asarray(v_rows, jnp.bfloat16)
                     .astype(jnp.float32))
    # tiny irregular keys: softmax weights are NEAR 1/T but not exactly
    # representable in bf16, so a probs-to-bf16 cast alone already
    # perturbs each +/-512 term by ~0.4%
    k_rows = np.asarray(jnp.asarray(
        0.02 * rng.randn(ctx + 1, H, D), jnp.bfloat16)
        .astype(jnp.float32))

    kpool = np.zeros((1, nb, bs, H, D), np.float32)
    vpool = np.zeros((1, nb, bs, H, D), np.float32)
    table = np.arange(1, maxb + 1, dtype=np.int32)[None]
    for t in range(ctx):
        kpool[0, table[0, t // bs], t % bs] = k_rows[t]
        vpool[0, table[0, t // bs], t % bs] = v16[t]
    q = np.asarray(jnp.asarray(0.02 * rng.randn(1, 1, H, D),
                               jnp.bfloat16).astype(jnp.float32))
    kn = k_rows[ctx][None, None]
    vn = v16[ctx][None, None]
    pos = np.asarray([ctx], np.int32)

    out, _, _ = paged_attention_step(
        jnp.asarray(q, jnp.bfloat16), jnp.asarray(kn, jnp.bfloat16),
        jnp.asarray(vn, jnp.bfloat16),
        jnp.asarray(kpool, jnp.bfloat16), jnp.asarray(vpool, jnp.bfloat16),
        0, table, pos, backend="dense")
    got = np.asarray(out._array.astype(jnp.float32))[0, 0]

    ref = _np_step_reference(q[0], kn[0], vn[0], k_rows, v16,
                             ctx)[0]            # fp64 softmax + PV
    # |out| is O(1) while the cancelled +/-A terms are 512: bf16
    # rounding of probs or of a partial accumulator leaves an O(1)+
    # residual error; the fp32-accumulation path stays ~1e-2
    assert np.abs(ref).max() < 3.0
    np.testing.assert_allclose(got, ref, atol=0.08)


# -- engine-level: pallas (interpret) vs dense, full serving run ----------

def _lockstep_engines(model, **kw):
    return {b: GenerationEngine(model, attention_backend=b, **kw)
            for b in ("dense", "pallas")}


def test_engine_run_token_exact_across_backends(model, monkeypatch):
    """The tentpole acceptance: a full engine run — mid-run admissions,
    an EOS early-stop, finished lanes vacated for later arrivals — is
    TOKEN-EXACT between the dense and pallas (interpret) backends, each
    with the decode count_traces == 1 contract, and the mid-run pool
    contents agree via the dense_gather_reference probe."""
    import paddle_tpu.jit as jit
    from paddle_tpu.ops.paged_attention import (
        PAGED_PATH_STATS, dense_gather_reference, reset_paged_path_stats)

    # the deploy knob must not silently collapse both engines onto one
    # backend (env wins over the constructor by design)
    monkeypatch.delenv("PADDLE_PAGED_ATTENTION_BACKEND", raising=False)
    rng = np.random.RandomState(0)
    reqs = [(rng.randint(0, VOCAB, rng.randint(1, 8)).astype(np.int32),
             int(rng.randint(3, 10))) for _ in range(8)]
    prompt = rng.randint(0, VOCAB, 5).astype(np.int32)
    plain = _reference(model, prompt, 12)
    eos = int(plain[len(prompt) + 2])            # 3rd generated token
    ref_eos = _reference(model, prompt, 12, eos=eos)

    reset_paged_path_stats()
    engines = _lockstep_engines(model, num_slots=3, block_size=4,
                                num_blocks=40,
                                prefill_buckets=(8, 16, 64))
    ids = {}
    for b, eng in engines.items():
        ids[b] = [eng.add_request(p, n) for p, n in reqs[:4]]
        ids[b].append(eng.add_request(prompt, 12, eos_token_id=eos))
        for _ in range(3):
            eng.step()                           # decode mid-stream

    # mid-run pool parity: every live slot's reassembled context is
    # bit-identical across backends (scatter writes vs fused DMA)
    de, pe = engines["dense"], engines["pallas"]
    for sd, sp in zip(de._slots, pe._slots):
        if sd is None or sp is None:
            assert sd is None and sp is None
            continue
        assert sd.req.req_id == sp.req.req_id
        n = len(sd.req.prompt) + len(sd.generated)
        for layer in range(model.config.num_layers):
            rowd = np.zeros(de.max_blocks, np.int32)
            rowd[:len(sd.blocks)] = sd.blocks
            rowp = np.zeros(pe.max_blocks, np.int32)
            rowp[:len(sp.blocks)] = sp.blocks
            gkd, gvd = dense_gather_reference(
                de.cache.kpool, de.cache.vpool, layer, rowd, n)
            gkp, gvp = dense_gather_reference(
                pe.cache.kpool, pe.cache.vpool, layer, rowp, n)
            np.testing.assert_allclose(gkd, gkp, rtol=2e-5, atol=2e-6)
            np.testing.assert_allclose(gvd, gvp, rtol=2e-5, atol=2e-6)

    outs = {}
    for b, eng in engines.items():
        ids[b] += [eng.add_request(p, n) for p, n in reqs[4:]]  # mid-run
        outs[b] = eng.run()
        assert eng.decode_traces == 1            # one program per backend
        # steady state: more churn retraces nothing
        with jit.expect_traces(eng._decode_pure, 0):
            eng.add_request(rng.randint(0, VOCAB, 5), 3)
            eng.run()

    assert PAGED_PATH_STATS["pallas"] > 0        # the kernel engaged
    assert PAGED_PATH_STATS["dense"] > 0
    for rid_d, rid_p in zip(ids["dense"], ids["pallas"]):
        assert outs["dense"][rid_d] == outs["pallas"][rid_p]
    # and both equal the single-request oracle (incl. the EOS stop)
    got = outs["pallas"][ids["pallas"][4]]
    assert got[-1] == eos and len(got) < len(prompt) + 12
    np.testing.assert_array_equal(got, ref_eos[:len(got)])
    for (p, n), rid in zip(reqs[:4], ids["pallas"][:4]):
        np.testing.assert_array_equal(np.asarray(outs["pallas"][rid]),
                                      _reference(model, p, n))


def test_engine_backend_metrics_and_env_override(model, monkeypatch):
    """The kernel-backend gauge + per-backend decode-span labels land
    in the engine's registry; PADDLE_PAGED_ATTENTION_BACKEND overrides
    the constructor; `auto` resolves to dense off-TPU; bad values are
    rejected loudly."""
    monkeypatch.delenv("PADDLE_PAGED_ATTENTION_BACKEND", raising=False)
    eng = GenerationEngine(model, num_slots=2, block_size=4,
                           num_blocks=20, prefill_buckets=(8, 64),
                           attention_backend="pallas")
    assert eng.attention_backend == "pallas"
    eng.add_request([1, 2, 3], 4)
    eng.run()
    snap = eng.metrics_snapshot()
    info = {s["labels"]["backend"]: s["value"]
            for s in snap["engine_attention_backend_info"]["series"]}
    assert info == {"pallas": 1.0}
    spans = {s["labels"]["backend"]: s["count"]
             for s in snap["engine_decode_step_seconds"]["series"]}
    assert spans["pallas"] >= 3                  # 4 tokens: 3 decodes
    text = eng.metrics.render_prometheus()
    assert 'engine_attention_backend_info{backend="pallas"} 1' in text
    assert 'engine_decode_step_seconds_bucket{backend="pallas"' in text

    # off-TPU `auto` resolves dense (the DESIGN_DECISIONS crossover)
    auto = GenerationEngine(model, num_slots=2, prefill_buckets=(8, 64))
    assert auto.attention_backend == "dense"
    assert auto.attention_backend_requested == "auto"

    monkeypatch.setenv("PADDLE_PAGED_ATTENTION_BACKEND", "pallas")
    over = GenerationEngine(model, num_slots=2, prefill_buckets=(8, 64),
                            attention_backend="dense")
    assert over.attention_backend == "pallas"    # env wins: deploy knob

    monkeypatch.setenv("PADDLE_PAGED_ATTENTION_BACKEND", "cuda")
    with pytest.raises(ValueError, match="backend"):
        GenerationEngine(model, num_slots=2, prefill_buckets=(8, 64))


# -- CI / tooling satellites ----------------------------------------------

def test_paged_kernel_import_has_no_backend_init():
    """Importing the kernel module must not initialize a JAX backend
    (the observability-smoke precedent): the module is imported by the
    op seam at dispatch time on serving hosts."""
    code = (
        "import paddle_tpu.ops.pallas.paged_attention as pk\n"
        "from jax._src import xla_bridge\n"
        "assert not xla_bridge._backends, 'backend initialized'\n"
        "assert callable(pk.paged_decode_attention)\n"
        "print('SMOKE_OK')\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    res = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "SMOKE_OK" in res.stdout


def test_new_bench_rows_registered_and_pending(capsys):
    """Both ISSUE-3 rows are in the suite (so a TPU run measures them)
    and `check_bench_result --pending` flags them until a `--save`
    refresh adopts them into OPBENCH.json."""
    import bench_ops

    names = bench_ops.suite_names()
    assert "paged_attention_decode_sweep" in names
    assert "gpt_engine_offered_load_pallas" in names

    sys.path.insert(0, os.path.join(REPO, "tools"))
    import check_bench_result as gate

    with open(os.path.join(REPO, "OPBENCH.json")) as f:
        baseline = json.load(f)
    assert "paged_attention_decode_sweep" not in baseline  # not adopted
    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as f:
        json.dump(baseline, f)
        tmp = f.name
    try:
        rc = gate.check_pending(tmp, suite_names=names, strict=True)
        out = capsys.readouterr().out
        assert rc == 1
        assert "PENDING: paged_attention_decode_sweep" in out
        assert "PENDING: gpt_engine_offered_load_pallas" in out
    finally:
        os.unlink(tmp)


def test_paged_sweep_bench_runner_tiny():
    """The microbench row's runner at test scale: dense cost must GROW
    with active context at fixed max_model_len (the bounded-work
    acceptance criterion — the pre-fix gather was flat at the
    max_model_len cost), and both backend curves are recorded."""
    import jax.numpy as jnp

    import bench_ops

    rec = bench_ops._paged_attention_sweep_case(
        num_slots=2, heads=2, head_dim=8, block_size=4,
        max_model_len=64, ctx_lengths=(4, 64),
        backends=("dense", "pallas"), dtype=jnp.float32)()
    assert rec["max_model_len"] == 64
    d4, d64 = rec["dense_ms_by_ctx"]["4"], rec["dense_ms_by_ctx"]["64"]
    assert d4 > 0 and d64 > 0
    # 16x the active context: the bounded fori_loop must cost clearly
    # more at full context than near-empty (flat == unbounded gather)
    assert d64 > 2.0 * d4
    assert set(rec["pallas_ms_by_ctx"]) == {"4", "64"}
    assert rec["ms"] == rec["pallas_ms_by_ctx"]["64"]
