import jax

import paddle_tpu.distributed as dist


@jax.jit
def traced_allreduce(x):
    dist.all_reduce(x)
    return x
