import jax


def sampling_row_folds_per_draw(key, pos, logits):
    # the ops/sampling.py pattern: fold the slot's position into the
    # base key, then a draw-purpose salt per consumer — every derived
    # key feeds exactly one sampler
    k = jax.random.fold_in(key, pos)
    u = jax.random.uniform(jax.random.fold_in(k, 1))
    r = jax.random.categorical(jax.random.fold_in(k, 0), logits)
    return u, r


def per_slot_fold(keys, positions, logits):
    def row(row_key, pos, row_lg):
        k = jax.random.fold_in(row_key, pos)
        return jax.random.categorical(k, row_lg)

    return jax.vmap(row)(keys, positions, logits)
