import jax

log = []


@jax.jit
def suppressed_effect(x):
    log.append(1)  # tpu-lint: disable=TPU005
    return x


@jax.jit
def unsuppressed_effect(x):
    log.append(2)
    return x
