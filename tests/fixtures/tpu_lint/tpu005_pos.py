import time

import jax

log = []


@jax.jit
def side_effects(x):
    log.append(1)
    t = time.time()
    return x * t
