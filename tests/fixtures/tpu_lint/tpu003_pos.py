import jax


def double_sample(key):
    a = jax.random.normal(key, (2,))
    b = jax.random.uniform(key, (2,))
    return a + b


def loop_reuse(key, xs):
    out = []
    for _x in xs:
        out.append(jax.random.normal(key, (2,)))
    return out
