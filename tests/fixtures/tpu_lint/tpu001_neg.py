import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def traced(x):
    n = int(x.shape[0])
    return x * n


def eager(x):
    return float(np.asarray(x).sum())
