import jax  # noqa: F401
from jax.experimental.shard_map import shard_map

import paddle_tpu.distributed as dist


def body(x):
    dist.all_reduce(x)
    return x


step = shard_map(body, mesh=None, in_specs=None, out_specs=None)
