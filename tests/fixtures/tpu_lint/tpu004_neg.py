import jax
import jax.numpy as jnp


def rebind_donated(x, y):
    step = jax.jit(lambda a, b: a + b, donate_argnums=(0,))
    x = step(x, y)
    return x * 2.0


def read_non_donated(x, y):
    step = jax.jit(lambda a, b: a + b, donate_argnums=(0,))
    out = step(x, y)
    return y + out


def loop_rebinds(x, y):
    step = jax.jit(lambda a, b: a + b, donate_argnums=(0,))
    for _ in range(3):
        x = step(x, y)
    return x
