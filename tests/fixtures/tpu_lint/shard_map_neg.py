import jax
from jax.experimental.shard_map import shard_map


def body(x):
    y = jax.lax.psum(x, "mp")
    return jax.lax.all_gather(y, "mp", axis=0, tiled=True)


step = shard_map(body, mesh=None, in_specs=None, out_specs=None)
