import jax


def sampling_row_reuses_folded_key(key, pos, logits):
    # the per-slot sampling-step anti-pattern: ONE folded key consumed
    # by both the acceptance uniform and the resample draw — the coin
    # and the categorical would be correlated
    k = jax.random.fold_in(key, pos)
    u = jax.random.uniform(k)
    r = jax.random.categorical(k, logits)
    return u, r
