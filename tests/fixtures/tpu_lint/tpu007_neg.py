import jax

import paddle_tpu.distributed as dist


def eager_allreduce(x):
    dist.all_reduce(x)
    return x


@jax.jit
def mesh_collective(x):
    return jax.lax.psum(x, "dp")
