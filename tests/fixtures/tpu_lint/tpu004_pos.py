import jax
import jax.numpy as jnp


def use_after_donate(x, y):
    step = jax.jit(lambda a, b: a + b, donate_argnums=(0,))
    out = step(x, y)
    return x * 2.0 + out


def loop_carried_donation(x, y):
    step = jax.jit(lambda a, b: a + b, donate_argnums=(0,))
    for _ in range(3):
        out = step(x, y)
    return out
