import jax  # noqa: F401
from jax.experimental.shard_map import shard_map


def body(x):
    v = float(x.sum())
    return v


step = shard_map(body, mesh=None, in_specs=None, out_specs=None)
