import jax


def split_between(key):
    a = jax.random.normal(key, (2,))
    key, sub = jax.random.split(key)
    b = jax.random.uniform(sub, (2,))
    return a + b


def branches_consume_once(key, flag):
    if flag:
        return jax.random.normal(key, (2,))
    return jax.random.uniform(key, (2,))


def fresh_key_per_loop(key, xs):
    out = []
    for i, _x in enumerate(xs):
        k = jax.random.fold_in(key, i)
        out.append(jax.random.normal(k, (2,)))
    return out
