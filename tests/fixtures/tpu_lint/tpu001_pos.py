import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def traced(x):
    v = float(x.sum())
    np.asarray(x)
    print(x)
    return v


@jax.jit
def method_sync(x):
    return x.item()
