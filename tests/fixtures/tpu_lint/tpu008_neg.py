import jax
import jax.numpy as jnp


def pinned_accumulate(blocks, q):
    acc = jnp.zeros((4, 8), jnp.float32)
    for b in blocks:
        b16 = b.astype(jnp.bfloat16)
        acc = acc + jnp.matmul(q, b16,
                               preferred_element_type=jnp.float32)
    return acc


def standalone_matmul(a, b):
    a16 = a.astype(jnp.bfloat16)
    return jnp.matmul(a16, b)
