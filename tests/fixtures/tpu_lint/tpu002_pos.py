import jax
import jax.numpy as jnp


def branch_on_operand(x, n):
    if n > 2:
        return x * 2.0
    return x / 2.0


traced = jax.jit(branch_on_operand)


@jax.jit
def loop_on_value(x):
    while x.sum() > 0:
        x = x - 1.0
    return x
