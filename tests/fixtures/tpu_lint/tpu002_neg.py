import jax
import jax.numpy as jnp

from paddle_tpu.jit import to_static


@jax.jit
def static_branches(x, flag=None):
    if flag is None:
        x = x + 1.0
    if x.shape[0] > 1:
        x = x * 2.0
    return x


@to_static
def dy2static_branch(x):
    if x.sum() > 0:
        return x
    return -x


def staticized(x, n):
    if n > 2:
        return x * 2.0
    return x / 2.0


traced = jax.jit(staticized, static_argnums=(1,))
