def sorted_iteration(tensors):
    out = []
    for name in sorted(set(tensors)):
        out.append(tensors[name])
    return out


def membership_only(keys, k):
    allowed = set(keys)
    return k in allowed


def order_free_loop(keys):
    seen = set(keys)
    for k in seen:
        print(k)


def order_free_comprehensions(keys, other):
    seen = set(keys)
    hit = any(k in other for k in seen)
    count = sum(1 for k in seen)
    ordered = sorted(k for k in seen)
    return hit, count, ordered
