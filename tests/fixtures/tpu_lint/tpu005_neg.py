import time

import jax


@jax.jit
def pure(x):
    acc = []
    acc.append(x * 2.0)
    return acc[0]


def host_wrapper(x):
    t0 = time.time()
    return x, t0
