def build_params(tensors):
    out = []
    for name in set(tensors):
        out.append(tensors[name])
    return out


def comp_over_set(keys):
    return {k: 0.0 for k in set(keys)}
