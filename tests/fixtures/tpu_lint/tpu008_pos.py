import jax
import jax.numpy as jnp


def block_accumulate(blocks, q):
    acc = jnp.zeros((4, 8), jnp.float32)
    for b in blocks:
        b16 = b.astype(jnp.bfloat16)
        acc = acc + jnp.matmul(q, b16)
    return acc
