# tpu-shard: disable=TPU301 -- fixture: proves the same-line tag
# (line 1 is the anchor line for every tpu-shard finding on this
# file; the disable above must suppress TPU301 and ONLY TPU301).
