# tpu-shard positive-fixture anchor: the tests' FIRING fixtures
# declare this file as their `declared_at`; findings must land at
# broken_step.py:1 (this line). No suppression comments here either —
# the findings must stay live.
