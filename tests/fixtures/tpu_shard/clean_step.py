# tpu-shard negative-fixture anchor: contracts in
# tests/test_tpu_shard.py declare this file as their `declared_at`, so
# every finding a rule would emit anchors HERE at line 1 — the tests
# assert the exact file:line. This file intentionally carries no
# suppression comments.
