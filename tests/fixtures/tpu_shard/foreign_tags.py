# tpu-lint: disable=TPU301
# tpu-race: disable=TPU301
# Fixture: SIBLING tiers' tags on the anchor line's file — line 1
# carries a tpu-lint disable for the very rule id the test fires, and
# it must NOT suppress a tpu-shard finding (tag namespaces are
# disjoint in both directions).
