"""TPU204 positive: device waits, queue gets and thread joins while
holding a lock."""
import queue
import threading

import jax


class Collector:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue()
        self._t = threading.Thread(target=self._noop, daemon=True)

    def _noop(self):
        pass

    def wait_out(self, out):
        with self._lock:
            jax.block_until_ready(out)

    def drain(self):
        with self._lock:
            return self._q.get()

    def join_worker(self):
        with self._lock:
            self._t.join()
