"""TPU204 negative: waits happen outside the guarded region, and a
str.join under the lock is not a blocking call."""
import queue
import threading

import jax


class Collector:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue()
        self._t = threading.Thread(target=self._noop, daemon=True)
        self._names = []

    def _noop(self):
        pass

    def wait_out(self, out):
        jax.block_until_ready(out)
        with self._lock:
            self._names.append("done")

    def drain(self):
        item = self._q.get()
        with self._lock:
            return ",".join(item)
