"""TPU205 negative: the spawn lives outside any traced region."""
import threading

import jax


@jax.jit
def step(x):
    return x + 1


def launch(x):
    threading.Thread(target=print, args=(x,), daemon=True).start()
