"""TPU202 negative: one lock everywhere; the lock-free helper asserts
its callers' lock with a guarded-by annotation."""
import threading


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._total = 0.0

    def add(self, amount):
        with self._lock:
            self._total += amount

    def _zero(self):
        self._total = 0.0        # guarded-by: _lock

    def reset(self):
        with self._lock:
            self._zero()
