"""Same-line suppression: only the tagged line is exempt."""
import threading


class R:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def add(self):
        with self._lock:
            self._n += 1

    def reset_a(self):
        self._n = 0  # tpu-race: disable=TPU202

    def reset_b(self):
        self._n = 0
