"""TPU200: this file does not parse (reported, never skipped)."""
def broken(:
