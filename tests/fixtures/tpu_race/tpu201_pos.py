"""TPU201 positive: helper-thread write, no common lock."""
import threading


class Worker:
    def __init__(self):
        self.count = 0
        self._t = threading.Thread(target=self._work, daemon=True)

    def _work(self):
        self.count += 1

    def step(self):
        return self.count
