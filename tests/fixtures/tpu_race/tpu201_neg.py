"""TPU201 negative: a common lock on both sides, and thread-local
scratch state confined by construction."""
import threading


class Worker:
    def __init__(self):
        self.count = 0
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._t = threading.Thread(target=self._work, daemon=True)

    def _work(self):
        with self._lock:
            self.count += 1
        self._tls.scratch = 1

    def step(self):
        with self._lock:
            return self.count
