"""TPU202 positive: locked write in one method, bare write in
another; and one attribute guarded by two different locks."""
import threading


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._total = 0.0

    def add(self, amount):
        with self._lock:
            self._total += amount

    def reset(self):
        self._total = 0.0


class TwoLocks:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._n = 0

    def f(self):
        with self._a:
            self._n += 1

    def g(self):
        with self._b:
            self._n += 1
