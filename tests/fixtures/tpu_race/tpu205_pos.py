"""TPU205 positive: jit-reachable code starts a thread (runs once at
trace time, stages nothing)."""
import threading

import jax


@jax.jit
def step(x):
    _log_async(x)
    return x + 1


def _log_async(x):
    threading.Thread(target=print, args=(x,), daemon=True).start()
