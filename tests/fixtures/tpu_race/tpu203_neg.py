"""TPU203 negative: the same depth-2 pipe with the fixed ordering —
complete the in-flight step, THEN recycle its blocks, then dispatch."""
import jax


class Pipe:
    def __init__(self, cache):
        self.cache = cache
        self.inflight = None

    def run(self, steps):
        for work in steps:
            if self.inflight is None:
                self.inflight = self._plain_dispatch(work)
                continue
            jax.block_until_ready(self.inflight.out)
            self.cache.free(self.inflight.blocks)
            self.inflight = self._plain_dispatch(work)

    def _plain_dispatch(self, work):
        return work
