"""TPU203 positive: a depth-2 async pipe that frees the previous
iteration's blocks BEFORE waiting on its dispatched step — the
zombie-write hazard (a dispatched step may still write the blocks)."""
import jax


class Pipe:
    def __init__(self, cache):
        self.cache = cache
        self.inflight = None

    def run(self, steps):
        for work in steps:
            if self.inflight is None:
                self.inflight = self._plain_dispatch(work)
                continue
            self.cache.free(self.inflight.blocks)
            jax.block_until_ready(self.inflight.out)
            self.inflight = self._plain_dispatch(work)

    def _plain_dispatch(self, work):
        return work
