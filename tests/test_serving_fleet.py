"""Replica-parallel serving tier (ISSUE 12): ServingFleet — the
prefix-affinity dp router over GenerationEngine replicas, with
disaggregated prefill/decode.

The contracts, proven the way the engine PRs proved theirs:

- ONE hashing truth: router keys ARE cache keys (`prefix_key` backs
  both `PagedKVCache.match_prefix`/`register_prefix` and the fleet's
  affinity decision), for aligned and ragged prompt lengths.
- Token exactness: a 1-replica fleet is BIT-identical to a bare
  engine on the same mixed-length QoS trace; an N-replica fleet
  produces the same per-request tokens (order-independent); the
  disaggregated prefill->decode handoff (block export/ingest +
  mid-stream adoption) is token-identical to a colocated engine at
  kv_dtype in {fp, int8} and under both prefill modes.
- Affinity routing demonstrably lands warm requests on the
  block-owning replica (hit tokens > 0 there, 0 elsewhere), and
  hysteresis spills a hot tenant once the warm replica's backlog
  exceeds the slack.
- drain(): admissions closed, in-flight lanes finished, every
  non-cached block back on the free list (the leak-check class the
  allocator's double-free hardening can't see).
- Fleet metrics fold replica-labeled through the exact-merge
  machinery (engine-metrics contract at N=2), and replicas
  join/leave the elastic registry under its token auth.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import (GenerationEngine, PagedKVCache,
                                  ServingFleet, prefix_key)
from paddle_tpu.observability.metrics import (label_snapshot,
                                              merge_snapshots,
                                              series_total)

VOCAB = 61


def _model(seed=0):
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(seed)
    cfg = GPTConfig.tiny(vocab=VOCAB, hidden=32, layers=2, heads=2,
                         seq=64)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return _model()


def _mixed_trace(rng, n=8):
    """(prompt, max_new, priority) mixed-length QoS trace."""
    prios = ("interactive", "standard", "batch")
    return [(rng.randint(0, VOCAB, int(rng.randint(3, 40))),
             int(rng.randint(2, 10)), prios[i % 3])
            for i in range(n)]


def _serve_engine(model, trace, eos=None, **kw):
    eng = GenerationEngine(model, num_slots=4, block_size=8, **kw)
    ids = [eng.add_request(p, max_new_tokens=n, priority=pr,
                           eos_token_id=eos)
           for p, n, pr in trace]
    out = eng.run()
    return {i: out[i] for i in ids}


def _serve_fleet(model, trace, eos=None, fleet_kw=(), **kw):
    fleet = ServingFleet(model, num_slots=4, block_size=8,
                         **dict(fleet_kw), **kw)
    ids = [fleet.add_request(p, max_new_tokens=n, priority=pr,
                             eos_token_id=eos)
           for p, n, pr in trace]
    out = fleet.run()
    return fleet, {i: out[i] for i in ids}


# ---------------------------------------------------------------------------
# satellite: one hashing truth — router keys ARE cache keys
# ---------------------------------------------------------------------------

def test_prefix_key_is_the_cache_key_aligned_and_ragged():
    """The digests prefix_key computes are exactly the keys the cache
    registers and matches under — for block-aligned prompts and for
    ragged tails (which must contribute nothing)."""
    bs = 4
    c = PagedKVCache(1, 10, bs, 2, 8)
    aligned = np.arange(12, dtype=np.int32)          # 3 full blocks
    ragged = np.concatenate([aligned, [7, 7]])       # + 2-token tail
    keys = prefix_key(aligned, bs)
    assert len(keys) == 3
    assert prefix_key(ragged, bs) == keys            # tail ignored
    assert prefix_key(aligned[:9], bs) == keys[:2]   # ragged shorter
    assert prefix_key(aligned[:3], bs) == ()         # sub-block
    # registering under the cache's walk publishes EXACTLY these keys
    blocks = c.allocate(3)
    assert c.register_prefix(aligned, blocks) == 3
    assert set(c._block_of) == set(keys)
    assert [c._block_of[k] for k in keys] == blocks
    # a router peek agrees with a cache match at every raggedness
    for toks in (aligned, ragged, aligned[:9], aligned[:3]):
        peek = c.warm_prefix_tokens(toks)
        got, hit = c.match_prefix(toks)
        assert peek == hit == (len(toks) // bs) * bs
        if got:
            c.free(got)
    # prefix-safety: same block content after a different parent
    # yields a DIFFERENT key chain
    shifted = np.concatenate([[9], aligned[:-1]]).astype(np.int32)
    assert prefix_key(shifted, bs)[1:] != keys[1:]
    assert c.warm_prefix_tokens(shifted) == 0


# ---------------------------------------------------------------------------
# tentpole: fleet-vs-engine token exactness
# ---------------------------------------------------------------------------

def test_single_replica_fleet_bit_identical_to_bare_engine(model):
    """The same mixed-length QoS trace through a 1-replica fleet and a
    bare engine: identical req ids, identical token lists — the fleet
    tier adds routing, not numerics."""
    rng = np.random.RandomState(0)
    trace = _mixed_trace(rng, n=8)
    ref = _serve_engine(model, trace, eos=5)
    _, got = _serve_fleet(model, trace, eos=5,
                          fleet_kw={"num_replicas": 1})
    assert got == ref


@pytest.mark.parametrize("n_replicas", [2, 3])
def test_n_replica_fleet_per_request_identical(model, n_replicas):
    """Whatever replica a request lands on, its tokens must equal the
    bare engine's (order-independent): replicas share the weights and
    the compiled-step numerics, and routing must not change either."""
    rng = np.random.RandomState(1)
    trace = _mixed_trace(rng, n=10)
    ref = _serve_engine(model, trace, eos=5)
    fleet, got = _serve_fleet(
        model, trace, eos=5, fleet_kw={"num_replicas": n_replicas})
    assert got == ref
    # the load actually spread: more than one replica generated
    active = [r.rid for r in fleet._replicas.values()
              if r.engine.tokens_generated > 0]
    assert len(active) > 1, "router sent everything to one replica"


@pytest.mark.parametrize("kv_dtype", [None, "int8"])
@pytest.mark.parametrize("bucketed", [False, True])
def test_disaggregated_fleet_token_exact(model, kv_dtype, bucketed):
    """The ambitious end state: dedicated prefill replicas hand
    finished KV blocks (+ int8 scale rows) into a decode replica's
    pool via the compiled export/ingest path, and the output stays
    EXACTLY what a colocated engine of the same config produces —
    both prefill modes, fp and quantized pools."""
    rng = np.random.RandomState(2)
    trace = _mixed_trace(rng, n=6)
    kw = {"kv_dtype": kv_dtype}
    if bucketed:
        kw["prefill_buckets"] = (16, 64)
    ref = _serve_engine(model, trace, eos=5, **kw)
    fleet, got = _serve_fleet(
        model, trace, eos=5,
        fleet_kw={"num_replicas": 1, "num_prefill_replicas": 1}, **kw)
    assert got == ref
    snap = fleet.metrics_snapshot()
    assert series_total(snap, "fleet_handoffs_total") > 0
    assert series_total(snap, "fleet_handoff_blocks_total") > 0
    # the handoff seam stayed shape-stable: one decode trace per
    # replica, no recompiles
    for rep in fleet._replicas.values():
        assert rep.engine.decode_traces <= 1


def test_disaggregated_prefill_never_decodes(model):
    """Role separation is real: prefill replicas emit exactly one
    token per request (the final chunk's), decode replicas run no
    prefill chunks — long-prompt admission can't steal decode-step
    FLOPs by construction."""
    rng = np.random.RandomState(3)
    trace = _mixed_trace(rng, n=5)
    fleet, _ = _serve_fleet(
        model, trace,
        fleet_kw={"num_replicas": 1, "num_prefill_replicas": 1})
    roles = {r.role: r.engine for r in fleet._replicas.values()}
    pre_snap = roles["prefill"].metrics.snapshot()
    dec_snap = roles["decode"].metrics.snapshot()
    assert roles["prefill"].tokens_generated == 5  # one per request
    # every prefill-side finish is a handoff, none a decode finish
    pre_fin = {s["labels"]["reason"]: s["value"]
               for s in pre_snap["engine_finished_total"]["series"]}
    assert set(pre_fin) == {"handoff"} and pre_fin["handoff"] == 5
    assert series_total(dec_snap, "engine_prefill_chunks_total") == 0
    assert roles["decode"].tokens_generated > 0


# ---------------------------------------------------------------------------
# tentpole: prefix-affinity routing with hysteresis
# ---------------------------------------------------------------------------

def test_affinity_routes_warm_requests_to_block_owner(model):
    """After a cold pass seeds one replica's prefix cache, every warm
    request for that tenant must land on the block-owning replica and
    be served from its cache (hit tokens > 0 there, zero on the
    other)."""
    rng = np.random.RandomState(4)
    fleet = ServingFleet(model, num_replicas=2, num_slots=4,
                         block_size=8)
    tenant = rng.randint(0, VOCAB, 24)          # 3 full blocks
    fleet.add_request(np.concatenate([tenant, rng.randint(0, VOCAB, 3)]),
                      max_new_tokens=3)
    fleet.run()
    owner = [r for r in fleet._replicas.values()
             if r.engine.cache.warm_prefix_tokens(tenant) > 0]
    assert len(owner) == 1                       # exactly one owner
    owner = owner[0]
    for _ in range(3):                           # warm passes
        fleet.add_request(
            np.concatenate([tenant, rng.randint(0, VOCAB, 3)]),
            max_new_tokens=3)
        fleet.run()
    snap = fleet.metrics_snapshot()
    routed = {(s["labels"]["replica"], s["labels"]["reason"]):
              s["value"] for s in snap["fleet_routed_total"]["series"]}
    assert routed.get((str(owner.rid), "affinity")) == 3
    assert series_total(snap, "fleet_affinity_hit_tokens_total") \
        == 3 * 24
    for rep in fleet._replicas.values():
        hits = series_total(
            rep.engine.metrics.snapshot(),
            "engine_prefix_cache_hit_tokens_total")
        assert (hits > 0) == (rep.rid == owner.rid)


def test_affinity_hysteresis_spills_hot_tenant(model):
    """affinity_slack bounds the imbalance affinity may create: with
    slack 0, the second warm request (warm replica already carrying
    the first) must spill to the least-loaded replica instead of
    queueing behind its tenant-mates."""
    rng = np.random.RandomState(5)
    fleet = ServingFleet(model, num_replicas=2, num_slots=4,
                         block_size=8, affinity_slack=0)
    tenant = rng.randint(0, VOCAB, 16)
    fleet.add_request(tenant, max_new_tokens=2)
    fleet.run()                                  # seed the owner
    # two warm adds back-to-back WITHOUT running: the first takes the
    # affinity route (loads equal), making the owner strictly more
    # loaded — the second must fall back to least-loaded
    fleet.add_request(np.concatenate([tenant, [1]]), max_new_tokens=2)
    fleet.add_request(np.concatenate([tenant, [2]]), max_new_tokens=2)
    snap = fleet.metrics_snapshot()
    by_reason = {}
    for s in snap["fleet_routed_total"]["series"]:
        by_reason[s["labels"]["reason"]] = \
            by_reason.get(s["labels"]["reason"], 0) + s["value"]
    assert by_reason.get("affinity") == 1
    assert by_reason.get("least_loaded") == 2    # cold seed + spill
    fleet.run()


# ---------------------------------------------------------------------------
# satellite: drain — admissions closed, lanes finished, no leaks
# ---------------------------------------------------------------------------

def test_engine_drain_finishes_and_leak_checks(model):
    """drain(): rejects new admissions, runs existing lanes to
    completion, and audits that every non-cached block returned to
    the free list (cached blocks parked evictable)."""
    rng = np.random.RandomState(6)
    eng = GenerationEngine(model, num_slots=2, block_size=8)
    ids = [eng.add_request(rng.randint(0, VOCAB, 12), max_new_tokens=4)
           for _ in range(4)]
    out = eng.drain()
    assert sorted(out) == sorted(ids)
    assert all(len(out[i]) == 12 + 4 for i in ids)
    with pytest.raises(RuntimeError, match="draining"):
        eng.add_request([1, 2], max_new_tokens=1)
    with pytest.raises(RuntimeError, match="draining"):
        eng.adopt_request([1, 2], 3, [1], 2)
    assert eng.cache.leak_check() == []


def test_engine_drain_catches_block_leak(model):
    """The audit really fires: a block held without an owner (the
    leak class refcounts alone can't flag) fails the drain loudly."""
    eng = GenerationEngine(model, num_slots=2, block_size=8)
    eng.add_request([1, 2, 3], max_new_tokens=2)
    eng.cache.allocate(1)                # leaked: never freed/seated
    with pytest.raises(RuntimeError, match="leak check failed"):
        eng.drain()


def test_engine_drain_refuses_parked_handoff(model):
    """A parked handoff holds blocks ON PURPOSE — drain must demand
    the fleet export-and-release it rather than declare a leak or
    silently recycle prompt KV."""
    eng = GenerationEngine(model, num_slots=2, block_size=8)
    rid = eng.add_request(np.arange(10) % VOCAB, max_new_tokens=1,
                          prefill_only=True)
    with pytest.raises(RuntimeError, match="handoff"):
        eng.drain()
    blocks, _ = eng.take_handoff(rid)
    eng.release_handoff(blocks)
    assert eng.cache.leak_check() == []


def test_reused_req_id_collides_with_parked_handoff(model):
    """A parked handoff still owns blocks under its req_id: reusing
    that id must be rejected, or the second finish would overwrite
    the parked entry and leak the first one's blocks forever."""
    eng = GenerationEngine(model, num_slots=2, block_size=8)
    rid = eng.add_request(np.arange(10) % VOCAB, max_new_tokens=1,
                          prefill_only=True)
    eng.run()                            # result drained, handoff parked
    with pytest.raises(ValueError, match="already"):
        eng.add_request(np.arange(10) % VOCAB, max_new_tokens=1,
                        prefill_only=True, req_id=rid)
    blocks, _ = eng.take_handoff(rid)
    eng.release_handoff(blocks)
    assert eng.cache.leak_check() == []


def test_adopt_request_validations(model):
    eng = GenerationEngine(model, num_slots=1, block_size=8)
    blocks = eng.cache.allocate(2)
    with pytest.raises(ValueError, match="exactly"):
        eng.adopt_request(np.arange(10), 3, blocks[:1], 4)
    # occupy the only lane, then adoption must refuse
    eng.add_request(np.arange(12) % VOCAB, max_new_tokens=8)
    eng.step()
    with pytest.raises(RuntimeError, match="free lane"):
        eng.adopt_request(np.arange(10) % VOCAB, 3, blocks, 4)
    eng.cache.free(blocks)
    eng.run()


# ---------------------------------------------------------------------------
# satellite: fleet metrics — replica-labeled exact merge
# ---------------------------------------------------------------------------

def test_label_snapshot_relabel_and_exact_merge():
    """Unit mechanics: stamped labels appear on every series, merge
    keeps replica series side-by-side and sums exactly, and a label
    collision raises instead of shadowing."""
    from paddle_tpu.observability.metrics import MetricsRegistry

    regs = [MetricsRegistry() for _ in range(2)]
    for i, reg in enumerate(regs):
        c = reg.counter("toks_total", "t", labelnames=("priority",))
        c.labels(priority="standard").inc(10 * (i + 1))
        h = reg.histogram("lat_seconds", "l", buckets=(0.1, 1.0))
        h.observe(0.05)
    merged = merge_snapshots(
        [label_snapshot(r.snapshot(), replica=str(i))
         for i, r in enumerate(regs)])
    fam = merged["toks_total"]
    assert fam["labelnames"] == ["priority", "replica"]
    vals = {s["labels"]["replica"]: s["value"] for s in fam["series"]}
    assert vals == {"0": 10.0, "1": 20.0}
    lat = merged["lat_seconds"]["series"]
    assert len(lat) == 2 and all(s["count"] == 1 for s in lat)
    with pytest.raises(ValueError, match="shadow"):
        label_snapshot(regs[0].snapshot(), priority="x")


def test_fleet_metrics_contract_two_replicas(model):
    """The engine-metrics contract survives the fold at N=2: merged
    token/admission counters equal the sums of the per-replica
    registries, every engine family carries the replica label, and
    the fleet's own router series ride alongside."""
    rng = np.random.RandomState(7)
    trace = _mixed_trace(rng, n=8)
    fleet, got = _serve_fleet(model, trace,
                              fleet_kw={"num_replicas": 2})
    snap = fleet.metrics_snapshot()
    per_replica = {
        str(r.rid): series_total(r.engine.metrics.snapshot(),
                                 "engine_tokens_generated_total")
        for r in fleet._replicas.values()}
    fam = snap["engine_tokens_generated_total"]
    assert "replica" in fam["labelnames"]
    merged = {s["labels"]["replica"]: s["value"]
              for s in fam["series"]}
    assert merged == per_replica
    total_new = sum(len(t) for t in got.values()) \
        - sum(len(p) for p, _, _ in trace)
    assert sum(merged.values()) == total_new
    assert series_total(snap, "engine_admissions_total") == len(trace)
    # TTFT observations: one per request, summed over (priority,
    # replica) series
    fam = snap["engine_ttft_seconds"]
    assert {"priority", "replica"} <= set(fam["labelnames"])
    assert sum(s["count"] for s in fam["series"]) == len(trace)
    # router-owned series are present and unlabeled-by-replica
    assert series_total(snap, "fleet_routed_total") == len(trace)


def test_fleet_admission_shed_at_max_queue(model):
    """Fleet-level admission control: past max_queue queued fleet-wide
    the incoming request is shed (result None) and counted."""
    rng = np.random.RandomState(8)
    fleet = ServingFleet(model, num_replicas=1, num_slots=2,
                         block_size=8, max_queue=2)
    ids = [fleet.add_request(rng.randint(0, VOCAB, 8),
                             max_new_tokens=2, priority="batch")
           for _ in range(8)]
    out = fleet.run()
    shed = [i for i in ids if out[i] is None]
    assert shed, "max_queue never shed"
    snap = fleet.metrics_snapshot()
    assert series_total(snap, "fleet_shed_total") == len(shed)
    assert all(out[i] is not None for i in ids if i not in shed)


# ---------------------------------------------------------------------------
# satellite: elastic join/leave under token auth
# ---------------------------------------------------------------------------

def test_fleet_elastic_join_drain_leave(model):
    from paddle_tpu.distributed.launch.elastic import ElasticMaster

    master = ElasticMaster(token="job-tok")
    try:
        with pytest.raises(RuntimeError, match="unauthorized"):
            ServingFleet(model, num_replicas=1, num_slots=2,
                         block_size=8,
                         elastic_endpoint=master.endpoint,
                         elastic_token="wrong")
        fleet = ServingFleet(model, num_replicas=2, num_slots=2,
                             block_size=8,
                             elastic_endpoint=master.endpoint,
                             elastic_token="job-tok")
        live = master.live()
        assert sorted(live) == ["fleet-replica-0", "fleet-replica-1"]
        assert live["fleet-replica-0"]["role"] == "mixed"
        assert live["fleet-replica-0"]["num_slots"] == 2
        # elastic scale-out rides the same path
        rid = fleet.add_replica()
        assert f"fleet-replica-{rid}" in master.live()
        # graceful leave: in-flight work finishes first, then the
        # membership drops
        rng = np.random.RandomState(9)
        ids = [fleet.add_request(rng.randint(0, VOCAB, 10),
                                 max_new_tokens=3) for _ in range(4)]
        fleet.remove_replica(rid)
        assert f"fleet-replica-{rid}" not in master.live()
        out = fleet.run()
        assert sorted(out) == sorted(ids)
        fleet.drain()
        assert master.live() == {}
        with pytest.raises(RuntimeError, match="draining"):
            fleet.add_request([1], max_new_tokens=1)
        with pytest.raises(RuntimeError, match="draining"):
            fleet.add_replica()
    finally:
        master.close()


def test_remove_last_replica_refused(model):
    fleet = ServingFleet(model, num_replicas=1, num_slots=2,
                         block_size=8)
    (rid,) = list(fleet._replicas)
    with pytest.raises(ValueError, match="last"):
        fleet.remove_replica(rid)


# ---------------------------------------------------------------------------
# CI plumbing: bench row registered + runner at test scale
# ---------------------------------------------------------------------------

def test_fleet_offered_load_bench_runner_tiny(model):
    import bench_ops

    assert "gpt_fleet_offered_load" in bench_ops.suite_names()
    rec = bench_ops._fleet_offered_load_case(
        model_cfg=model.config, num_tenants=2, per_tenant=4,
        uniques=2, prefix_len=16, suffix_max=6, max_new=6,
        num_slots=4, block_size=8, prefill_chunk=16)()
    assert rec["replicas"] == 2
    assert rec["tokens_per_s"] > 0 and rec["tokens_per_s_r1"] > 0
    assert rec["affinity_hit_tokens"] > 0
    assert rec["prefix_hit_tokens"] > 0


# ---------------------------------------------------------------------------
# multi-tenant adapters (ISSUE 13 satellite): adapter-salted routing
# ---------------------------------------------------------------------------

def _lora_registry(cfg, seed=3):
    from paddle_tpu.adapters import AdapterRegistry

    rng = np.random.RandomState(seed)
    reg = AdapterRegistry(cfg, max_rank=2)
    H, L = cfg.hidden_size, cfg.num_layers
    for aid in (1, 2):
        w = {"qkv": [(rng.randn(2, H).astype(np.float32) * 0.5,
                      rng.randn(3 * H, 2).astype(np.float32) * 0.5)
                     for _ in range(L)]}
        reg.register(aid, w, scaling=0.5)
    return reg


def test_adapter_salted_affinity_routes_tenants_independently(model):
    """ISSUE 13 satellite: `prefix_key`'s affinity chain carries the
    SAME adapter-id salt the caches hash with (router keys stay ==
    cache keys), so a hot base prompt under two adapters routes AND
    caches independently — each tenant's requests land on the replica
    owning ITS chain, and neither can claim the other's KV."""
    reg = _lora_registry(model.config)
    fleet = ServingFleet(model, num_replicas=2, num_slots=2,
                         block_size=8, prefill_chunk=8, adapters=reg)
    reps = list(fleet._replicas.values())
    p = (np.arange(16, dtype=np.int32) % VOCAB)
    # warm each tenant's chain on its own replica (driving the engines
    # directly pins placement)
    reps[0].engine.add_request(p, 2, adapter_id=1)
    reps[0].engine.run()
    reps[1].engine.add_request(p, 2, adapter_id=2)
    reps[1].engine.run()
    # router keys ARE cache keys, per tenant: the salted digests peek
    # exactly the chain that tenant's prefill registered
    assert reps[0].engine.cache.warm_prefix_tokens(
        p, keys=prefix_key(p, 8, 1)) == 16
    assert reps[0].engine.cache.warm_prefix_tokens(
        p, keys=prefix_key(p, 8, 2)) == 0
    rep, reason, warm = fleet._route(p, 1)
    assert (rep.rid, reason, warm) == (reps[0].rid, "affinity", 16)
    rep, reason, warm = fleet._route(p, 2)
    assert (rep.rid, reason, warm) == (reps[1].rid, "affinity", 16)
    # the base adapter owns neither chain: cold, least-loaded
    rep, reason, warm = fleet._route(p, 0)
    assert reason == "least_loaded" and warm == 0
    # end-to-end: each tenant's request lands on ITS warm replica and
    # actually hits (hit tokens grow there, never cross-tenant)
    h0 = reps[0].engine.prefix_hit_tokens
    h1 = reps[1].engine.prefix_hit_tokens
    r1 = fleet.add_request(p, 3, adapter_id=1)
    r2 = fleet.add_request(p, 3, adapter_id=2)
    out = fleet.run()
    assert reps[0].engine.prefix_hit_tokens == h0 + 16
    assert reps[1].engine.prefix_hit_tokens == h1 + 16
    assert out[r1] != out[r2]
    snap = fleet.metrics_snapshot()
    routed = {(s["labels"]["replica"], s["labels"]["reason"]):
              s["value"] for s in snap["fleet_routed_total"]["series"]}
    assert routed[(str(reps[0].rid), "affinity")] == 1
    assert routed[(str(reps[1].rid), "affinity")] == 1


def test_unknown_adapter_rejected_before_router_state(model):
    """Regression: an unregistered adapter_id must reject CLEANLY at
    fleet intake — before the routing record exists — or the phantom
    in-flight request deadlocks every later run() and strands all
    other results."""
    reg = _lora_registry(model.config)
    fleet = ServingFleet(model, num_replicas=2, num_slots=2,
                         block_size=8, prefill_chunk=8, adapters=reg)
    p = (np.arange(9, dtype=np.int32) % VOCAB)
    good = fleet.add_request(p, 2, adapter_id=1)
    with pytest.raises(ValueError, match="not registered"):
        fleet.add_request(p, 2, adapter_id=99)
    # no adapter subsystem at all: nonzero ids reject the same way
    bare = ServingFleet(model, num_replicas=1, num_slots=2,
                        block_size=8, prefill_chunk=8)
    with pytest.raises(ValueError, match="adapters="):
        bare.add_request(p, 2, adapter_id=1)
    assert fleet.num_outstanding == 1          # no phantom request
    out = fleet.run()                          # and the fleet still runs
    assert list(out) == [good]
    snap = fleet.metrics_snapshot()
    assert series_total(snap, "fleet_routed_total") == 1
