"""Elastic scale-out worker (VERDICT r4 next #1): attempt 1 loses two
ranks at once (simulated 2-rank host loss -> scale-in to the ACTUAL
survivor count); on the scaled-in attempt a "recovered host" announces
itself to the membership registry (PADDLE_ELASTIC_MASTER) and the ranks
idle until the launcher's membership watch re-rendezvouses the pod at
the bigger world; the final attempt finishes training there.

Usage (launch --nprocs 4 --elastic-min 2 --max-restarts 2):
    elastic_scaleout_worker.py <ckpt.json> <kill_sentinel>
"""
import json
import os
import signal
import sys
import time

import numpy as np


def main():
    ckpt_path, sentinel = sys.argv[1], sys.argv[2]

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    dist.init_parallel_env()
    rank = dist.get_rank()
    world = dist.get_world_size()

    start = 0
    if os.path.exists(ckpt_path):
        with open(ckpt_path) as f:
            start = json.load(f)["step"]

    for step in range(start, 10):
        t = paddle.to_tensor(np.ones((1,), np.float32))
        dist.all_reduce(t)
        assert float(np.asarray(t._array)[0]) == float(world)
        if rank == 0:
            tmp = ckpt_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"step": step + 1, "world": world}, f)
            os.replace(tmp, ckpt_path)
        first_attempt = not os.path.exists(sentinel)
        dist.barrier()
        if step == 5 and world == 4 and rank >= 2 and first_attempt:
            if rank == 3:
                open(sentinel, "w").close()
            print(f"KILLING self rank={rank} (2-rank host loss)",
                  flush=True)
            os.kill(os.getpid(), signal.SIGKILL)
        if step == 7 and world == 2:
            # the scaled-in attempt: a recovered host announces itself
            # (in a real job this is `launch.elastic join` on that
            # host); then idle — the launcher's membership watch tears
            # the pod down and relaunches at the bigger world
            if rank == 0:
                from paddle_tpu.distributed.launch.elastic import (
                    ElasticClient,
                )

                ElasticClient(
                    os.environ["PADDLE_ELASTIC_MASTER"]
                ).register("rejoined-host", ttl=120)
                print("announced rejoined-host", flush=True)
            time.sleep(300)  # ended by the launcher's SIGTERM

    print(f"ELASTIC_DONE rank={rank} world={world} resumed_from={start}",
          flush=True)


if __name__ == "__main__":
    main()
