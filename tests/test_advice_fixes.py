"""Regression tests for the round-1 ADVICE findings:
1. grad clip applied inside compiled TrainStep/DistributedTrainStep
2. frozen (stop_gradient) params not updated by TrainStep
3. dropout gets a fresh PRNG key per compiled step
4. cross_entropy use_softmax=False + weight/label_smoothing semantics
5. setitem records a tape node (correct grads through mutation)
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.jit as jit


def _tiny_model():
    paddle.seed(7)
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


def test_trainstep_applies_grad_clip():
    """ClipGradByGlobalNorm(1e-6) must freeze params to ~zero movement
    inside the compiled step (ADVICE r1 high #1)."""
    model = _tiny_model()
    before = [p.numpy().copy() for p in model.parameters()]
    opt = paddle.optimizer.Momentum(
        learning_rate=1.0, parameters=model.parameters(),
        grad_clip=nn.ClipGradByGlobalNorm(1e-6))
    step = jit.TrainStep(model, opt, lambda out, lab: F.mse_loss(out, lab))
    x = paddle.randn([4, 8])
    y = paddle.randn([4, 4])
    step(x, y)
    moved = sum(np.abs(p.numpy() - b).max()
                for p, b in zip(model.parameters(), before))
    assert moved < 1e-4, f"params moved by {moved} despite clip 1e-6"


def test_trainstep_grad_clip_matches_eager():
    """Compiled-step clip parity vs eager optimizer.step with the same
    clip (one SGD step, clip_norm small enough to actually engage)."""
    import copy

    paddle.seed(3)
    xe = paddle.randn([4, 8])
    ye = paddle.randn([4, 4])

    def build():
        paddle.seed(11)
        m = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4))
        o = paddle.optimizer.SGD(learning_rate=0.1,
                                 parameters=m.parameters(),
                                 grad_clip=nn.ClipGradByGlobalNorm(0.05))
        return m, o

    m1, o1 = build()
    loss = F.mse_loss(m1(xe), ye)
    loss.backward()
    o1.step()

    m2, o2 = build()
    step = jit.TrainStep(m2, o2, lambda out, lab: F.mse_loss(out, lab))
    step(xe, ye)

    for p1, p2 in zip(m1.parameters(), m2.parameters()):
        np.testing.assert_allclose(p1.numpy(), p2.numpy(), atol=1e-5)


def test_trainstep_skips_frozen_params():
    """stop_gradient=True params must not move (ADVICE r1 high #2)."""
    model = _tiny_model()
    frozen = model[0].bias
    frozen.stop_gradient = True
    fb = frozen.numpy().copy()
    opt = paddle.optimizer.Adam(learning_rate=0.5,
                                parameters=model.parameters())
    step = jit.TrainStep(model, opt, lambda out, lab: F.mse_loss(out, lab))
    for _ in range(3):
        step(paddle.randn([4, 8]), paddle.randn([4, 4]))
    np.testing.assert_array_equal(frozen.numpy(), fb)
    # and trainable params did move
    assert np.abs(model[0].weight.numpy()).sum() > 0


def test_trainstep_dropout_fresh_mask_per_step():
    """With lr=0 the loss depends only on the dropout mask; identical
    losses across steps would mean a baked-in key (ADVICE r1 medium #3)."""
    paddle.seed(5)
    model = nn.Sequential(nn.Linear(16, 64), nn.Dropout(0.5),
                          nn.Linear(64, 1))
    model.train()
    opt = paddle.optimizer.SGD(learning_rate=0.0,
                               parameters=model.parameters())
    step = jit.TrainStep(model, opt, lambda out, lab: F.mse_loss(out, lab))
    x = paddle.randn([8, 16])
    y = paddle.randn([8, 1])
    losses = [float(step(x, y)) for _ in range(4)]
    assert len(set(losses)) > 1, f"identical dropout mask every step: {losses}"
    # scan path too: per-step fold_in must vary the mask
    xs = paddle.stack([x, x, x], axis=0)
    ys = paddle.stack([y, y, y], axis=0)
    scan_losses = step.run_scan(xs, ys).numpy()
    assert len(set(np.round(scan_losses, 7).tolist())) > 1


def test_cross_entropy_use_softmax_false():
    """input already probabilities -> plain NLL (ADVICE r1 medium #4)."""
    probs = np.array([[0.7, 0.2, 0.1], [0.1, 0.6, 0.3]], np.float32)
    lab = np.array([0, 2], np.int64)
    expect = -np.log(probs[np.arange(2), lab]).mean()
    got = float(F.cross_entropy(paddle.to_tensor(probs),
                                paddle.to_tensor(lab), use_softmax=False))
    np.testing.assert_allclose(got, expect, rtol=1e-5)


def test_cross_entropy_weight_with_label_smoothing():
    """weight + label_smoothing used to crash with a broadcast error;
    weights must be selected by the ORIGINAL hard labels."""
    logits = paddle.to_tensor(
        np.random.RandomState(0).randn(6, 4).astype(np.float32))
    lab_np = np.array([0, 1, 2, 3, 1, 0], np.int64)
    lab = paddle.to_tensor(lab_np)
    w_np = np.array([1.0, 2.0, 0.5, 1.5], np.float32)
    w = paddle.to_tensor(w_np)
    got = float(F.cross_entropy(logits, lab, weight=w, label_smoothing=0.1))
    # reference: smoothed soft CE per-sample, weighted mean by w[label]
    lg = logits.numpy().astype(np.float64)
    logp = lg - np.log(np.exp(lg - lg.max(1, keepdims=True)).sum(1, keepdims=True)) - lg.max(1, keepdims=True)
    onehot = np.eye(4)[lab_np]
    soft = onehot * 0.9 + 0.1 / 4
    per = -(soft * logp).sum(1)
    wsel = w_np[lab_np]
    expect = (per * wsel).sum() / wsel.sum()
    np.testing.assert_allclose(got, expect, rtol=1e-4)


def test_cross_entropy_weighted_mean_denominator():
    """paddle semantics: weighted mean divides by sum of selected weights."""
    logits = paddle.to_tensor(
        np.random.RandomState(1).randn(4, 3).astype(np.float32))
    lab_np = np.array([0, 1, 2, 1], np.int64)
    w_np = np.array([2.0, 1.0, 0.5], np.float32)
    got = float(F.cross_entropy(logits, paddle.to_tensor(lab_np),
                                weight=paddle.to_tensor(w_np)))
    lg = logits.numpy().astype(np.float64)
    logp = lg - np.log(np.exp(lg).sum(1, keepdims=True))
    per = -logp[np.arange(4), lab_np]
    wsel = w_np[lab_np]
    expect = (per * wsel).sum() / wsel.sum()
    np.testing.assert_allclose(got, expect, rtol=1e-4)


def test_setitem_gradient_through_mutation():
    """y[0]=5 then y.sum().backward(): dx must be 0 at the overwritten
    position (ADVICE r1 medium #5 — previously gave dx=[2,2,2])."""
    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
    x.stop_gradient = False
    y = x * 2.0
    y[0] = 5.0
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [0.0, 2.0, 2.0])


def test_setitem_value_gradient():
    """The assigned value tensor receives the gathered cotangent."""
    x = paddle.to_tensor(np.zeros((3,), np.float32))
    x.stop_gradient = False
    v = paddle.to_tensor(np.array([7.0], np.float32))
    v.stop_gradient = False
    y = x * 3.0
    y[1] = v
    (y * paddle.to_tensor(np.array([1.0, 10.0, 100.0], np.float32))).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [3.0, 0.0, 300.0])
    np.testing.assert_allclose(v.grad.numpy(), [10.0])


def test_setitem_after_use_raises_version_error():
    """Mutating a tensor AFTER it fed another op must make backward of
    that op raise (torch/paddle version-counter semantics) instead of
    silently routing grads through the post-mutation graph."""
    w = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
    w.stop_gradient = False
    x = w * 2.0
    y = x.sum()
    x[0] = 0.0
    with pytest.raises(RuntimeError, match="mutated in place"):
        y.backward()


def test_cross_entropy_smoothing_with_ignore_index():
    """label_smoothing + ignore_index: ignored rows contribute zero loss
    and are excluded from the mean denominator."""
    rng = np.random.RandomState(2)
    logits_np = rng.randn(4, 3).astype(np.float32)
    lab_np = np.array([0, -100, 2, 1], np.int64)
    got = float(F.cross_entropy(paddle.to_tensor(logits_np),
                                paddle.to_tensor(lab_np),
                                label_smoothing=0.1, ignore_index=-100))
    lg = logits_np.astype(np.float64)
    logp = lg - np.log(np.exp(lg).sum(1, keepdims=True))
    valid = lab_np != -100
    onehot = np.eye(3)[np.where(valid, lab_np, 0)]
    soft = onehot * 0.9 + 0.1 / 3
    per = -(soft * logp).sum(1)
    expect = per[valid].mean()
    np.testing.assert_allclose(got, expect, rtol=1e-4)


def test_setitem_on_trainable_leaf_raises():
    x = paddle.to_tensor(np.ones((3,), np.float32))
    x.stop_gradient = False
    with pytest.raises(RuntimeError):
        x[0] = 2.0


def test_setitem_nograd_still_works():
    x = paddle.to_tensor(np.ones((3,), np.float32))
    x[0] = 9.0
    np.testing.assert_allclose(x.numpy(), [9.0, 1.0, 1.0])
    with paddle.no_grad():
        w = paddle.to_tensor(np.ones((2,), np.float32))
        w.stop_gradient = False
        w[0] = 4.0
        np.testing.assert_allclose(w.numpy(), [4.0, 1.0])
