"""Autograd engine tests — analytic grads vs numpy/finite-difference, the
OpTest check_grad pattern (unittests/op_test.py:2122 analog)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def _leaf(data):
    t = paddle.to_tensor(data)
    t.stop_gradient = False
    return t


def test_simple_backward():
    x = _leaf([2.0, 3.0])
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])


def test_chain():
    x = _leaf([1.0, 2.0])
    y = paddle.exp(x * 2.0).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 2 * np.exp([2.0, 4.0]), rtol=1e-5)


def test_grad_accumulation():
    x = _leaf([1.0])
    y1 = (x * 2.0).sum()
    y2 = (x * 3.0).sum()
    y1.backward()
    y2.backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])


def test_stop_gradient():
    x = _leaf([1.0, 2.0])
    w = paddle.to_tensor([3.0, 4.0])  # stop_gradient=True
    y = (x * w).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [3.0, 4.0])
    assert w.grad is None


def test_detach():
    x = _leaf([2.0])
    y = x * 3.0
    z = y.detach() * x
    z.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0])  # only via second factor


def test_matmul_grad():
    a = _leaf(np.random.randn(3, 4).astype(np.float32))
    b = _leaf(np.random.randn(4, 2).astype(np.float32))
    (a @ b).sum().backward()
    ga = np.ones((3, 2)) @ b.numpy().T
    gb = a.numpy().T @ np.ones((3, 2))
    np.testing.assert_allclose(a.grad.numpy(), ga, rtol=1e-5)
    np.testing.assert_allclose(b.grad.numpy(), gb, rtol=1e-5)


def test_broadcast_grad():
    x = _leaf(np.ones((3, 4), np.float32))
    b = _leaf(np.ones((4,), np.float32))
    (x + b).sum().backward()
    np.testing.assert_allclose(b.grad.numpy(), [3, 3, 3, 3])


def test_branching_graph():
    x = _leaf([2.0])
    a = x * 2.0
    b = x * 3.0
    y = (a + b).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])


def test_multi_output_op_grad():
    x = _leaf(np.array([3.0, 1.0, 2.0], np.float32))
    v, i = paddle.topk(x, 2)
    v.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [1.0, 0.0, 1.0])


def test_softmax_ce_grad_matches_numeric():
    logits = np.random.randn(4, 5).astype(np.float32)
    labels = np.array([0, 2, 1, 4])
    x = _leaf(logits)
    loss = paddle.nn.functional.cross_entropy(x, paddle.to_tensor(labels))
    loss.backward()
    # numeric gradient
    eps = 1e-3
    g = np.zeros_like(logits)
    import paddle_tpu.nn.functional as F

    for i in range(logits.shape[0]):
        for j in range(logits.shape[1]):
            lp = logits.copy()
            lp[i, j] += eps
            lm = logits.copy()
            lm[i, j] -= eps
            fp = float(F.cross_entropy(paddle.to_tensor(lp), paddle.to_tensor(labels)).numpy())
            fm = float(F.cross_entropy(paddle.to_tensor(lm), paddle.to_tensor(labels)).numpy())
            g[i, j] = (fp - fm) / (2 * eps)
    np.testing.assert_allclose(x.grad.numpy(), g, atol=1e-2)


def test_no_grad():
    x = _leaf([1.0])
    with paddle.no_grad():
        y = x * 2.0
    assert y.stop_gradient
    assert y._creator is None


def test_paddle_grad_api():
    x = _leaf([2.0])
    y = (x ** 3.0).sum()
    (gx,) = paddle.grad(y, x)
    np.testing.assert_allclose(gx.numpy(), [12.0], rtol=1e-5)
    assert x.grad is None  # .grad slot untouched


def test_backward_with_grad_tensor():
    x = _leaf([1.0, 2.0])
    y = x * 2.0
    y.backward(paddle.to_tensor([1.0, 0.5]))
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 1.0])


def test_register_hook():
    x = _leaf([1.0])
    y = x * 2.0
    seen = []
    y.register_hook(lambda g: seen.append(g.numpy()) or g * 2.0)
    y.sum().backward()
    assert len(seen) == 1
    np.testing.assert_allclose(x.grad.numpy(), [4.0])


def test_retain_graph():
    x = _leaf([2.0])
    y = (x * x).sum()
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [8.0])


def test_embedding_int_input_grad():
    w = _leaf(np.random.randn(10, 4).astype(np.float32))
    ids = paddle.to_tensor([1, 3, 1])
    out = paddle.nn.functional.embedding(ids, w)
    out.sum().backward()
    expect = np.zeros((10, 4), np.float32)
    expect[1] = 2.0
    expect[3] = 1.0
    np.testing.assert_allclose(w.grad.numpy(), expect)
