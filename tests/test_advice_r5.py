"""Regression tests for ADVICE round-4 findings (all low severity).

1. fleet.init rejects degree products that don't divide the device
   count (not just products larger than it).
2. ASP check_mask_2d is vacuously True for matrices with no complete
   m x m block, so prune-then-verify round-trips on small layers.
3. bench.py exits nonzero when ANY model row fails, not only the
   flagship (last) row.
4. PS Communicator: push after stop() raises instead of enqueueing into
   a dead queue; a drain-thread error is surfaced once, not forever.
5. bench_ops conv sweep seeds weights deterministically (crc32, not
   randomized str hash).
"""
import zlib

import numpy as np
import pytest


def test_fleet_init_rejects_non_dividing_degree_product():
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet import DistributedStrategy

    s = DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 3}  # 8 devices: 3 doesn't divide
    with pytest.raises(ValueError, match="divide"):
        fleet.init(is_collective=True, strategy=s)
    # a dividing product still initializes
    s2 = DistributedStrategy()
    s2.hybrid_configs = {"dp_degree": 2, "mp_degree": 2}
    hcg = fleet.init(is_collective=True, strategy=s2)
    assert hcg is not None


def test_asp_check_mask_2d_small_matrix_vacuously_true():
    from paddle_tpu.incubate import asp

    small = np.ones((2, 2), np.float32)
    mask = asp.create_mask_2d_greedy(small)
    assert mask.shape == (2, 2)
    # the round trip must agree: the greedy mask for a block-less
    # matrix is dense, and check reports it compliant
    assert asp.check_mask_2d(mask)
    assert asp.check_mask_2d(np.ones((3, 7), np.float32))
    # 1d checker agrees on the vacuous case (same remainder contract)
    assert asp.check_mask_1d(np.ones((3, 8), np.float32))
    assert not asp.check_mask_1d(np.ones((8, 8), np.float32))
    # non-2d stays invalid, complete blocks still checked
    assert not asp.check_mask_2d(np.ones(4, np.float32))
    assert not asp.check_mask_2d(np.ones((4, 4), np.float32))


def test_bench_exits_nonzero_when_any_row_fails(monkeypatch):
    import bench

    ok = ({"metric": "m", "value": 1.0, "unit": "u",
           "vs_baseline": 1.0}, "info")

    def boom(on_tpu):
        raise RuntimeError("synthetic row failure")

    monkeypatch.setenv("BENCH_MODEL", "all")
    monkeypatch.setattr(bench, "bench_bert", boom)
    monkeypatch.setattr(bench, "bench_resnet50", lambda on_tpu: ok)
    monkeypatch.setattr(bench, "bench_gpt", lambda on_tpu: ok)
    with pytest.raises(SystemExit) as ei:
        bench.main()
    assert ei.value.code == 1
    # all green -> exit 0 (main returns without SystemExit)
    monkeypatch.setattr(bench, "bench_bert", lambda on_tpu: ok)
    bench.main()


class _FlakyClient:
    dim = 4

    def __init__(self, fail_times=1):
        self.fail_times = fail_times
        self.pushed = []

    def push_direct(self, ids, grads, wait=True):
        if self.fail_times > 0:
            self.fail_times -= 1
            raise RuntimeError("transport down")
        self.pushed.append((ids.copy(), grads.copy()))


def test_communicator_push_after_stop_raises():
    from paddle_tpu.distributed.ps.service import Communicator

    comm = Communicator(mode="async")
    comm.bind(_FlakyClient(fail_times=0))
    ids = np.arange(2, dtype=np.int64)
    grads = np.ones((2, 4), np.float32)
    comm.push(ids, grads)
    comm.stop()
    with pytest.raises(RuntimeError, match="stop"):
        comm.push(ids, grads)


def test_communicator_drain_error_surfaces_once():
    from paddle_tpu.distributed.ps.service import Communicator

    comm = Communicator(mode="async")
    client = _FlakyClient(fail_times=1)
    comm.bind(client)
    ids = np.arange(2, dtype=np.int64)
    grads = np.ones((2, 4), np.float32)
    comm.push(ids, grads)
    with pytest.raises(RuntimeError, match="transport down"):
        comm.flush()
    # error is consumed: later pushes work and flush is clean
    comm.push(ids, grads)
    comm.flush()
    assert len(client.pushed) == 1
    comm.stop()


def test_bench_ops_conv_seed_deterministic():
    import bench_ops

    cases = bench_ops.suite()
    name = "conv_c2_3x3_64"
    _, (i, w), _ = cases[name]
    expect = bench_ops._rand(w.shape,
                             seed=zlib.crc32(name.encode()) % 97)
    assert np.array_equal(np.asarray(w, np.float32),
                          np.asarray(expect, np.float32))
