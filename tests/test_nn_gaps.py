"""3-D pooling, Unfold/Fold (im2col/col2im), SpectralNorm, and
ConcatDataset — reference python/paddle/nn/layer/{pooling,common,norm}.py
and python/paddle/io.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.io import ConcatDataset, TensorDataset


def test_max_avg_pool3d():
    rs = np.random.RandomState(0)
    x = rs.randn(2, 3, 4, 8, 8).astype(np.float32)
    mx = nn.MaxPool3D(2)(paddle.to_tensor(x))
    av = nn.AvgPool3D(2)(paddle.to_tensor(x))
    assert mx.shape == [2, 3, 2, 4, 4] and av.shape == [2, 3, 2, 4, 4]
    # numpy reference on one window
    win = x[0, 0, :2, :2, :2]
    np.testing.assert_allclose(mx.numpy()[0, 0, 0, 0, 0], win.max(),
                               rtol=1e-6)
    np.testing.assert_allclose(av.numpy()[0, 0, 0, 0, 0], win.mean(),
                               rtol=1e-5)


def test_unfold_matches_manual_im2col():
    img = np.arange(1 * 2 * 4 * 4, dtype=np.float32).reshape(1, 2, 4, 4)
    u = nn.Unfold(2)(paddle.to_tensor(img)).numpy()
    manual = np.zeros((1, 8, 9), np.float32)
    i = 0
    for ho in range(3):
        for wo in range(3):
            manual[0, :, i] = img[0][:, ho:ho + 2, wo:wo + 2].reshape(-1)
            i += 1
    np.testing.assert_allclose(u, manual)


def test_fold_is_unfold_adjoint():
    """fold(unfold(x)) multiplies each pixel by its patch multiplicity
    (exactly 9 for interior pixels of a 3x3/s1/p1 unfold)."""
    img = np.zeros((1, 1, 6, 6), np.float32)
    img[0, 0, 3, 3] = 1.0
    u = nn.Unfold(3, strides=1, paddings=1)(paddle.to_tensor(img))
    f = nn.Fold((6, 6), 3, strides=1, paddings=1)(u).numpy()
    assert f[0, 0, 3, 3] == 9.0 and f.sum() == 9.0


def test_unfold_fold_gradients():
    x = paddle.to_tensor(
        np.random.RandomState(1).randn(1, 2, 6, 6).astype(np.float32))
    x.stop_gradient = False
    u = nn.Unfold(3, paddings=1)(x)
    nn.Fold((6, 6), 3, paddings=1)(u).sum().backward()
    assert x.grad is not None
    # d(sum fold(unfold(x)))/dx = patch multiplicity map (9 interior)
    g = np.asarray(x.grad._array)
    assert g[0, 0, 3, 3] == 9.0 and g[0, 0, 0, 0] == 4.0  # corner: 4


def test_spectral_norm_unit_sigma():
    paddle.seed(0)
    sn = nn.SpectralNorm((8, 16), power_iters=30)
    w = np.random.RandomState(2).randn(8, 16).astype(np.float32)
    out = sn(paddle.to_tensor(w)).numpy()
    # after normalization the top singular value is ~1
    s = np.linalg.svd(out.reshape(8, -1), compute_uv=False)
    np.testing.assert_allclose(s[0], 1.0, atol=1e-3)
    # eval mode keeps u/v fixed (no iteration) but still normalizes
    sn.eval()
    out2 = sn(paddle.to_tensor(w)).numpy()
    s2 = np.linalg.svd(out2.reshape(8, -1), compute_uv=False)
    np.testing.assert_allclose(s2[0], 1.0, atol=1e-3)


def test_spectral_norm_gradient_flows_to_weight():
    paddle.seed(0)
    sn = nn.SpectralNorm((4, 4), power_iters=5)
    w = paddle.to_tensor(
        np.random.RandomState(3).randn(4, 4).astype(np.float32))
    w.stop_gradient = False
    sn(w).sum().backward()
    assert w.grad is not None


def test_concat_dataset():
    a = TensorDataset([paddle.to_tensor(np.arange(3, dtype=np.float32))])
    b = TensorDataset([paddle.to_tensor(np.arange(10, 15,
                                                  dtype=np.float32))])
    cd = ConcatDataset([a, b])
    assert len(cd) == 8
    vals = [float(cd[i][0]._array) for i in range(8)]
    assert vals == [0, 1, 2, 10, 11, 12, 13, 14]
    assert float(cd[-1][0]._array) == 14


def test_pool3d_rejects_unsupported_modes():
    x = paddle.to_tensor(np.zeros((1, 1, 2, 4, 4), np.float32))
    with pytest.raises(NotImplementedError, match="ceil_mode"):
        nn.MaxPool3D(2, ceil_mode=True)(x)
    with pytest.raises(NotImplementedError, match="NCDHW"):
        nn.AvgPool3D(2, data_format="NDHWC")(x)
    with pytest.raises(NotImplementedError, match="return_mask"):
        nn.MaxPool3D(2, return_mask=True)(x)


def test_spectral_norm_eval_from_fresh_buffers_still_normalizes():
    paddle.seed(1)
    sn = nn.SpectralNorm((8, 16), power_iters=30)
    sn.eval()  # never trained: power iteration must still run
    w = np.random.RandomState(4).randn(8, 16).astype(np.float32)
    out = sn(paddle.to_tensor(w)).numpy()
    s = np.linalg.svd(out, compute_uv=False)
    np.testing.assert_allclose(s[0], 1.0, atol=1e-3)
    # eval did not advance the stored state
    u_before = np.asarray(sn.weight_u._array).copy()
    sn(paddle.to_tensor(w))
    np.testing.assert_allclose(np.asarray(sn.weight_u._array), u_before)


def test_concat_dataset_rejects_out_of_range_negative():
    a = TensorDataset([paddle.to_tensor(np.arange(3, dtype=np.float32))])
    cd = ConcatDataset([a])
    with pytest.raises(ValueError, match="out of range"):
        cd[-4]
