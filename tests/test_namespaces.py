"""distribution / sparse / quantization namespace tests (SURVEY item 38,
VERDICT r2 missing #8).

Reference analogs: python/paddle/distribution/, python/paddle/sparse/,
python/paddle/quantization/.
"""
import numpy as np
import pytest
from scipy import stats as spstats

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distribution import (Bernoulli, Categorical, Normal,
                                     Uniform, kl_divergence)


# -- distribution -------------------------------------------------------
def test_normal_log_prob_and_entropy():
    d = Normal(loc=1.0, scale=2.0)
    for v in (-1.0, 0.0, 3.5):
        np.testing.assert_allclose(float(d.log_prob(v)._array),
                                   spstats.norm.logpdf(v, 1.0, 2.0),
                                   rtol=1e-5)
    np.testing.assert_allclose(float(d.entropy()._array),
                               spstats.norm.entropy(1.0, 2.0), rtol=1e-5)


def test_normal_sample_statistics():
    paddle.seed(0)
    d = Normal(loc=np.array([0.0, 5.0], np.float32), scale=1.0)
    s = np.asarray(d.sample((4000,))._array)
    assert s.shape == (4000, 2)
    np.testing.assert_allclose(s.mean(0), [0.0, 5.0], atol=0.1)
    np.testing.assert_allclose(s.std(0), [1.0, 1.0], atol=0.1)


def test_normal_rsample_grad_flows():
    paddle.seed(0)
    loc = paddle.to_tensor(np.array([0.5], np.float32))
    loc.stop_gradient = False
    d = Normal(loc=loc, scale=1.0)
    s = d.rsample((64,))
    s.mean().backward()
    assert loc.grad is not None
    np.testing.assert_allclose(float(loc.grad._array[0]), 1.0, rtol=1e-4)


def test_categorical_and_kl():
    logits = np.log(np.array([[0.2, 0.3, 0.5]], np.float32))
    d = Categorical(logits=logits)
    np.testing.assert_allclose(float(d.log_prob(np.array([2]))._array[0]),
                               np.log(0.5), rtol=1e-5)
    q = Categorical(probs=np.array([[1 / 3] * 3], np.float32))
    kl = float(kl_divergence(d, q)._array[0])
    want = (np.array([0.2, 0.3, 0.5]) *
            np.log(np.array([0.2, 0.3, 0.5]) / (1 / 3))).sum()
    np.testing.assert_allclose(kl, want, rtol=1e-5)


def test_kl_normal_normal_closed_form():
    p, q = Normal(0.0, 1.0), Normal(1.0, 2.0)
    got = float(kl_divergence(p, q)._array)
    vr = 0.25
    want = 0.5 * (vr + 0.25 - 1 - np.log(vr))
    np.testing.assert_allclose(got, want, rtol=1e-5)
    with pytest.raises(NotImplementedError, match="no KL"):
        kl_divergence(p, Bernoulli(probs=0.5))


def test_kl_gradient_reaches_parameters():
    """VAE-style: KL(Normal(mu,1) || Normal(0,1)) must train mu."""
    mu = paddle.to_tensor(np.array([2.0], np.float32))
    mu.stop_gradient = False
    kl = kl_divergence(Normal(mu, 1.0), Normal(0.0, 1.0))
    kl.sum().backward()
    assert mu.grad is not None
    # d/dmu [0.5*mu^2] = mu
    np.testing.assert_allclose(np.asarray(mu.grad._array), [2.0],
                               rtol=1e-5)


def test_uniform_bernoulli():
    u = Uniform(low=2.0, high=4.0)
    assert float(u.log_prob(3.0)._array) == pytest.approx(np.log(0.5))
    assert float(u.log_prob(5.0)._array) == -np.inf
    b = Bernoulli(probs=0.25)
    np.testing.assert_allclose(float(b.log_prob(1.0)._array), np.log(0.25),
                               rtol=1e-5)
    paddle.seed(1)
    s = np.asarray(b.sample((5000,))._array)
    assert abs(s.mean() - 0.25) < 0.03


# -- sparse -------------------------------------------------------------
def _coo_fixture():
    dense = np.zeros((3, 4), np.float32)
    dense[0, 1] = 1.0
    dense[1, 3] = 2.0
    dense[2, 0] = -3.0
    idx = np.array([[0, 1, 2], [1, 3, 0]])
    vals = np.array([1.0, 2.0, -3.0], np.float32)
    return dense, idx, vals


def test_sparse_coo_roundtrip():
    dense, idx, vals = _coo_fixture()
    sp = paddle.sparse.sparse_coo_tensor(idx, vals, [3, 4])
    assert sp.nnz() == 3 and sp.is_sparse_coo()
    np.testing.assert_array_equal(np.asarray(sp.to_dense()._array), dense)
    # dense -> coo -> csr -> dense
    t = paddle.to_tensor(dense)
    coo = t.to_sparse_coo(2)
    assert coo.nnz() == 3
    csr = coo.to_sparse_csr()
    np.testing.assert_array_equal(np.asarray(csr.crows()._array),
                                  [0, 1, 2, 3])
    np.testing.assert_array_equal(np.asarray(csr.to_dense()._array), dense)


def test_sparse_matmul_matches_dense_and_backprops():
    dense, idx, vals = _coo_fixture()
    sp = paddle.sparse.sparse_coo_tensor(idx, vals, [3, 4])
    y = paddle.to_tensor(np.arange(8, dtype=np.float32).reshape(4, 2))
    y.stop_gradient = False
    out = paddle.sparse.matmul(sp, y)
    np.testing.assert_allclose(np.asarray(out._array), dense @
                               np.asarray(y._array), rtol=1e-6)
    out.sum().backward()
    np.testing.assert_allclose(np.asarray(y.grad._array),
                               dense.T @ np.ones((3, 2), np.float32),
                               rtol=1e-6)


def test_sparse_add_and_unary():
    dense, idx, vals = _coo_fixture()
    a = paddle.sparse.sparse_coo_tensor(idx, vals, [3, 4])
    b = paddle.sparse.sparse_coo_tensor(idx, vals, [3, 4])
    s = paddle.sparse.add(a, b)
    np.testing.assert_array_equal(np.asarray(s.to_dense()._array),
                                  2 * dense)
    r = paddle.sparse.relu(a)
    np.testing.assert_array_equal(np.asarray(r.to_dense()._array),
                                  np.maximum(dense, 0))
    # different patterns: union + coalesce
    idx2 = np.array([[0, 2], [1, 0]])
    c = paddle.sparse.sparse_coo_tensor(idx2,
                                        np.array([10.0, 5.0], np.float32),
                                        [3, 4])
    u = paddle.sparse.add(a, c)
    want = dense.copy()
    want[0, 1] += 10.0
    want[2, 0] += 5.0
    np.testing.assert_array_equal(np.asarray(u.to_dense()._array), want)


def test_sparse_masked_matmul():
    rs = np.random.RandomState(0)
    A = rs.randn(3, 5).astype(np.float32)
    B = rs.randn(5, 4).astype(np.float32)
    _, idx, _ = _coo_fixture()
    mask = paddle.sparse.sparse_coo_tensor(
        idx, np.ones(3, np.float32), [3, 4])
    out = paddle.sparse.masked_matmul(paddle.to_tensor(A),
                                      paddle.to_tensor(B), mask)
    full = A @ B
    got = np.asarray(out.values()._array)
    for k, (i, j) in enumerate(zip(idx[0], idx[1])):
        np.testing.assert_allclose(got[k], full[i, j], rtol=1e-5)


# -- quantization -------------------------------------------------------
def test_quantize_absmax_roundtrip():
    from paddle_tpu.quantization import dequantize, quantize_absmax

    w = paddle.to_tensor(np.linspace(-2, 2, 32).astype(np.float32))
    q, scale = quantize_absmax(w)
    assert str(q.dtype) == "int8"
    np.testing.assert_allclose(np.asarray(dequantize(q, scale)),
                               np.asarray(w._array), atol=2 / 127 + 1e-6)


def test_fake_quant_ste_gradient():
    from paddle_tpu.quantization import fake_quant

    x = paddle.to_tensor(np.array([0.11, -0.49, 0.3], np.float32))
    x.stop_gradient = False
    y = fake_quant(x, np.float32(0.1))
    np.testing.assert_allclose(np.asarray(y._array), [0.1, -0.5, 0.3],
                               atol=1e-6)
    y.sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad._array), [1, 1, 1])


def test_qat_trains_and_ptq_converts():
    from paddle_tpu.quantization import (PTQ, QAT, QuantConfig,
                                         FakeQuanterWithAbsMaxObserver,
                                         QuantedLinear)

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4))
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(16, 8).astype(np.float32))
    ref = np.asarray(net(x)._array)

    # QAT: fake-quant wrappers train
    qat_net = QAT(QuantConfig(
        activation=FakeQuanterWithAbsMaxObserver,
        weight=FakeQuanterWithAbsMaxObserver)).quantize(net)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=qat_net.parameters())
    tgt = paddle.to_tensor(np.zeros((16, 4), np.float32))
    losses = []
    for _ in range(5):
        loss = F.mse_loss(qat_net(x), tgt)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]

    # PTQ: observe then convert to int8-weight layers
    paddle.seed(0)
    net2 = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4))
    ptq = PTQ()
    net2 = ptq.quantize(net2)
    for _ in range(3):
        net2(x)  # calibration passes
    net2 = ptq.convert(net2)
    assert isinstance(net2[0], QuantedLinear)
    out = np.asarray(net2(x)._array)
    # int8 weights: close to the fp32 reference (same seed)
    paddle.seed(0)
    net3 = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4))
    ref3 = np.asarray(net3(x)._array)
    assert np.abs(out - ref3).max() < 0.1
