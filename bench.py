"""Benchmark: GPT training throughput on one chip, bf16, fully-compiled
TrainStep (fwd+bwd+AdamW in a single donated XLA program).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
vs_baseline is achieved MFU / 0.45 (the BASELINE.md target MFU).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def main():
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.jit as jit
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    backend = jax.default_backend()
    on_tpu = backend in ("tpu", "axon")

    if on_tpu:
        # the BASELINE.md flagship: GPT-3 1.3B class. hidden=2048/head_dim=128
        # saturates the MXU (hidden=768-class matmuls measured at <30% peak on
        # v5e); batch 2 fits without remat — recompute-free beats every remat
        # policy measured (0.432 vs 0.382 MFU pure-jax).
        cfg = GPTConfig(vocab_size=32768, hidden_size=2048, num_layers=24,
                        num_heads=16, max_seq_len=2048, dropout=0.0)
        batch = int(os.environ.get("BENCH_BATCH", "2"))
        steps = int(os.environ.get("BENCH_STEPS", "10"))
        peak_flops = 197e12  # v5e bf16 peak per chip
    else:  # CPU smoke mode
        cfg = GPTConfig.tiny(vocab=512, hidden=128, layers=2, heads=4, seq=128)
        batch, steps = 2, 5
        peak_flops = 1e12

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()  # no dropout inside compiled step
    model.to(dtype="bfloat16")  # MXU-native; optimizer keeps fp32 master state
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    step = jit.TrainStep(model, opt, model.loss_fn)

    seq = cfg.max_seq_len

    # multi-step: the whole timed region is ONE XLA program (lax.scan over
    # steps) so per-dispatch latency doesn't pollute the measurement
    ids_stack = paddle.to_tensor(
        np.random.randint(0, cfg.vocab_size, (steps, batch, seq), np.int32))

    t0 = time.time()
    losses = step.run_scan(ids_stack, ids_stack)  # compile + first run
    np.asarray(losses._array)  # full readback: block_until_ready is unreliable through the axon tunnel
    compile_s = time.time() - t0

    t1 = time.time()
    losses = step.run_scan(ids_stack, ids_stack)
    np.asarray(losses._array)  # full readback: block_until_ready is unreliable through the axon tunnel
    dt = time.time() - t1
    loss = losses[-1]

    tokens_per_step = batch * seq
    tok_s = tokens_per_step * steps / dt
    # training FLOPs/token: 6N (fwd+bwd params) + attention term
    n_params = model.num_params()
    flops_tok = model.flops_per_token(seq)
    mfu = tok_s * flops_tok / peak_flops

    result = {
        "metric": "gpt_1p3b_train_tokens_per_sec_per_chip",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4),
    }
    print(json.dumps(result))
    print(f"# backend={backend} params={n_params/1e6:.1f}M batch={batch} "
          f"seq={seq} steps={steps} compile={compile_s:.1f}s "
          f"step={dt/steps*1000:.1f}ms mfu={mfu:.3f} loss={float(loss):.3f}",
          file=sys.stderr)


if __name__ == "__main__":
    main()
