"""Benchmark suite: training throughput on one chip, bf16, fully-compiled
TrainStep (fwd+bwd+optimizer in a single donated XLA program).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
vs_baseline is achieved MFU / 0.45 (the BASELINE.md target MFU).

BENCH_MODEL selects the BASELINE.md row:
  gpt      (default) GPT-3 1.3B class, tokens/s/chip      — row 3
  bert     BERT-base seq-512 fine-tune, tokens/s/chip      — row 2
  resnet50 ResNet-50 @224 synthetic data, images/s/chip    — row 1
Run all three: for m in gpt bert resnet50; do BENCH_MODEL=$m python bench.py; done
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

V5E_PEAK = 197e12  # bf16 FLOP/s per v5e chip

# ResNet-50 @224 fwd FLOPs (2*MACs, the torchvision/PaddleClas-quoted
# 4.1 GFLOPs); training fwd+bwd ~= 3x fwd.
RESNET50_FWD_FLOPS = 4.09e9


def _run_scan_steps(step, xs, ys):
    """Time xs.shape[0] training steps executed as ONE XLA program
    (lax.scan); returns (dt_seconds, compile_seconds, last_loss)."""
    t0 = time.time()
    losses = step.run_scan(xs, ys)
    np.asarray(losses._array)  # readback: block_until_ready is unreliable through the axon tunnel
    compile_s = time.time() - t0
    t1 = time.time()
    losses = step.run_scan(xs, ys)
    np.asarray(losses._array)
    dt = time.time() - t1
    return dt, compile_s, losses[-1]


def _run_repeat_steps(step, x, y, steps):
    """Like _run_scan_steps but feeds ONE batch repeatedly (TrainStep.
    run_repeat): a [steps, batch, 3, 224, 224] input stack would occupy
    multiple GB of HBM and starve the model (measured: batch=256 resnet
    went 61ms -> 1814ms/step purely from stacked-input pressure)."""
    t0 = time.time()
    losses = step.run_repeat(x, y, steps)
    np.asarray(losses._array)
    compile_s = time.time() - t0
    t1 = time.time()
    losses = step.run_repeat(x, y, steps)
    np.asarray(losses._array)
    dt = time.time() - t1
    return dt, compile_s, losses[-1]


def _emit(metric, unit, rate, flops_per_unit, on_tpu, extra):
    """Uniform result row: rate in units/s, MFU vs the BASELINE.md 0.45
    target on the v5e peak (1e12 nominal peak in CPU smoke mode).
    hbm_gb = currently-allocated device bytes after the run (live-array
    accounting — the axon tunnel publishes no PJRT allocator stats, see
    paddle_tpu/device/memory.py)."""
    peak = V5E_PEAK if on_tpu else 1e12
    mfu = rate * flops_per_unit / peak
    try:
        from paddle_tpu.device import memory as dmem

        hbm_gb = round(dmem.record_peak() / 1e9, 2)
    except Exception:
        hbm_gb = None
    return {
        "metric": metric,
        "value": round(rate, 1),
        "unit": unit,
        "vs_baseline": round(mfu / 0.45, 4),
    }, f"{extra} mfu={mfu:.3f} hbm_gb={hbm_gb}"


def bench_gpt(on_tpu):
    import paddle_tpu as paddle
    import paddle_tpu.jit as jit
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    if on_tpu:
        # the BASELINE.md flagship: GPT-3 1.3B class. hidden=2048/head_dim=128
        # saturates the MXU; batch 2 fits without remat — recompute-free
        # beats every remat policy measured.
        cfg = GPTConfig(vocab_size=32768, hidden_size=2048, num_layers=24,
                        num_heads=16, max_seq_len=2048, dropout=0.0)
        batch = int(os.environ.get("BENCH_BATCH", "2"))
        steps = int(os.environ.get("BENCH_STEPS", "10"))
    else:  # CPU smoke mode
        cfg = GPTConfig.tiny(vocab=512, hidden=128, layers=2, heads=4, seq=128)
        batch, steps = 2, 5

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()  # no dropout inside compiled step
    model.to(dtype="bfloat16")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    step = jit.TrainStep(model, opt, model.loss_fn)

    seq = cfg.max_seq_len
    ids = paddle.to_tensor(
        np.random.randint(0, cfg.vocab_size, (steps, batch, seq), np.int32))
    dt, compile_s, loss = _run_scan_steps(step, ids, ids)

    tok_s = batch * seq * steps / dt
    return _emit(
        "gpt_1p3b_train_tokens_per_sec_per_chip", "tokens/s", tok_s,
        model.flops_per_token(seq), on_tpu,
        f"params={model.num_params()/1e6:.1f}M batch={batch} seq={seq} "
        f"steps={steps} compile={compile_s:.1f}s step={dt/steps*1000:.1f}ms "
        f"loss={float(loss):.3f}")


# Measured ceilings on the bench chip (v5e via the axon tunnel), for
# reading the numbers below in context:
# - Large-matmul FLOPs (GPT ffn shapes) sustain ~118 TF/s inside the
#   full compiled train step. The flagship's decoder attention was the
#   next-largest term (~110ms of the r3 305ms step; the tuned library
#   flash kernel runs 22.5 TF/s causal-useful at B2 H16 S2048 D128);
#   the chunked causal kernel (flash_attention.py
#   chunked_causal_attention: whole head per program, static prefix-k
#   blocks, exact softmax, single-pass bwd) runs 1.74x faster and took
#   the row from 0.61 to 0.66 MFU in r4.
# - BERT-base e2e was attention-bound in r3 (0.36 mfu): at S512/D64 the
#   library flash kernel runs 8.9 ms/layer fwd+bwd (768 tiny programs,
#   twice-recomputing backward). The fused short-seq kernel
#   (ops/pallas/flash_attention.py shortseq_attention: whole seq in
#   VMEM, 6 heads per program, single-pass 5-GEMM backward) runs 4.15
#   ms/layer, lifting the row to 0.53 mfu (r4).
# - ResNet-50's ~0.15 mfu is an HBM-bandwidth roofline, NOT a conv-
#   engine ceiling. The r4 OPBENCH sweep (fixed adaptive timing)
#   shows the convs themselves run fast — 150-280 TF/s fwd+bwd for
#   every stage-2+ shape (OPBENCH.json conv_* rows). Stage-resolved
#   e2e timing at batch 256 (truncated-model runs): layer1 36.6ms,
#   layer2 26.0ms, layer3 21.9ms, layer4 4.4ms, stem+pool+head 19.9ms.
#   A c2 bottleneck block moves ~10GB of activations fwd+bwd
#   (56x56x256 tensors through 3 convs + 3 BNs + residual), i.e.
#   ~12ms at the 819GB/s HBM peak — and measures 12.2ms: the early
#   stages run at ~90% of the bandwidth roofline. v5e's 240 FLOP/byte
#   ratio makes bf16 ResNet-50 bandwidth-bound below ~0.18 mfu at any
#   batch (remat of blocks: -3%; BN removal: -27ms, confirming BN
#   traffic as the second-largest term). 2350 img/s/chip is in line
#   with published v5e ResNet-50 numbers; throughput, not
#   mfu-vs-matmul-peak, is the comparable metric for the conv bench.
# - r5 bounded fusion attempt (the one untried lever): replacing batch
#   BN with a per-channel affine — the zero-traffic upper bound for a
#   perfect conv+BN+ReLU fusion with epilogue stats + load-time
#   normalize — takes a c2 bottleneck block fwd+bwd from 1.79 ms to
#   1.14 ms at B64 (fwd-only 0.69->0.38; the gap splits evenly fwd/
#   bwd). So full fusion could reach ~0.19-0.20 MFU, but BOTH passes
#   need conv-kernel-resident stats/normalize: scale-shift cannot fold
#   through ReLU into the next conv's weights, and XLA does not fuse
#   elementwise into conv operands on TPU — realizing it means a
#   custom Pallas conv suite (fwd+bwd), out of scope. The repo BN is
#   already the optimal XLA formulation (single-pass f32 E[x^2]-m^2
#   stats). The row's justification: HBM roofline, evidence above.


def bench_bert(on_tpu):
    import paddle_tpu as paddle
    import paddle_tpu.jit as jit
    from paddle_tpu.models import BertConfig, BertForSequenceClassification

    if on_tpu:
        cfg = BertConfig.bert_base()
        # 64 = the largest power-of-two batch that fits 16G HBM at seq 512
        batch = int(os.environ.get("BENCH_BATCH", "64"))
        seq = 512
        steps = int(os.environ.get("BENCH_STEPS", "10"))
    else:
        cfg = BertConfig.tiny()
        batch, seq, steps = 2, 64, 5
    cfg.hidden_dropout = 0.0
    cfg.attention_dropout = 0.0

    paddle.seed(0)
    model = BertForSequenceClassification(cfg)
    model.eval()
    model.to(dtype="bfloat16")
    opt = paddle.optimizer.AdamW(learning_rate=2e-5,
                                 parameters=model.parameters())
    step = jit.TrainStep(model, opt, model.loss_fn)

    ids = paddle.to_tensor(
        np.random.randint(0, cfg.vocab_size, (batch, seq), np.int32))
    labels = paddle.to_tensor(
        np.random.randint(0, cfg.num_labels, (batch,), np.int64))
    dt, compile_s, loss = _run_repeat_steps(step, ids, labels, steps)

    tok_s = batch * seq * steps / dt
    return _emit(
        "bert_base_finetune_tokens_per_sec_per_chip", "tokens/s", tok_s,
        model.flops_per_token(seq), on_tpu,
        f"params={model.num_params()/1e6:.1f}M batch={batch} seq={seq} "
        f"steps={steps} compile={compile_s:.1f}s step={dt/steps*1000:.1f}ms "
        f"loss={float(loss):.3f}")


def bench_resnet50(on_tpu):
    import paddle_tpu as paddle
    import paddle_tpu.jit as jit
    import paddle_tpu.nn.functional as F
    from paddle_tpu.vision.models import resnet50

    if on_tpu:
        batch = int(os.environ.get("BENCH_BATCH", "256"))
        size, classes = 224, 1000
        steps = int(os.environ.get("BENCH_STEPS", "10"))
        fwd_flops = RESNET50_FWD_FLOPS
    else:
        batch, size, classes, steps = 4, 32, 10, 3
        fwd_flops = RESNET50_FWD_FLOPS * (32 / 224) ** 2

    paddle.seed(0)
    model = resnet50(num_classes=classes)
    model.to(dtype="bfloat16")
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=model.parameters())
    step = jit.TrainStep(model, opt, F.cross_entropy)

    imgs = paddle.to_tensor(np.random.uniform(
        -1, 1, (batch, 3, size, size)).astype(np.float32))
    imgs = imgs.astype("bfloat16")
    labels = paddle.to_tensor(
        np.random.randint(0, classes, (batch,), np.int64))
    dt, compile_s, loss = _run_repeat_steps(step, imgs, labels, steps)

    imgs_s = batch * steps / dt
    return _emit(
        "resnet50_train_images_per_sec_per_chip", "images/s", imgs_s,
        3 * fwd_flops, on_tpu,
        f"batch={batch} size={size} steps={steps} compile={compile_s:.1f}s "
        f"step={dt/steps*1000:.1f}ms loss={float(loss):.3f} "
        "| hbm-roofline row: early stages ~90% of bandwidth bound; "
        "r5 fusion probe: perfect conv+BN fusion caps at ~0.20 MFU — "
        "the custom conv suite now exists (ops/pallas/conv.py, eval "
        "path; BENCH_MODEL=resnet50_infer + bench_ops conv_fused_sweep "
        "measure it) and the training-graph fusion is the follow-up")


def bench_resnet50_infer(on_tpu):
    """ResNet-50 EVAL forward through the fused Pallas conv suite
    (ISSUE 14): the same synthetic-data geometry as the training row,
    served once with `conv_backend='dense'` (today's conv->BN->ReLU
    composition — the r5 fusion-probe ceiling) and once with
    `conv_backend='pallas'` (every bottleneck conv+BN+ReLU one fused
    kernel, `PADDLE_CONV_BACKEND` seam). Outputs are tolerance-
    asserted before timing; the emitted metric is the FUSED images/s,
    with the dense number in the info line. Named-row only
    (`BENCH_MODEL=resnet50_infer`) so the default three-row output —
    and the committed BENCH_BASELINE metric set — is unchanged until
    a TPU run decides a baseline for it."""
    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.vision.models import resnet50

    if on_tpu:
        batch, size, classes = 256, 224, 1000
        steps = int(os.environ.get("BENCH_STEPS", "10"))
        fwd_flops = RESNET50_FWD_FLOPS
    else:
        batch, size, classes, steps = 4, 32, 10, 2
        fwd_flops = RESNET50_FWD_FLOPS * (32 / 224) ** 2

    imgs = np.random.uniform(-1, 1, (batch, 3, size, size)) \
        .astype(np.float32)
    x = paddle.to_tensor(imgs).astype("bfloat16")

    def serve(backend):
        paddle.seed(0)                  # identical weights per build
        model = resnet50(num_classes=classes, conv_backend=backend)
        model.to(dtype="bfloat16")
        model.eval()
        fwd = jax.jit(lambda a: model(Tensor._wrap(a))._array)
        t0 = time.time()
        out = fwd(x._array)
        np.asarray(out)                 # compile + first run
        compile_s = time.time() - t0
        t1 = time.time()
        for _ in range(steps):
            out = fwd(x._array)
        np.asarray(out)
        return out, (time.time() - t1) / steps, compile_s

    out_d, dt_d, _ = serve("dense")
    out_p, dt_p, compile_s = serve("pallas")
    ref = np.asarray(out_d, np.float32)
    got = np.asarray(out_p, np.float32)
    err = np.max(np.abs(got - ref)) / max(np.max(np.abs(ref)), 1e-6)
    from bench_ops import CONV_FUSED_REL_TOL

    assert err <= CONV_FUSED_REL_TOL, \
        f"fused eval diverged from dense ({err:.4f}, budget " \
        f"{CONV_FUSED_REL_TOL})"
    imgs_s = batch / dt_p
    return _emit(
        "resnet50_infer_images_per_sec_per_chip", "images/s", imgs_s,
        fwd_flops, on_tpu,
        f"batch={batch} size={size} compile={compile_s:.1f}s "
        f"fused={dt_p*1000:.1f}ms dense={dt_d*1000:.1f}ms "
        f"dense_images_s={batch/dt_d:.0f} rel_err={err:.4f}")


def bench_resnet50_train(on_tpu):
    """ResNet-50 TRAINING through the fused Pallas conv suite
    (ISSUE 16): the same TrainStep geometry as the tracked `resnet50`
    row, run once with `conv_backend='dense'` (the composition the
    0.152-MFU BENCH_r05 number and its ~0.20 perfect-fusion ceiling
    were measured on) and once with `conv_backend='pallas'` (all 52
    bottleneck/downsample convs through the fused custom_vjp — fused
    forward epilogue stats AND fused dInput/dWeight backward).
    First-step losses (identical weights, pre-update) are tolerance-
    asserted before timing; the emitted metric is the FUSED images/s
    with the dense number in the info line. Named-row only
    (`BENCH_MODEL=resnet50_train`) so the committed BENCH_BASELINE
    metric set is unchanged until a TPU `--save` refresh adopts it —
    this is the row that shows whether training moved past the
    fusion ceiling."""
    import paddle_tpu as paddle
    import paddle_tpu.jit as jit
    import paddle_tpu.nn.functional as F
    from paddle_tpu.vision.models import resnet50

    if on_tpu:
        batch = int(os.environ.get("BENCH_BATCH", "256"))
        size, classes = 224, 1000
        steps = int(os.environ.get("BENCH_STEPS", "10"))
        fwd_flops = RESNET50_FWD_FLOPS
    else:
        batch, size, classes, steps = 2, 32, 10, 2
        fwd_flops = RESNET50_FWD_FLOPS * (32 / 224) ** 2

    imgs_np = np.random.uniform(
        -1, 1, (batch, 3, size, size)).astype(np.float32)
    labels = paddle.to_tensor(
        np.random.randint(0, classes, (batch,), np.int64))

    def train(backend):
        paddle.seed(0)                  # identical weights per build
        model = resnet50(num_classes=classes, conv_backend=backend)
        model.to(dtype="bfloat16")
        opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                        parameters=model.parameters())
        step = jit.TrainStep(model, opt, F.cross_entropy)
        imgs = paddle.to_tensor(imgs_np).astype("bfloat16")
        t0 = time.time()
        first = float(step(imgs, labels))     # compile + step 1
        compile_s = time.time() - t0
        dt, _, loss = _run_repeat_steps(step, imgs, labels, steps)
        return first, float(loss), dt, compile_s

    first_d, _, dt_d, _ = train("dense")
    first_p, loss_p, dt_p, compile_s = train("pallas")
    from bench_ops import CONV_FUSED_REL_TOL

    err = abs(first_p - first_d) / max(abs(first_d), 1e-6)
    assert err <= CONV_FUSED_REL_TOL, \
        f"fused first-step loss diverged from dense ({err:.4f}, " \
        f"budget {CONV_FUSED_REL_TOL})"
    imgs_s = batch * steps / dt_p
    return _emit(
        "resnet50_train_fused_images_per_sec_per_chip", "images/s",
        imgs_s, 3 * fwd_flops, on_tpu,
        f"batch={batch} size={size} steps={steps} "
        f"compile={compile_s:.1f}s step={dt_p/steps*1000:.1f}ms "
        f"dense_step={dt_d/steps*1000:.1f}ms "
        f"dense_images_s={batch*steps/dt_d:.0f} loss={loss_p:.3f} "
        f"first_loss_rel_err={err:.4f}")


def main():
    import jax

    backend = jax.default_backend()
    on_tpu = backend in ("tpu", "axon")
    which = os.environ.get("BENCH_MODEL", "all")
    table = {"gpt": bench_gpt, "bert": bench_bert,
             "resnet50": bench_resnet50,
             "resnet50_infer": bench_resnet50_infer,
             "resnet50_train": bench_resnet50_train}
    if which == "all":
        # every BASELINE.md model row, one JSON line each — the GPT
        # flagship LAST so a last-line parser still reads the headline
        order = ["bert", "resnet50", "gpt"]
    elif which in table:
        order = [which]
    else:
        sys.exit(f"unknown BENCH_MODEL={which!r}; valid: "
                 f"{sorted(table)} or 'all'")
    any_failed = False
    for name in order:
        try:
            result, info = table[name](on_tpu)
        except Exception as e:  # one broken row must not hide the rest
            print(f"# {name} FAILED: {type(e).__name__}: {e}",
                  file=sys.stderr)
            # explicit failure row in-position: a last-line parser can
            # never mistake an earlier model's row for the flagship
            print(json.dumps({"metric": f"{name}_FAILED", "value": 0,
                              "unit": "error", "vs_baseline": 0.0}),
                  flush=True)
            any_failed = True
            if len(order) == 1:
                raise
            continue
        print(json.dumps(result), flush=True)
        print(f"# backend={backend} {info}", file=sys.stderr)
    if any_failed:
        sys.exit(1)


if __name__ == "__main__":
    main()
